"""Host-side continuous batching: admit, decode, evict — and survive.

The scheduler is deliberately plain Python over numpy: it owns the
request queue and the slot map, and the ONLY device work it triggers is
calls into the engine's AOT-compiled executables — nothing here can
compile, which is what lets a whole serving trace (including every
fault-tolerance path) run under ``assert_no_recompiles``.

Time has two faces here. *Arrivals* are virtual — ``Request.arrival``
is measured in decode ticks (one tick per scheduler step), so a trace
is deterministic: the same seed yields the same admission schedule, the
same bucket sequence, and therefore the same (zero) steady-state
compile count on every run, regardless of host speed. *Latencies* and
*deadlines* are wall-clock — TTFT runs from the moment a request became
eligible (arrival tick reached) to its first sampled token landing on
the host, so queueing-for-a-slot time counts, which is the honest
serving number.

Fault tolerance (:mod:`apex_tpu.serving.robust` holds the policy):

- **admission control** — a bounded pending queue sheds overflow
  (reject-newest or shed-oldest) with a ``serve/rejected`` event per
  shed request instead of growing without bound;
- **deadlines** — TTFT and total-latency budgets are checked each
  tick; an expired request is evicted with the ``deadline_exceeded``
  terminal status instead of occupying a slot forever;
- **quarantine** — the engine's per-slot finite flag evicts a poisoned
  sequence (status ``poisoned``, KV rows already reset in-graph) while
  healthy slots keep decoding; every slot non-finite at once escalates
  to :class:`~apex_tpu.resilience.NonFiniteError` (that is poisoned
  weights, not one poisoned request);
- **retry & partial failure** — transient decode failures retry inside
  the engine with capped backoff; a
  :class:`~apex_tpu.serving.robust.DecodeFailedError` past the budget
  fails ONLY the implicated slots' requests (status ``failed``);
- **graceful drain** — a :class:`~apex_tpu.resilience.preemption.
  PreemptionGuard` (or :meth:`Scheduler.drain`) stops admissions,
  finishes in-flight work up to the drain deadline, and emits a
  :class:`~apex_tpu.serving.robust.DrainReport`.

Telemetry (``serve/*``, docs/serving.md has the glossary): ``serve/ttft``
and ``serve/tok_latency`` histograms (milliseconds; p50/p99 from the
registry's reservoir), ``serve/slot_occupancy`` + ``serve/pending_depth``
gauges, ``serve/tokens_generated`` / ``serve/requests_completed`` /
``serve/rejected`` / ``serve/expired`` / ``serve/quarantined`` /
``serve/drained`` counters, a ``serve`` JSONL event per terminal
request, a periodic + end-of-run ``health`` snapshot event, and a
``kv_cache`` slot census event at end of run.
"""

import dataclasses
import time
import warnings
from typing import List, Optional

import numpy as np

from apex_tpu.serving import robust as robust_mod
from apex_tpu.telemetry.registry import get_registry
from apex_tpu.telemetry.trace import emit_span, new_span_id, new_trace_id


@dataclasses.dataclass
class Request:
    """One serving request. ``arrival`` is in decode ticks (virtual
    time — see module docstring); ``max_new_tokens`` bounds generation
    (eos, when the engine's config defines one, may end it earlier).
    ``ttft_deadline_s`` / ``total_deadline_s`` override the scheduler's
    :class:`~apex_tpu.serving.robust.RobustConfig` defaults for this
    request (None = inherit).

    ``tier`` is the SLO class (``"interactive"`` | ``"batch"``; None =
    the fleet's default tier). The scheduler itself is tier-blind —
    :class:`~apex_tpu.serving.fleet.ServeFleet` resolves a tier into
    the per-request deadline fields above at admission and keeps the
    per-tier latency accounting.

    ``trace_id`` is the request's causal identity (None = allocate at
    submit when telemetry is on). It survives ``dataclasses.replace``,
    so a migration continuation keeps the donor's id and the donor +
    survivor span trees stitch into ONE trace."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival: float = 0.0
    ttft_deadline_s: Optional[float] = None
    total_deadline_s: Optional[float] = None
    tier: Optional[str] = None
    trace_id: Optional[str] = None


@dataclasses.dataclass
class CompletedRequest:
    rid: int
    tokens: np.ndarray          # generated tokens (prompt excluded)
    ttft_s: float               # eligible -> first token; NaN if never served
    mean_tok_latency_s: float   # decode steps only (excludes TTFT)
    finish_reason: str          # robust.OK_STATUSES | robust.FAILURE_STATUSES


def synthetic_trace(n_requests=16, *, seed=0, mean_interarrival=0.5,
                    prompt_lens=(4, 8, 12, 24), max_new=(8, 16, 24),
                    vocab_size=256, shared_prefix_len=0,
                    shared_frac=0.8):
    """Deterministic many-user trace: Poisson arrivals (exponential
    inter-arrival gaps in decode ticks) with mixed prompt/output
    lengths — the bench.py ``serve_decode`` workload. Same seed, same
    trace, byte for byte.

    ``shared_prefix_len > 0`` makes the trace prefix-heavy (the
    realistic millions-of-users shape): one ``shared_prefix_len``-token
    system prompt is drawn once, and each request opens with it with
    probability ``shared_frac`` (its ``prompt_lens`` draw then sizes
    the UNIQUE tail). The default (0) leaves the legacy byte stream
    untouched — no extra RNG draws happen."""
    rs = np.random.RandomState(seed)
    gaps = rs.exponential(mean_interarrival, size=n_requests)
    arrivals = np.cumsum(gaps) - gaps[0]        # first request at t=0
    shared = (rs.randint(0, vocab_size,
                         size=int(shared_prefix_len)).astype(np.int32)
              if shared_prefix_len else None)
    out = []
    for i in range(n_requests):
        plen = int(rs.choice(prompt_lens))
        prompt = rs.randint(0, vocab_size, size=plen).astype(np.int32)
        if shared is not None and rs.random_sample() < shared_frac:
            prompt = np.concatenate([shared, prompt])
        out.append(Request(
            rid=i,
            prompt=prompt,
            max_new_tokens=int(rs.choice(max_new)),
            arrival=float(arrivals[i])))
    return out


class _Active:
    __slots__ = ("req", "tokens", "last", "latencies", "ttft_s")

    def __init__(self, req, first_token, ttft_s):
        self.req = req
        self.tokens = [int(first_token)]
        self.last = int(first_token)
        self.latencies = []
        self.ttft_s = float(ttft_s)


class Scheduler:
    """Continuous batching over one :class:`ServeEngine`.

    One :meth:`step` = expire deadline-blown requests, admit every
    eligible request into free slots (grouped prefills; skipped while
    draining), then one decode pass over the active set (padded to the
    engine's batch bucket with distinct free slots), then evict
    finished/poisoned sequences. :meth:`run` drives a request list to
    completion; fast-forwards virtual time across idle gaps so a
    sparse trace never spins.
    """

    def __init__(self, engine, *, registry=None,
                 clock=time.perf_counter, robust=None, guard=None,
                 trace_label=None):
        self.engine = engine
        self._registry = registry
        self._clock = clock
        # process-row key on every span this scheduler emits; the fleet
        # sets "replica<N>" so trace_export gives each replica a row
        self.trace_label = trace_label or "serve"
        # rid -> {"trace_id", "root" (root span id), "submit_perf",
        # "eligible_perf", "admit_perf"} — populated only while the
        # registry is enabled; span timestamps use time.perf_counter()
        # directly (NOT self._clock, which tests may virtualize) so
        # they live on the registry's epoch clock
        self._tr = {}
        self.robust = robust or robust_mod.RobustConfig()
        self.guard = guard
        self.num_slots = engine.config.num_slots
        self.free = list(range(self.num_slots))
        self.pending: List[Request] = []
        self.active = {}                      # slot -> _Active
        self.completed: List[CompletedRequest] = []
        self.rejected: List[robust_mod.RejectedRequest] = []
        self.health = robust_mod.ServeHealth()
        self.tick = 0.0
        self.decode_steps = 0
        self.prefill_calls = 0
        self.tokens_generated = 0
        self.draining = False
        self.drain_report: Optional[robust_mod.DrainReport] = None
        self._drain_reason = None
        self._drain_start_wall = None
        self._drain_start_tick = None
        self._drain_completed_before = 0
        self._known_rids = set()
        self._eligible_wall = {}
        self._ttft_ms = []
        self._tok_latency_ms = []
        # prefix-cache hit accounting (engine-fed): TTFT split by
        # whether the request's admission prefill hit the store
        self._ttft_hit_ms = []
        self._ttft_miss_ms = []
        # speculative-decode acceptance accounting
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._t_start = None
        self._t_end = None
        self._retries_before = engine.decode_retries_total

    def _reg(self):
        return self._registry or get_registry()

    # -- submission & admission control ------------------------------------

    def _reject(self, request, reason, detail=""):
        """Record one shed/bounced request: host list + counter +
        JSONL event. Returns False (the ``submit`` contract)."""
        rec = robust_mod.RejectedRequest(
            rid=request.rid, reason=reason, tick=self.tick,
            prompt_len=len(request.prompt), detail=detail)
        self.rejected.append(rec)
        self.health.rejected += 1
        reg = self._reg()
        reg.counter("serve/rejected").inc()
        reg.event("serve", "rejected", rid=request.rid, reason=reason,
                  tick=self.tick, prompt_len=len(request.prompt),
                  detail=detail)
        return False

    def submit(self, request: Request):
        """Queue a request, or shed it. Returns True when queued;
        False when rejected — with the reason recorded in
        :attr:`rejected`, the ``serve/rejected`` counter, and a
        ``serve`` JSONL event (never an exception: admission control
        is traffic policy, not a caller bug)."""
        rc = self.robust
        eng = self.engine
        plen = len(request.prompt)
        self.health.submitted += 1
        if request.rid in self._known_rids:
            return self._reject(
                request, "duplicate_rid",
                f"rid {request.rid} is already tracked by this scheduler")
        if self.draining:
            return self._reject(
                request, "draining",
                "scheduler is draining; admissions are closed")
        if plen > eng.config.prefill_buckets[-1]:
            return self._reject(
                request, "prompt_too_long",
                f"prompt ({plen}) exceeds the largest prefill bucket "
                f"({eng.config.prefill_buckets[-1]})")
        headroom = getattr(eng, "decode_headroom", 0)
        if plen + request.max_new_tokens + headroom > eng.max_len:
            return self._reject(
                request, "budget_too_long",
                f"prompt ({plen}) + max_new_tokens "
                f"({request.max_new_tokens})"
                + (f" + speculative window ({headroom})" if headroom
                   else "")
                + f" exceeds max_position_embeddings ({eng.max_len})")
        if rc.max_pending is not None and len(self.pending) >= rc.max_pending:
            if rc.admission_policy == "reject_newest":
                return self._reject(
                    request, "queue_full",
                    f"pending queue at max_pending ({rc.max_pending})")
            # shed_oldest: the newcomer is the one a user is still
            # waiting at; the oldest queued request has already blown
            # the most patience — shed it to make room
            oldest = self.pending.pop(0)
            self._known_rids.discard(oldest.rid)
            self._tr.pop(oldest.rid, None)
            self._reject(oldest, "shed",
                         f"shed for rid {request.rid} "
                         f"(max_pending {rc.max_pending})")
        self._known_rids.add(request.rid)
        reg = self._reg()
        if reg.enabled:
            if request.trace_id is None:
                request.trace_id = new_trace_id()
            self._tr[request.rid] = {
                "trace_id": request.trace_id,
                "root": new_span_id(),
                "submit_perf": time.perf_counter(),
            }
        self.pending.append(request)
        self.pending.sort(key=lambda r: (r.arrival, r.rid))
        return True

    # -- terminal bookkeeping ----------------------------------------------

    _TERMINAL_COUNTERS = {
        "deadline_exceeded": "serve/expired",
        "poisoned": "serve/quarantined",
        "failed": "serve/failed",
        "drained": "serve/drained",
        "max_steps": "serve/cancelled",
    }

    def _terminal(self, req, reason, *, tokens=(), ttft_s=float("nan"),
                  latencies=(), **event_fields):
        """Land one request in a terminal state: completed-list record,
        per-status counter, ``serve`` JSONL event. Every failure path
        funnels through here so no request ever vanishes silently.
        When the request is traced, the phase spans close here too:
        ``serve/decode`` (admission -> terminal), an instant
        ``serve/evict``, and the root ``serve/request`` span."""
        rec = CompletedRequest(
            rid=req.rid,
            tokens=np.asarray(list(tokens), np.int32),
            ttft_s=float(ttft_s),
            mean_tok_latency_s=(float(np.mean(list(latencies)))
                                if latencies else 0.0),
            finish_reason=reason)
        self.completed.append(rec)
        reg = self._reg()
        counter = self._TERMINAL_COUNTERS.get(reason)
        if counter:
            reg.counter(counter).inc()
        reg.counter("serve/requests_completed").inc()
        reg.counter("serve/tokens_generated").inc(len(rec.tokens))
        tr = self._tr.pop(req.rid, None)
        trace_fields = {}
        if tr is not None:
            trace_fields["trace_id"] = tr["trace_id"]
            self._close_request_trace(tr, req, reason, reg,
                                      tokens=len(rec.tokens))
        reg.event("serve", "request_done", rid=req.rid,
                  tokens=len(rec.tokens), prompt_len=len(req.prompt),
                  ttft_ms=(round(rec.ttft_s * 1e3, 3)
                           if np.isfinite(rec.ttft_s) else None),
                  mean_tok_latency_ms=round(
                      rec.mean_tok_latency_s * 1e3, 3),
                  finish_reason=reason, **trace_fields, **event_fields)
        return rec

    def _close_request_trace(self, tr, req, reason, reg, *, tokens):
        """Emit the end-of-life spans for one traced request (see
        :meth:`_terminal`). A request that never reached admission has
        no ``serve/decode`` span — its whole life was the queue."""
        now_p = time.perf_counter()
        admit = tr.get("admit_perf")
        if admit is not None:
            emit_span("serve/decode", admit, now_p, registry=reg,
                      trace_id=tr["trace_id"], parent_id=tr["root"],
                      rid=req.rid, replica=self.trace_label,
                      tokens=tokens)
        emit_span("serve/evict", now_p, now_p, registry=reg,
                  trace_id=tr["trace_id"], parent_id=tr["root"],
                  rid=req.rid, reason=reason, replica=self.trace_label)
        start = tr.get("eligible_perf", tr["submit_perf"])
        emit_span("serve/request", start, now_p, registry=reg,
                  trace_id=tr["trace_id"], span_id=tr["root"],
                  rid=req.rid, tier=req.tier, finish_reason=reason,
                  replica=self.trace_label, tokens=tokens)

    # -- the phases --------------------------------------------------------

    def _ttft_deadline(self, req):
        return (req.ttft_deadline_s if req.ttft_deadline_s is not None
                else self.robust.ttft_deadline_s)

    def _total_deadline(self, req):
        return (req.total_deadline_s if req.total_deadline_s is not None
                else self.robust.total_deadline_s)

    def _expire_deadlines(self):
        """Evict every request past its budget — queued requests past
        their TTFT deadline, active ones past their total-latency
        deadline — with the ``deadline_exceeded`` terminal status."""
        now = self._clock()
        now_p = time.perf_counter() if self._tr else None
        # eligibility is stamped here (not only at admission) so a
        # request stuck in the queue accrues wait time toward its
        # TTFT deadline from the moment it became eligible
        for r in self.pending:
            if r.arrival <= self.tick:
                self._eligible_wall.setdefault(r.rid, now)
                if now_p is not None and r.rid in self._tr:
                    self._tr[r.rid].setdefault("eligible_perf", now_p)
        for r in list(self.pending):
            limit = self._ttft_deadline(r)
            t0 = self._eligible_wall.get(r.rid)
            if limit is None or t0 is None or now - t0 <= limit:
                continue
            self.pending.remove(r)
            self.health.expired += 1
            self._terminal(r, "deadline_exceeded", where="pending",
                           waited_ms=round((now - t0) * 1e3, 3))
        for slot in sorted(self.active):
            st = self.active[slot]
            limit = self._total_deadline(st.req)
            t0 = self._eligible_wall.get(st.req.rid)
            if limit is None or t0 is None or now - t0 <= limit:
                continue
            del self.active[slot]
            self._release(slot)
            self.health.expired += 1
            self._terminal(st.req, "deadline_exceeded", where="active",
                           tokens=st.tokens, ttft_s=st.ttft_s,
                           latencies=st.latencies,
                           waited_ms=round((now - t0) * 1e3, 3))

    def _admit(self):
        now = self._clock()
        now_p = time.perf_counter() if self._tr else None
        eligible = [r for r in self.pending if r.arrival <= self.tick]
        for r in eligible:
            self._eligible_wall.setdefault(r.rid, now)
            if now_p is not None and r.rid in self._tr:
                self._tr[r.rid].setdefault("eligible_perf", now_p)
        buckets = self.engine.config.batch_buckets
        while eligible and self.free:
            # the prefill call occupies a whole batch bucket (real +
            # pad slots, all distinct), so the group must shrink to the
            # largest bucket that fits entirely inside the free pool
            fits = [b for b in buckets if b <= len(self.free)]
            if not fits:
                break
            group = eligible[:min(len(self.free), fits[-1])]
            eligible = eligible[len(group):]
            for r in group:
                self.pending.remove(r)
            slots = [self.free.pop(0) for _ in group]
            p0 = time.perf_counter() if self._tr else None
            first = self.engine.prefill(
                slots, [r.prompt for r in group],
                pad_slot_ids=self.free)
            t1 = self._clock()
            p1 = time.perf_counter() if self._tr else None
            self.prefill_calls += 1
            cuts = list(getattr(self.engine, "last_prefill_hits",
                                ()) or [0] * len(group))
            reg = self._reg()
            for slot, r, tok, cut in zip(slots, group, first, cuts):
                ttft = t1 - self._eligible_wall[r.rid]
                self._ttft_ms.append(ttft * 1e3)
                (self._ttft_hit_ms if cut
                 else self._ttft_miss_ms).append(ttft * 1e3)
                reg.histogram("serve/ttft").observe(ttft * 1e3)
                if cut:
                    reg.histogram("serve/ttft_prefix_hit").observe(
                        ttft * 1e3)
                reg.counter("serve/requests_admitted").inc()
                tr = self._tr.get(r.rid)
                if tr is not None:
                    emit_span("serve/queued",
                              tr.get("eligible_perf",
                                     tr["submit_perf"]), p0,
                              registry=reg, trace_id=tr["trace_id"],
                              parent_id=tr["root"], rid=r.rid,
                              replica=self.trace_label)
                    emit_span("serve/prefill", p0, p1, registry=reg,
                              trace_id=tr["trace_id"],
                              parent_id=tr["root"], rid=r.rid,
                              slot=slot, prefix_cut=int(cut),
                              replica=self.trace_label)
                    tr["admit_perf"] = p1
                self.tokens_generated += 1
                st = _Active(r, tok, ttft)
                if self._finished(st):
                    self._evict(slot, st)
                else:
                    self.active[slot] = st

    def _finished(self, st):
        eos = self.engine.config.eos_token_id
        if eos is not None and st.last == eos:
            return True
        return len(st.tokens) >= st.req.max_new_tokens

    def _decode_once(self):
        if not self.active:
            return
        rc = self.robust
        spec = bool(getattr(self.engine, "spec_enabled", False))
        max_bucket = self.engine.config.batch_buckets[-1]
        slots = sorted(self.active)
        trace_on = self._reg().enabled
        for i in range(0, len(slots), max_bucket):
            chunk = slots[i:i + max_bucket]
            toks = [self.active[s].last for s in chunk]
            t0 = self._clock()
            p0 = time.perf_counter() if trace_on else None
            try:
                out = self.engine.decode(
                    chunk, toks, pad_slot_ids=self.free,
                    retries=rc.decode_retries,
                    backoff_s=rc.retry_backoff_s,
                    backoff_cap_s=rc.retry_backoff_cap_s)
                if spec:
                    emitted, counts, finite = out
                else:
                    nxt, finite = out
            except robust_mod.DecodeFailedError as e:
                # persistent dispatch failure: fail ONLY this chunk's
                # requests; other chunks (and future traffic) continue
                self.health.decode_failures += 1
                reg = self._reg()
                reg.counter("serve/decode_failures").inc()
                reg.event("serve", "decode_failed", slots=list(chunk),
                          attempts=e.attempts,
                          error=type(e.last_error).__name__)
                for s in chunk:
                    st = self.active.pop(s)
                    self._release(s)
                    self.health.failed += 1
                    self._terminal(st.req, "failed", tokens=st.tokens,
                                   ttft_s=st.ttft_s,
                                   latencies=st.latencies,
                                   attempts=e.attempts)
                continue
            dt = self._clock() - t0
            self.decode_steps += 1
            reg = self._reg()
            reg.counter("serve/decode_steps").inc()
            if p0 is not None and reg.enabled:
                # engine-row span: one per dispatch, covering the whole
                # chunk (spec engines verify drafts inside this call)
                emit_span("serve/decode_chunk", p0, registry=reg,
                          slots=len(chunk), spec=spec,
                          replica=self.trace_label, tick=self.tick)
            if rc.quarantine and len(chunk) >= 2 and not finite.any():
                # every slot non-finite at once: that is poisoned
                # weights/activations, not one poisoned request — the
                # whole-batch guard escalates after the quarantine
                # bookkeeping lands (a 1-slot batch can't distinguish
                # the two, so it stays a per-slot quarantine)
                from apex_tpu.resilience import NonFiniteError

                self.health.all_slots_nonfinite += 1
                for s in chunk:
                    st = self.active.pop(s)
                    self._quarantine(s, st)
                reg.event("serve", "all_slots_nonfinite",
                          slots=list(chunk), tick=self.tick)
                raise NonFiniteError(
                    f"every slot in the decode batch ({list(chunk)}) "
                    f"produced non-finite logits at tick {self.tick} — "
                    f"this is model-level poison (weights/activations), "
                    f"not a per-request fault; restore from the last "
                    f"verified checkpoint")
            if spec:
                # acceptance bookkeeping: proposed = k per real slot,
                # accepted = counts - 1 (the +1 is the target's own
                # correction/bonus token, not a draft acceptance)
                k = int(self.engine.config.num_draft_tokens)
                proposed = k * len(chunk)
                accepted = int(sum(int(c) - 1
                                   for c, ok in zip(counts, finite)
                                   if ok))
                self.spec_proposed += proposed
                self.spec_accepted += accepted
                reg.counter("serve/spec_proposed").inc(proposed)
                if accepted:
                    reg.counter("serve/spec_accepted").inc(accepted)
                blocks = [list(emitted[j][:int(counts[j])])
                          for j in range(len(chunk))]
            else:
                blocks = [[tok] for tok in nxt]
            for s, block, ok in zip(chunk, blocks, finite):
                st = self.active[s]
                if rc.quarantine and not ok:
                    del self.active[s]
                    self._quarantine(s, st)
                    continue
                # one dispatch may emit several verified tokens (the
                # speculative round's accepted prefix + bonus); the
                # per-token latency is the dispatch amortized over
                # them, and eos / max_new truncate the block exactly
                # where a one-token engine would have stopped
                per_tok = dt / max(len(block), 1)
                done = False
                for tok in block:
                    st.tokens.append(int(tok))
                    st.last = int(tok)
                    st.latencies.append(per_tok)
                    self._tok_latency_ms.append(per_tok * 1e3)
                    reg.histogram("serve/tok_latency").observe(
                        per_tok * 1e3)
                    self.tokens_generated += 1
                    if self._finished(st):
                        done = True
                        break
                if done:
                    del self.active[s]
                    self._evict(s, st)

    def _release(self, slot):
        self.free.append(slot)
        self.free.sort()

    def _quarantine(self, slot, st):
        """Evict one poisoned sequence: its KV rows were already reset
        in-graph by the decode step; here the slot returns to the free
        pool and the request lands with status ``poisoned``."""
        self._release(slot)
        self.health.quarantined += 1
        self._terminal(st.req, "poisoned", tokens=st.tokens,
                       ttft_s=st.ttft_s, latencies=st.latencies,
                       slot=slot, tick=self.tick)

    def _evict(self, slot, st):
        if slot in self.active:
            del self.active[slot]
        self._release(slot)
        eos = self.engine.config.eos_token_id
        reason = "eos" if (eos is not None and st.last == eos) \
            else "length"
        self._terminal(st.req, reason, tokens=st.tokens,
                       ttft_s=st.ttft_s, latencies=st.latencies)

    # -- migration seam (serving.fleet) ------------------------------------

    def extract_unfinished(self, reason="migrated", which="all"):
        """Remove in-flight and/or queued requests WITHOUT landing a
        terminal status — the fleet's migration seam: a quarantined or
        lost replica's unfinished work is re-admitted to survivors, so
        the requests must leave this scheduler accounted-for but not
        finished. Returns one record per request — ``{"request",
        "tokens" (emitted so far), "ttft_s", "latencies", "where",
        "slot"}`` — everything the fleet needs to build the re-prefill
        continuation (prompt + emitted tokens; greedy decode resumes
        token-identically). ``"slot"`` is the store slot the request
        occupied (None for pending records): slot release only returns
        the id to the free pool — the KV rows stay resident — so the
        fleet can still ``extract_kv_state`` the donor's cache AFTER
        this sweep, as long as nothing prefills in between. Each extraction ticks ``serve/extracted``
        and lands a ``serve``/``extracted`` JSONL event; ``which``
        scopes the sweep (``"all"`` | ``"active"`` | ``"pending"`` —
        a draining replica migrates its queue immediately but lets
        active slots finish inside the drain window)."""
        if which not in ("all", "active", "pending"):
            raise ValueError(f"which ({which!r}) not in "
                             f"('all', 'active', 'pending')")
        out = []
        if which in ("all", "active"):
            for slot in sorted(self.active):
                st = self.active.pop(slot)
                self._release(slot)
                out.append({"request": st.req,
                            "tokens": list(st.tokens),
                            "ttft_s": st.ttft_s,
                            "latencies": list(st.latencies),
                            "where": "active",
                            "slot": slot})
        if which in ("all", "pending"):
            for r in list(self.pending):
                self.pending.remove(r)
                out.append({"request": r, "tokens": [],
                            "ttft_s": float("nan"), "latencies": [],
                            "where": "pending", "slot": None})
        reg = self._reg()
        for rec in out:
            rid = rec["request"].rid
            self._known_rids.discard(rid)
            self._eligible_wall.pop(rid, None)
            reg.counter("serve/extracted").inc()
            tr = self._tr.pop(rid, None)
            trace_fields = {}
            if tr is not None:
                # close the donor side of the trace: the survivor's
                # scheduler opens a fresh serve/request root under the
                # SAME trace_id when the continuation is re-submitted
                trace_fields["trace_id"] = tr["trace_id"]
                self._close_request_trace(tr, rec["request"], reason,
                                          reg,
                                          tokens=len(rec["tokens"]))
            reg.event("serve", "extracted", rid=rid, reason=reason,
                      where=rec["where"], tokens=len(rec["tokens"]),
                      tick=self.tick, **trace_fields)
        return out

    # -- drain -------------------------------------------------------------

    def drain(self, reason="requested"):
        """Stop admissions now; :meth:`run` finishes in-flight work up
        to ``robust.drain_deadline_s`` and emits the drain report."""
        if not self.draining:
            self._begin_drain(reason)

    def _begin_drain(self, reason):
        self.draining = True
        self._drain_reason = reason
        self._drain_start_wall = self._clock()
        self._drain_start_tick = self.tick
        self._drain_completed_before = len(self.completed)
        reg = self._reg()
        reg.event("serve", "drain_start", reason=reason, tick=self.tick,
                  active=len(self.active), pending=len(self.pending))

    def _drain_deadline_passed(self):
        return (self._clock() - self._drain_start_wall
                > self.robust.drain_deadline_s)

    def _finish_drain(self):
        """Cancel whatever the drain deadline stranded and emit the
        report: every cancelled request gets the ``drained`` terminal
        status (non-silent), the counter ticks per request, and the
        ``drain_report`` event summarizes what the grace window
        bought."""
        cancelled_active = 0
        for slot in sorted(self.active):
            st = self.active.pop(slot)
            self._release(slot)
            self.health.drained += 1
            cancelled_active += 1
            self._terminal(st.req, "drained", tokens=st.tokens,
                           ttft_s=st.ttft_s, latencies=st.latencies)
        cancelled_pending = 0
        for r in list(self.pending):
            self.pending.remove(r)
            self.health.drained += 1
            cancelled_pending += 1
            self._terminal(r, "drained", where="pending")
        drain_s = self._clock() - self._drain_start_wall
        completed_in_drain = (len(self.completed)
                              - self._drain_completed_before
                              - cancelled_active - cancelled_pending)
        self.drain_report = robust_mod.DrainReport(
            reason=self._drain_reason,
            started_tick=self._drain_start_tick,
            drain_s=drain_s,
            completed_in_drain=completed_in_drain,
            cancelled_active=cancelled_active,
            cancelled_pending=cancelled_pending,
            deadline_hit=cancelled_active > 0)
        reg = self._reg()
        reg.event("serve", "drain_report",
                  **self.drain_report.as_event_fields())

    # -- driving -----------------------------------------------------------

    def step(self):
        """One scheduler iteration: check for preemption, expire
        deadline-blown requests, admit (unless draining), decode once,
        advance the virtual clock one tick."""
        if self._t_start is None:
            self._t_start = self._clock()
        if not self.draining and self.guard is not None \
                and self.guard.preempted:
            self._begin_drain("preempted")
        self._expire_deadlines()
        if not self.draining:
            self._admit()
        self._decode_once()
        reg = self._reg()
        reg.gauge("serve/slot_occupancy").set(
            len(self.active) / self.num_slots)
        every = self.robust.health_every
        if every and int(self.tick) % every == 0:
            self._health_event()
        self.tick += 1.0

    def run(self, requests=None, *, max_steps=100_000):
        """Drive ``requests`` (plus anything already submitted) to a
        terminal state; returns the completed list in finish order.
        Every request ends with an explicit ``finish_reason`` —
        deadline-blown, poisoned, failed, drained at preemption, or
        cancelled at ``max_steps`` exhaustion — never an unexplained
        disappearance."""
        for r in requests or ():
            self.submit(r)
        steps = 0
        while self.pending or self.active:
            if self.draining and (not self.active
                                  or self._drain_deadline_passed()):
                break
            if not self.active and self.pending and \
                    min(r.arrival for r in self.pending) > self.tick:
                # idle gap: fast-forward virtual time to the next
                # arrival instead of spinning empty decode ticks
                self.tick = min(r.arrival for r in self.pending)
            self.step()
            steps += 1
            if steps > max_steps:
                self._exhaust_max_steps(max_steps)
                break
        if self.draining:
            self._finish_drain()
        self._t_end = self._clock()
        self._census_event()
        self._spec_prefix_events()
        self._health_event()
        return self.completed

    def _exhaust_max_steps(self, max_steps):
        """``max_steps`` ran out with work left: cancel it loudly —
        terminal status ``max_steps`` per request plus a warning —
        instead of raising away the scheduler's accounting."""
        stranded_active = len(self.active)
        stranded_pending = len(self.pending)
        for slot in sorted(self.active):
            st = self.active.pop(slot)
            self._release(slot)
            self.health.max_steps += 1
            self._terminal(st.req, "max_steps", tokens=st.tokens,
                           ttft_s=st.ttft_s, latencies=st.latencies)
        for r in list(self.pending):
            self.pending.remove(r)
            self.health.max_steps += 1
            self._terminal(r, "max_steps", where="pending")
        self._reg().event("serve", "max_steps_exhausted",
                          max_steps=max_steps, tick=self.tick,
                          cancelled_active=stranded_active,
                          cancelled_pending=stranded_pending)
        warnings.warn(
            f"scheduler exhausted max_steps ({max_steps}) with "
            f"{stranded_pending} pending / {stranded_active} active "
            f"request(s) — all cancelled with terminal status "
            f"'max_steps' (a request was not converging)", stacklevel=3)

    # -- accounting --------------------------------------------------------

    def _census_event(self):
        eng = self.engine
        reg = self._reg()
        reg.gauge("serve/kv_cache_bytes").set(eng.kv_cache_bytes())
        reg.event("serve", "kv_cache",
                  slots_total=self.num_slots,
                  slots_used=len(self.active),
                  slots_free=len(self.free),
                  bytes_per_slot=eng.spec.bytes_per_slot(),
                  cache_dtype=eng.spec.cache_dtype_name(),
                  kv_cache_bytes=eng.kv_cache_bytes())

    def _spec_prefix_events(self):
        """End-of-run rollups for the two serving multipliers (only
        when the engine runs them): acceptance accounting for the
        speculative ladder, hit/miss accounting for the prefix store
        (tools/telemetry_report.py renders both)."""
        reg = self._reg()
        if getattr(self.engine, "spec_enabled", False):
            rate = (self.spec_accepted / self.spec_proposed
                    if self.spec_proposed else 0.0)
            reg.gauge("serve/spec_acceptance_rate").set(rate)
            reg.event("serve", "spec_report",
                      proposed=self.spec_proposed,
                      accepted=self.spec_accepted,
                      acceptance_rate=round(rate, 4),
                      num_draft_tokens=int(
                          self.engine.config.num_draft_tokens),
                      decode_steps=self.decode_steps,
                      tokens_generated=self.tokens_generated)
        store = getattr(self.engine, "prefix_store", None)
        if store is not None:
            s = store.stats()
            reg.gauge("serve/prefix_hit_rate").set(s["hit_rate"])
            reg.event("serve", "prefix_report", **s)

    def _health_event(self):
        self.health.decode_retries = (self.engine.decode_retries_total
                                      - self._retries_before)
        self.health.emit(
            self._reg(), tick=self.tick, pending=len(self.pending),
            active=len(self.active), free=len(self.free),
            completed_ok=sum(
                1 for c in self.completed
                if c.finish_reason in robust_mod.OK_STATUSES),
            draining=self.draining)

    @staticmethod
    def _pct(samples, q):
        return float(np.percentile(samples, q)) if samples else None

    def stats(self):
        """Host-side summary of the run (independent of registry
        enablement — the bench's emission source). Goodput counts only
        requests that finished ``length``/``eos``; every failure mode
        has its own count next to the shed rate."""
        wall = ((self._t_end or self._clock())
                - (self._t_start or self._clock()))
        self.health.decode_retries = (self.engine.decode_retries_total
                                      - self._retries_before)
        by_reason = {}
        goodput_tokens = 0
        for c in self.completed:
            by_reason[c.finish_reason] = \
                by_reason.get(c.finish_reason, 0) + 1
            if c.finish_reason in robust_mod.OK_STATUSES:
                goodput_tokens += len(c.tokens)
        h = self.health
        extra = {}
        if getattr(self.engine, "spec_enabled", False):
            tps = (self.tokens_generated / wall) if wall > 0 else None
            extra.update({
                "spec_proposed": self.spec_proposed,
                "spec_accepted": self.spec_accepted,
                "acceptance_rate": round(
                    self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed else 0.0,
                # every emitted token is target-verified, so the
                # accepted-tokens rate IS the engine's tokens/sec —
                # named explicitly for the serve_spec bench contract
                "accepted_tokens_per_sec": tps,
            })
        store = getattr(self.engine, "prefix_store", None)
        if store is not None:
            ps = store.stats()
            extra.update({
                "prefix_lookups": ps["lookups"],
                "prefix_hits": ps["hits"],
                "prefix_hit_rate": round(ps["hit_rate"], 4),
                "prefix_hit_tokens": ps["hit_tokens"],
                "prefix_store_bytes": ps["bytes"],
                "prefix_store_entries": ps["entries"],
                "ttft_p50_prefix_hit_ms": self._pct(
                    self._ttft_hit_ms, 50),
                "ttft_p50_prefix_miss_ms": self._pct(
                    self._ttft_miss_ms, 50),
            })
        return {
            **extra,
            "requests_completed": len(self.completed),
            "requests_ok": sum(by_reason.get(r, 0)
                               for r in robust_mod.OK_STATUSES),
            "requests_by_reason": by_reason,
            "requests_rejected": h.rejected,
            "requests_expired": h.expired,
            "requests_quarantined": h.quarantined,
            "requests_failed": h.failed,
            "requests_drained": h.drained,
            "shed_rate": round(h.shed_rate(), 4),
            "decode_retries": h.decode_retries,
            "tokens_generated": self.tokens_generated,
            "goodput_tokens": goodput_tokens,
            "wall_s": wall,
            "tokens_per_sec": (self.tokens_generated / wall)
            if wall > 0 else None,
            "goodput_tokens_per_sec": (goodput_tokens / wall)
            if wall > 0 else None,
            "decode_steps": self.decode_steps,
            "prefill_calls": self.prefill_calls,
            "ttft_p50_ms": self._pct(self._ttft_ms, 50),
            "ttft_p99_ms": self._pct(self._ttft_ms, 99),
            "tok_latency_p50_ms": self._pct(self._tok_latency_ms, 50),
            "tok_latency_p99_ms": self._pct(self._tok_latency_ms, 99),
            "slot_occupancy_last": len(self.active) / self.num_slots,
        }
