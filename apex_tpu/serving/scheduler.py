"""Host-side continuous batching: admit, decode, evict — and account.

The scheduler is deliberately plain Python over numpy: it owns the
request queue and the slot map, and the ONLY device work it triggers is
calls into the engine's AOT-compiled executables — nothing here can
compile, which is what lets a whole serving trace run under
``assert_no_recompiles``.

Time has two faces here. *Arrivals* are virtual — ``Request.arrival``
is measured in decode ticks (one tick per scheduler step), so a trace
is deterministic: the same seed yields the same admission schedule, the
same bucket sequence, and therefore the same (zero) steady-state
compile count on every run, regardless of host speed. *Latencies* are
wall-clock — TTFT runs from the moment a request became eligible
(arrival tick reached) to its first sampled token landing on the host,
so queueing-for-a-slot time counts, which is the honest serving number.

Telemetry (``serve/*``, docs/serving.md has the glossary): ``serve/ttft``
and ``serve/tok_latency`` histograms (milliseconds; p50/p99 from the
registry's reservoir), ``serve/slot_occupancy`` gauge,
``serve/tokens_generated`` / ``serve/requests_completed`` counters, a
``serve`` JSONL event per completed request, and a ``kv_cache`` slot
census event at end of run (slots used/free, bytes per slot, cache
dtype — tools/memory_report.py renders it).
"""

import dataclasses
import time
from typing import List, Optional

import numpy as np

from apex_tpu.telemetry.registry import get_registry


@dataclasses.dataclass
class Request:
    """One serving request. ``arrival`` is in decode ticks (virtual
    time — see module docstring); ``max_new_tokens`` bounds generation
    (eos, when the engine's config defines one, may end it earlier)."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    arrival: float = 0.0


@dataclasses.dataclass
class CompletedRequest:
    rid: int
    tokens: np.ndarray          # generated tokens (prompt excluded)
    ttft_s: float               # eligible -> first token, wall clock
    mean_tok_latency_s: float   # decode steps only (excludes TTFT)
    finish_reason: str          # "length" | "eos"


def synthetic_trace(n_requests=16, *, seed=0, mean_interarrival=0.5,
                    prompt_lens=(4, 8, 12, 24), max_new=(8, 16, 24),
                    vocab_size=256):
    """Deterministic many-user trace: Poisson arrivals (exponential
    inter-arrival gaps in decode ticks) with mixed prompt/output
    lengths — the bench.py ``serve_decode`` workload. Same seed, same
    trace, byte for byte."""
    rs = np.random.RandomState(seed)
    gaps = rs.exponential(mean_interarrival, size=n_requests)
    arrivals = np.cumsum(gaps) - gaps[0]        # first request at t=0
    out = []
    for i in range(n_requests):
        plen = int(rs.choice(prompt_lens))
        out.append(Request(
            rid=i,
            prompt=rs.randint(0, vocab_size, size=plen).astype(np.int32),
            max_new_tokens=int(rs.choice(max_new)),
            arrival=float(arrivals[i])))
    return out


class _Active:
    __slots__ = ("req", "tokens", "last", "latencies", "ttft_s")

    def __init__(self, req, first_token, ttft_s):
        self.req = req
        self.tokens = [int(first_token)]
        self.last = int(first_token)
        self.latencies = []
        self.ttft_s = float(ttft_s)


class Scheduler:
    """Continuous batching over one :class:`ServeEngine`.

    One :meth:`step` = admit every eligible request into free slots
    (grouped prefills), then one decode pass over the active set
    (padded to the engine's batch bucket with distinct free slots),
    then evict finished sequences. :meth:`run` drives a request list to
    completion; fast-forwards virtual time across idle gaps so a sparse
    trace never spins.
    """

    def __init__(self, engine, *, registry=None,
                 clock=time.perf_counter):
        self.engine = engine
        self._registry = registry
        self._clock = clock
        self.num_slots = engine.config.num_slots
        self.free = list(range(self.num_slots))
        self.pending: List[Request] = []
        self.active = {}                      # slot -> _Active
        self.completed: List[CompletedRequest] = []
        self.tick = 0.0
        self.decode_steps = 0
        self.prefill_calls = 0
        self.tokens_generated = 0
        self._eligible_wall = {}
        self._ttft_ms = []
        self._tok_latency_ms = []
        self._t_start = None
        self._t_end = None

    def _reg(self):
        return self._registry or get_registry()

    # -- submission --------------------------------------------------------

    def submit(self, request: Request):
        plen = len(request.prompt)
        eng = self.engine
        if plen > eng.config.prefill_buckets[-1]:
            raise ValueError(
                f"request {request.rid}: prompt ({plen}) exceeds the "
                f"largest prefill bucket "
                f"({eng.config.prefill_buckets[-1]})")
        if plen + request.max_new_tokens > eng.max_len:
            raise ValueError(
                f"request {request.rid}: prompt ({plen}) + "
                f"max_new_tokens ({request.max_new_tokens}) exceeds "
                f"max_position_embeddings ({eng.max_len})")
        self.pending.append(request)
        self.pending.sort(key=lambda r: (r.arrival, r.rid))

    # -- the three phases --------------------------------------------------

    def _admit(self):
        now = self._clock()
        eligible = [r for r in self.pending if r.arrival <= self.tick]
        for r in eligible:
            self._eligible_wall.setdefault(r.rid, now)
        buckets = self.engine.config.batch_buckets
        while eligible and self.free:
            # the prefill call occupies a whole batch bucket (real +
            # pad slots, all distinct), so the group must shrink to the
            # largest bucket that fits entirely inside the free pool
            fits = [b for b in buckets if b <= len(self.free)]
            if not fits:
                break
            group = eligible[:min(len(self.free), fits[-1])]
            eligible = eligible[len(group):]
            for r in group:
                self.pending.remove(r)
            slots = [self.free.pop(0) for _ in group]
            t0 = self._clock()
            first = self.engine.prefill(
                slots, [r.prompt for r in group],
                pad_slot_ids=self.free)
            t1 = self._clock()
            self.prefill_calls += 1
            reg = self._reg()
            for slot, r, tok in zip(slots, group, first):
                ttft = t1 - self._eligible_wall[r.rid]
                self._ttft_ms.append(ttft * 1e3)
                reg.histogram("serve/ttft").observe(ttft * 1e3)
                reg.counter("serve/requests_admitted").inc()
                self.tokens_generated += 1
                st = _Active(r, tok, ttft)
                if self._finished(st):
                    self._evict(slot, st)
                else:
                    self.active[slot] = st

    def _finished(self, st):
        eos = self.engine.config.eos_token_id
        if eos is not None and st.last == eos:
            return True
        return len(st.tokens) >= st.req.max_new_tokens

    def _decode_once(self):
        if not self.active:
            return
        max_bucket = self.engine.config.batch_buckets[-1]
        slots = sorted(self.active)
        for i in range(0, len(slots), max_bucket):
            chunk = slots[i:i + max_bucket]
            toks = [self.active[s].last for s in chunk]
            t0 = self._clock()
            nxt = self.engine.decode(chunk, toks,
                                     pad_slot_ids=self.free)
            dt = self._clock() - t0
            self.decode_steps += 1
            reg = self._reg()
            reg.counter("serve/decode_steps").inc()
            for s, tok in zip(chunk, nxt):
                st = self.active[s]
                st.tokens.append(int(tok))
                st.last = int(tok)
                st.latencies.append(dt)
                self._tok_latency_ms.append(dt * 1e3)
                reg.histogram("serve/tok_latency").observe(dt * 1e3)
                self.tokens_generated += 1
                if self._finished(st):
                    del self.active[s]
                    self._evict(s, st)

    def _evict(self, slot, st):
        if slot in self.active:
            del self.active[slot]
        self.free.append(slot)
        self.free.sort()
        eos = self.engine.config.eos_token_id
        reason = "eos" if (eos is not None and st.last == eos) \
            else "length"
        rec = CompletedRequest(
            rid=st.req.rid,
            tokens=np.asarray(st.tokens, np.int32),
            ttft_s=st.ttft_s,
            mean_tok_latency_s=(float(np.mean(st.latencies))
                                if st.latencies else 0.0),
            finish_reason=reason)
        self.completed.append(rec)
        reg = self._reg()
        reg.counter("serve/requests_completed").inc()
        reg.counter("serve/tokens_generated").inc(len(st.tokens))
        reg.event("serve", "request_done", rid=st.req.rid,
                  tokens=len(st.tokens),
                  prompt_len=len(st.req.prompt),
                  ttft_ms=round(rec.ttft_s * 1e3, 3),
                  mean_tok_latency_ms=round(
                      rec.mean_tok_latency_s * 1e3, 3),
                  finish_reason=reason)

    # -- driving -----------------------------------------------------------

    def step(self):
        """One scheduler iteration: admit, decode once, advance the
        virtual clock one tick."""
        if self._t_start is None:
            self._t_start = self._clock()
        self._admit()
        self._decode_once()
        self._reg().gauge("serve/slot_occupancy").set(
            len(self.active) / self.num_slots)
        self.tick += 1.0

    def run(self, requests=None, *, max_steps=100_000):
        """Drive ``requests`` (plus anything already submitted) to
        completion; returns the completed list in finish order."""
        for r in requests or ():
            self.submit(r)
        steps = 0
        while self.pending or self.active:
            if not self.active and self.pending and \
                    min(r.arrival for r in self.pending) > self.tick:
                # idle gap: fast-forward virtual time to the next
                # arrival instead of spinning empty decode ticks
                self.tick = min(r.arrival for r in self.pending)
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"scheduler exceeded max_steps ({max_steps}) with "
                    f"{len(self.pending)} pending / {len(self.active)} "
                    f"active — a request is not converging")
        self._t_end = self._clock()
        self._census_event()
        return self.completed

    # -- accounting --------------------------------------------------------

    def _census_event(self):
        eng = self.engine
        reg = self._reg()
        reg.gauge("serve/kv_cache_bytes").set(eng.kv_cache_bytes())
        reg.event("serve", "kv_cache",
                  slots_total=self.num_slots,
                  slots_used=len(self.active),
                  slots_free=len(self.free),
                  bytes_per_slot=eng.spec.bytes_per_slot(),
                  cache_dtype=eng.spec.cache_dtype_name(),
                  kv_cache_bytes=eng.kv_cache_bytes())

    @staticmethod
    def _pct(samples, q):
        return float(np.percentile(samples, q)) if samples else None

    def stats(self):
        """Host-side summary of the run (independent of registry
        enablement — the bench's emission source)."""
        wall = ((self._t_end or self._clock())
                - (self._t_start or self._clock()))
        return {
            "requests_completed": len(self.completed),
            "tokens_generated": self.tokens_generated,
            "wall_s": wall,
            "tokens_per_sec": (self.tokens_generated / wall)
            if wall > 0 else None,
            "decode_steps": self.decode_steps,
            "prefill_calls": self.prefill_calls,
            "ttft_p50_ms": self._pct(self._ttft_ms, 50),
            "ttft_p99_ms": self._pct(self._ttft_ms, 99),
            "tok_latency_p50_ms": self._pct(self._tok_latency_ms, 50),
            "tok_latency_p99_ms": self._pct(self._tok_latency_ms, 99),
            "slot_occupancy_last": len(self.active) / self.num_slots,
        }
