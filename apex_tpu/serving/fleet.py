"""Multi-replica serving fleet: router, SLO tiers, quarantine/respawn,
elastic autoscaling.

One :class:`~apex_tpu.serving.engine.ServeEngine` is one replica; the
ROADMAP's "millions of users" need a fleet. :class:`ServeFleet` is a
host-side router over N engines on distinct mesh slices
(``jax.devices()`` partitioned ``max_replicas`` ways, e.g. 2 replicas
x 4 devices, each with its own ``NamedSharding`` over its own slice)
— the PR-7/8 survive-by-moving-state discipline lifted one level up:
requests and their emitted tokens must outlive any single replica.

**Dispatch** is load-aware and host-side: each fleet tick routes every
eligible queued request to the serving replica with the most free
slots (ties broken toward the shortest backlog), bounded by a
per-replica queue-depth cap so one replica never hoards the backlog
while another idles. Migrated requests and ``interactive``-tier
requests jump the dispatch order.

**SLO tiers** (:class:`TierConfig`): ``Request.tier`` in
``{"interactive", "batch"}`` maps to tier-default TTFT/total-deadline
budgets — the PR-7 per-request deadline machinery, filled in at fleet
admission — with per-tier p50/p99 TTFT accounting
(``fleet/ttft_<tier>`` histograms + the per-tier rollup in
:meth:`ServeFleet.stats`).

**Replica health** is a per-replica state machine::

    healthy --bad counters--> degraded --more--> quarantined
       ^                                             |
       |                                      drain + migrate
       +----------- respawning <---------------------+

driven by the replica scheduler's existing
:class:`~apex_tpu.serving.robust.ServeHealth` counters (quarantined
slots, failed requests, decode failures; an ``all_slots_nonfinite``
or a raised NonFiniteError quarantines immediately) plus the
:func:`~apex_tpu.resilience.faults.inject_replica_loss` fault (the
hard-loss drill: the engine drops dead mid-trace). A soft-quarantined
replica is drained via ``Scheduler.drain()`` — its queue migrates
immediately, in-flight slots finish inside the drain window — while a
lost replica migrates everything at once. **Migration** re-admits each
unfinished request as a continuation (prompt + emitted tokens,
remaining budget) and, when the fleet runs the shared prefix store,
moves the KV-cache *state* with it: the donor's slots are device_get
into checksummed canonical host payloads
(``ServeEngine.extract_kv_state``), verified (crc32 + layout) and
inserted into the fleet-wide :class:`PrefixStore` keyed by each
continuation's prefix — so the survivor's seeded prefill hits the
carried state and runs a ONE-token suffix bucket regardless of
context length (constant-cost failover; docs/serving.md#kv-state-
migration). A failed checksum or incompatible layout falls back
LOUDLY (``fleet/kv_fallback_reprefills`` + ``kv_fallback`` event) to
plain token re-prefill; without the store, token re-prefill is the
only path. Either way, because the engine's ``cache_index`` rollback
makes a right-padded prefill equivalent to having decoded the same
prefix, resumed greedy decode is token-identical to an unkilled run
(the e2e acceptance pins it; for sampled decode the RNG stream
differs — see docs/serving.md). ``FleetConfig.model_parallel`` turns
each replica slice into a (data=1, tp=m) mesh — a model too big for
one DP slice serves under the same quarantine/respawn machinery, and
canonical payloads hand off between replicas of ANY tp size.
A respawned replica builds a fresh engine on the same device slice and
re-registers its AOT ladder with the CompileWatcher under a fresh
generation name (same ladder + new name = zero false recompiles).

**Elastic scale**: total pending depth (fleet queue + replica
backlogs) sustained above ``scale_up_pending`` for
``scale_sustain_ticks`` spawns a replica into an idle slot; sustained
at/below ``scale_down_pending`` retires the least-loaded replica with
a graceful drain (queue re-routed, in-flight finishes, then the
engine is dropped).

Everything here is host-side policy over the PR-6/7 machinery —
nothing traces or compiles outside engine (re)spawns, so per-replica
``assert_no_recompiles`` holds across any traffic and any fault.
Telemetry lands under ``fleet/*`` (docs/serving.md has the glossary);
``bench.py serve_fleet`` is the packaged chaos proof.
"""

import dataclasses
import time
import warnings
from typing import List, Mapping, Optional

import numpy as np

from apex_tpu.serving import robust as robust_mod
from apex_tpu.serving.scheduler import CompletedRequest, Request, Scheduler
from apex_tpu.telemetry.registry import get_registry
from apex_tpu.telemetry.trace import emit_flow, emit_span, new_trace_id

TIERS = ("interactive", "batch")

#: replica lifecycle states (the FleetHealth state machine; "idle" is a
#: slot with no engine — never spawned, retired, or awaiting respawn)
REPLICA_STATES = ("idle", "healthy", "degraded", "quarantined",
                  "respawning", "retiring")


@dataclasses.dataclass(frozen=True)
class TierConfig:
    """Per-tier SLO defaults, filled into a request's
    ``ttft_deadline_s`` / ``total_deadline_s`` at fleet admission
    unless the request already carries its own override (the PR-7
    deadline machinery does the enforcement)."""

    ttft_deadline_s: Optional[float] = None
    total_deadline_s: Optional[float] = None


#: the default tier table: interactive traffic carries tight budgets,
#: batch tolerates queueing (no TTFT budget) but not unbounded total
DEFAULT_TIERS = {
    "interactive": TierConfig(ttft_deadline_s=30.0,
                              total_deadline_s=120.0),
    "batch": TierConfig(ttft_deadline_s=None, total_deadline_s=600.0),
}


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Fleet topology, health thresholds, and elastic-scale policy.

    ``num_replicas`` spawn at startup; ``max_replicas`` (0 = same)
    bounds scale-up — device slices are partitioned for the max up
    front, so a scale-up never re-shards a serving replica.
    ``devices_per_replica=0`` auto-partitions ``jax.devices()`` evenly
    over ``max_replicas`` (a host with too few devices falls back to
    meshless replicas sharing the default device — the 1-core CPU
    smoke path)."""

    num_replicas: int = 2
    max_replicas: int = 0               # 0 = num_replicas
    min_replicas: int = 1
    devices_per_replica: int = 0        # 0 = auto-partition
    tiers: Optional[Mapping[str, TierConfig]] = None
    default_tier: str = "interactive"
    robust: Optional[robust_mod.RobustConfig] = None
    replica_queue_depth: int = 0        # 0 = the engine's num_slots
    # health: bad-counter score thresholds (quarantined + failed +
    # decode_failures deltas accumulate; all_slots_nonfinite or a
    # NonFiniteError quarantines immediately)
    degraded_after: int = 1
    quarantine_after: int = 3
    recover_after_ticks: int = 5        # clean ticks: degraded -> healthy
    respawn: bool = True
    respawn_delay_ticks: int = 1
    drain_deadline_s: float = 30.0      # soft-quarantine / retire grace
    # elastic scale (None disables the direction)
    scale_up_pending: Optional[int] = None
    scale_down_pending: Optional[int] = None
    scale_sustain_ticks: int = 3
    # live-monitoring feed: emit a ``fleet``/``health`` event (the
    # :meth:`ServeFleet.health_snapshot` dict) every N ticks; 0 = off
    # (the default — offline JSONL volume is unchanged unless a
    # monitor/dashboard opts in)
    health_event_every: int = 0
    data_axis: str = "data"
    # tensor-parallel width per replica: each replica becomes a
    # (data=1, tp=m) mesh slice, so a model too big for one DP slice
    # serves under the fleet. Requires parallel_state initialized with
    # the same tp (the engine validates it); auto-partition gives each
    # replica exactly m devices.
    model_parallel: int = 1

    def __post_init__(self):
        if self.model_parallel < 1:
            raise ValueError(
                f"model_parallel ({self.model_parallel}) must be >= 1")
        if self.num_replicas < 1:
            raise ValueError(
                f"num_replicas ({self.num_replicas}) must be >= 1")
        maxr = self.max_replicas or self.num_replicas
        if maxr < self.num_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < num_replicas "
                f"({self.num_replicas})")
        if not (1 <= self.min_replicas <= self.num_replicas):
            raise ValueError(
                f"min_replicas ({self.min_replicas}) must be in "
                f"[1, num_replicas]")
        for tier in (self.tiers or {}):
            if tier not in TIERS:
                raise ValueError(f"unknown tier {tier!r}; tiers are "
                                 f"{TIERS}")
        tiers = dict(DEFAULT_TIERS, **(self.tiers or {}))
        if self.default_tier not in tiers:
            raise ValueError(
                f"default_tier {self.default_tier!r} not in "
                f"{tuple(tiers)}")
        if self.degraded_after < 1 or self.quarantine_after < 1:
            raise ValueError("health thresholds must be >= 1")
        if self.quarantine_after < self.degraded_after:
            raise ValueError(
                f"quarantine_after ({self.quarantine_after}) < "
                f"degraded_after ({self.degraded_after})")
        if (self.scale_up_pending is not None
                and self.scale_down_pending is not None
                and self.scale_down_pending >= self.scale_up_pending):
            raise ValueError(
                f"scale_down_pending ({self.scale_down_pending}) must "
                f"be < scale_up_pending ({self.scale_up_pending}) — "
                f"overlapping thresholds oscillate")
        if self.scale_sustain_ticks < 1:
            raise ValueError("scale_sustain_ticks must be >= 1")

    @property
    def resolved_max_replicas(self):
        return self.max_replicas or self.num_replicas


def diurnal_trace(n_requests=32, *, seed=0, period=16.0,
                  base_interarrival=1.0, amplitude=0.6,
                  burst_at=None, burst_n=0, batch_every=4,
                  prompt_lens=(4, 8, 12), max_new=(6, 10),
                  vocab_size=256):
    """Deterministic diurnal + burst many-user trace: inter-arrival
    gaps are exponential with a sinusoidally modulated rate (virtual
    decode ticks — period ``period`` ticks, peak rate ``1+amplitude``
    times the trough's), optionally with ``burst_n`` extra requests
    all arriving at tick ``burst_at`` (the flash-crowd leg). Every
    ``batch_every``-th request is ``tier="batch"``, the rest
    ``"interactive"`` — the ``serve_fleet`` bench workload. Same seed,
    same trace, byte for byte."""
    rs = np.random.RandomState(seed)
    out = []
    t = 0.0
    for i in range(int(n_requests)):
        rate = 1.0 + float(amplitude) * np.sin(
            2.0 * np.pi * t / float(period))
        t += float(rs.exponential(
            float(base_interarrival) / max(rate, 1e-3)))
        plen = int(rs.choice(prompt_lens))
        out.append(Request(
            rid=i,
            prompt=rs.randint(0, vocab_size, size=plen).astype(np.int32),
            max_new_tokens=int(rs.choice(max_new)),
            arrival=t,
            tier="batch" if batch_every and (i % batch_every
                                             == batch_every - 1)
            else "interactive"))
    if burst_at is not None and burst_n:
        for j in range(int(burst_n)):
            plen = int(rs.choice(prompt_lens))
            out.append(Request(
                rid=int(n_requests) + j,
                prompt=rs.randint(0, vocab_size,
                                  size=plen).astype(np.int32),
                max_new_tokens=int(rs.choice(max_new)),
                arrival=float(burst_at), tier="interactive"))
    out.sort(key=lambda r: (r.arrival, r.rid))
    if out:
        first = out[0].arrival
        for r in out:
            r.arrival -= first
    return out


class Replica:
    """One fleet slot: a device slice plus (when spawned) an engine
    and its scheduler. The fleet owns the state transitions; the
    replica just carries the bookkeeping."""

    def __init__(self, idx, devices=None, mesh=None):
        self.idx = int(idx)
        self.devices = devices
        self.mesh = mesh
        self.state = "idle"
        self.engine = None
        self.sched = None
        self.generation = 0          # spawn count -> fresh AOT names
        self.dispatched = 0
        self.completed = 0
        self.evicted = 0             # poisoned-slot evictions observed
        self.respawns = 0
        self.respawn_at = None       # fleet step to respawn at
        self.spawn_seconds = 0.0
        self._health_seen = {}
        self._bad_score = 0
        self._clean_ticks = 0
        self._drain_started_wall = None

    def serving(self):
        return self.state in ("healthy", "degraded")

    def busy(self):
        return self.sched is not None and (self.sched.pending
                                           or self.sched.active)

    def table_row(self):
        return {
            "replica": self.idx,
            "state": self.state,
            "generation": self.generation,
            "dispatched": self.dispatched,
            "completed": self.completed,
            "evicted": self.evicted,
            "respawns": self.respawns,
            "compile_count": getattr(self.engine, "compile_count", None),
            # the serving multipliers stay per-replica columns even on
            # the fleet-SHARED prefix store: each engine reads its own
            # per-scope counters, so hits earned by this replica's
            # traffic (including migrated continuations hitting their
            # own carried prefixes) land here and nowhere else
            "prefix_hits": getattr(self.engine, "prefix_hits", 0),
            "spec_accepted": getattr(self.sched, "spec_accepted", 0),
            "spec_proposed": getattr(self.sched, "spec_proposed", 0),
        }


class ServeFleet:
    """Host-side router over N :class:`ServeEngine` replicas.

    ``engine_factory(replica_idx, mesh, name) -> engine`` overrides
    engine construction (stub engines in the policy tests; the default
    builds a :class:`~apex_tpu.serving.engine.ServeEngine` from
    ``model``/``params``/``serve_config`` on the replica's mesh slice,
    AOT ladder registered with the shared ``watcher`` under ``name``).

    Time follows the scheduler's two-face convention: arrivals are
    virtual (fleet ticks), latencies wall-clock. Fleet-level TTFT is
    honest end-to-end — time queued at the fleet router counts on top
    of the replica-level eligible->first-token measurement.
    """

    def __init__(self, model=None, params=None, serve_config=None,
                 config: FleetConfig = None, *, engine_factory=None,
                 registry=None, watcher=None,
                 clock=time.perf_counter):
        self.config = config or FleetConfig()
        if engine_factory is None and (model is None or params is None):
            raise ValueError("ServeFleet needs model+params (default "
                             "engine factory) or an engine_factory")
        self._model = model
        self._params = params
        self._serve_config = serve_config
        self._factory = engine_factory or self._default_factory
        self._registry = registry
        self._watcher = watcher
        self._clock = clock
        self.tiers = dict(DEFAULT_TIERS, **(self.config.tiers or {}))
        self._robust = self.config.robust or robust_mod.RobustConfig()
        self.max_replicas = self.config.resolved_max_replicas

        self.replicas: List[Replica] = [
            Replica(i, devs, mesh) for i, (devs, mesh) in
            enumerate(self._partition_devices(self.max_replicas))]
        self.pending: List[Request] = []
        self.completed: List[CompletedRequest] = []
        self.rejected = []           # fleet-level RejectedRequest list
        self.tick = 0.0
        self.step_count = 0          # lifetime counter (fault keying)
        self.quarantine_count = 0
        self.respawn_count = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.lost_requests = 0
        self.kv_handoffs = 0
        self.kv_handoff_bytes = 0
        self.kv_fallback_reprefills = 0
        # ONE fleet-scoped prefix store shared by every replica (and
        # every respawn generation): a system prompt prefilled by
        # replica 0 hits on replica 3, a dead replica's prefix work
        # survives it, and KV-state handoff seeds migrated requests
        # through it. Per-scope counters keep each replica's hit
        # columns truthful on the shared store.
        self.prefix_store = None
        if serve_config is not None and getattr(serve_config,
                                                "prefix_cache", False):
            from apex_tpu.serving.prefix_cache import PrefixStore

            self.prefix_store = PrefixStore(
                max_entries=serve_config.prefix_max_entries,
                min_len=serve_config.prefix_min_len)
        # lifetime prefix/spec totals folded in when an engine drops
        # (quarantine/retire) so respawns never erase the accounting
        self._multiplier_totals = {"prefix_lookups": 0, "prefix_hits": 0,
                                   "spec_accepted": 0,
                                   "spec_proposed": 0}
        self.migrated_rids = set()
        self.rebalance_ms: List[float] = []
        self._rebalance = None       # {"t0": wall, "rids": set}
        self._rid_info = {}
        self._tier_stats = {
            t: {"requests": 0, "ok": 0, "goodput_tokens": 0,
                "ttft_ms": []} for t in self.tiers}
        self._above = 0
        self._below = 0
        self._t_start = None
        self._t_end = None

        for i in range(self.config.num_replicas):
            self._spawn(self.replicas[i], reason="startup")
        reg = self._reg()
        if reg.enabled:
            reg.event(
                "fleet", "fleet_start",
                replicas=self.config.num_replicas,
                max_replicas=self.max_replicas,
                devices_per_replica=(
                    len(self.replicas[0].devices)
                    if self.replicas[0].devices else 0),
                tiers={t: dataclasses.asdict(tc)
                       for t, tc in self.tiers.items()})

    # -- construction -------------------------------------------------------

    def _reg(self):
        return self._registry or get_registry()

    def _default_factory(self, idx, mesh, name):
        from apex_tpu.serving.engine import ServeEngine

        return ServeEngine(self._model, self._params,
                           self._serve_config, mesh=mesh,
                           watcher=self._watcher,
                           registry=self._registry, name=name)

    def _partition_devices(self, n_replicas):
        """Slice ``jax.devices()`` into ``n_replicas`` distinct mesh
        slices (each replica's data axis spans only its own devices).
        Falls back to meshless shared-device replicas when the host
        has too few devices — the CPU smoke path."""
        import jax

        devices = jax.devices()
        m = int(self.config.model_parallel)
        dpr = self.config.devices_per_replica
        if dpr == 0 and len(devices) >= n_replicas:
            dpr = m if m > 1 else len(devices) // n_replicas
        if dpr < 1 or len(devices) < n_replicas * dpr:
            return [(None, None)] * n_replicas
        if m > 1 and dpr % m:
            raise ValueError(
                f"devices_per_replica ({dpr}) must be a multiple of "
                f"model_parallel ({m}) — each replica is a (data, tp) "
                f"slice")
        from jax.sharding import Mesh

        slices = []
        for i in range(n_replicas):
            devs = tuple(devices[i * dpr:(i + 1) * dpr])
            if m > 1:
                # a (data, tp) slice per replica; the engine enforces
                # data size 1 (scale out with replicas, not DP width)
                mesh = Mesh(np.asarray(devs).reshape(dpr // m, m),
                            (self.config.data_axis, "tp"))
            else:
                mesh = Mesh(np.asarray(devs), (self.config.data_axis,))
            slices.append((devs, mesh))
        return slices

    def _spawn(self, rep, reason):
        """Build a fresh engine + scheduler into a replica slot. A
        respawn gets a new generation suffix so its AOT ladder
        re-registers under fresh watcher names (same signatures under
        the old names would be flagged as recompiles)."""
        t0 = self._clock()
        name = (f"replica{rep.idx}" if rep.generation == 0
                else f"replica{rep.idx}.g{rep.generation}")
        rep.engine = self._factory(rep.idx, rep.mesh, name)
        if self.prefix_store is not None and hasattr(
                rep.engine, "adopt_prefix_store"):
            # host-only and compile-free, so post-construction is safe;
            # the fresh generation name doubles as a fresh scope
            rep.engine.adopt_prefix_store(self.prefix_store)
        rep.sched = Scheduler(rep.engine, registry=self._registry,
                              robust=self._robust, clock=self._clock,
                              trace_label=f"replica{rep.idx}")
        rep.generation += 1
        rep.respawn_at = None
        rep.spawn_seconds = self._clock() - t0
        rep._health_seen = dict(rep.sched.health.snapshot())
        rep._bad_score = 0
        rep._clean_ticks = 0
        rep._drain_started_wall = None
        if reason == "respawn":
            rep.respawns += 1
            self.respawn_count += 1
            self._reg().counter("fleet/respawns").inc()
            self._reg().event(
                "fleet", "respawn", replica=rep.idx,
                generation=rep.generation,
                spawn_s=round(rep.spawn_seconds, 4),
                compile_count=getattr(rep.engine, "compile_count",
                                      None),
                tick=self.tick)
        self._set_state(rep, "healthy", reason)

    def _set_state(self, rep, state, reason):
        if state == rep.state:
            return
        old = rep.state
        rep.state = state
        reg = self._reg()
        reg.event("fleet", "replica_state", replica=rep.idx,
                  old=old, new=state, reason=reason, tick=self.tick)
        if state == "quarantined":
            self.quarantine_count += 1
            reg.counter("fleet/replicas_quarantined").inc()

    # -- admission ----------------------------------------------------------

    def _fleet_reject(self, request, reason, detail=""):
        rec = robust_mod.RejectedRequest(
            rid=request.rid, reason=reason, tick=self.tick,
            prompt_len=len(request.prompt), detail=detail)
        self.rejected.append(rec)
        reg = self._reg()
        reg.counter("fleet/rejected").inc()
        reg.event("fleet", "rejected", rid=request.rid, reason=reason,
                  tick=self.tick, detail=detail)
        return False

    def submit(self, request: Request):
        """Queue a request at the fleet router. Resolves the tier into
        the PR-7 deadline fields (request-level overrides win) and
        records the tier for the per-tier SLO rollup. Returns False —
        with a ``fleet``/``rejected`` event — on an unknown tier or a
        duplicate rid; replica-level shape rejections surface later at
        dispatch."""
        tier = request.tier or self.config.default_tier
        if tier not in self.tiers:
            return self._fleet_reject(
                request, "unknown_tier",
                f"tier {tier!r} not in {tuple(self.tiers)}")
        if request.rid in self._rid_info:
            return self._fleet_reject(
                request, "duplicate_rid",
                f"rid {request.rid} is already tracked by this fleet")
        tc = self.tiers[tier]
        # trace identity is allocated HERE (not at the replica
        # scheduler) so the fleet's canonical copy carries it: a
        # migration continuation is dataclasses.replace'd from
        # info["orig"], and the donor + survivor span trees must share
        # one trace_id
        trace_id = request.trace_id
        if trace_id is None and self._reg().enabled:
            trace_id = new_trace_id()
        req = dataclasses.replace(
            request, tier=tier, trace_id=trace_id,
            ttft_deadline_s=(request.ttft_deadline_s
                             if request.ttft_deadline_s is not None
                             else tc.ttft_deadline_s),
            total_deadline_s=(request.total_deadline_s
                              if request.total_deadline_s is not None
                              else tc.total_deadline_s))
        self._rid_info[req.rid] = {
            "tier": tier, "orig": req, "base_tokens": [],
            "base_ttft": float("nan"), "base_latencies": [],
            "eligible_wall": None, "wait_s": 0.0, "migrations": 0,
            "replica": None, "done": False,
        }
        self.pending.append(req)
        self.pending.sort(key=lambda r: (r.arrival, r.rid))
        self._reg().counter("fleet/submitted").inc()
        return True

    # -- dispatch -----------------------------------------------------------

    def _queue_cap(self, rep):
        if self.config.replica_queue_depth:
            return self.config.replica_queue_depth
        return getattr(rep.engine.config, "num_slots", 8)

    def _pick_replica(self):
        """Load-aware choice: the serving replica with the most free
        slots, ties toward the shortest backlog, healthy before
        degraded; None when every replica is at capacity (its free
        slots plus the queue-depth cap) — the backlog then waits at
        the fleet, where the autoscale thresholds can see it."""
        best, best_score = None, None
        for rep in self.replicas:
            if not rep.serving():
                continue
            backlog = len(rep.sched.pending)
            if backlog >= self._queue_cap(rep) + len(rep.sched.free):
                continue
            score = (len(rep.sched.free), -backlog,
                     rep.state == "healthy", -rep.dispatched)
            if best is None or score > best_score:
                best, best_score = rep, score
        return best

    def _dispatch(self):
        now = self._clock()
        eligible = [r for r in self.pending if r.arrival <= self.tick]
        for r in eligible:
            info = self._rid_info[r.rid]
            if info["eligible_wall"] is None:
                info["eligible_wall"] = now

        def prio(r):
            info = self._rid_info[r.rid]
            return (0 if info["migrations"] else 1,
                    0 if info["tier"] == "interactive" else 1,
                    r.arrival, r.rid)

        for r in sorted(eligible, key=prio):
            rep = self._pick_replica()
            if rep is None:
                break                # no capacity; autoscale sees it
            ok = rep.sched.submit(
                dataclasses.replace(r, arrival=rep.sched.tick))
            if not ok:
                reason = (rep.sched.rejected[-1].reason
                          if rep.sched.rejected else "rejected")
                if reason in ("prompt_too_long", "budget_too_long"):
                    # an impossible shape is impossible everywhere:
                    # reject at the fleet, don't retry forever
                    self.pending.remove(r)
                    self._rid_info[r.rid]["done"] = True
                    self._fleet_reject(r, reason)
                continue             # transient (full queue): next tick
            self.pending.remove(r)
            info = self._rid_info[r.rid]
            info["wait_s"] += now - info["eligible_wall"]
            info["eligible_wall"] = None
            info["replica"] = rep.idx
            rep.dispatched += 1
            self._reg().counter("fleet/dispatched").inc()
            if self._rebalance and r.rid in self._rebalance["rids"]:
                self._rebalance["rids"].discard(r.rid)
                if r.trace_id is not None:
                    # survivor end of the handoff arrow: flow_id must
                    # match the donor's "out" record in _migrate
                    emit_flow("migrate",
                              f"{r.trace_id}:m{info['migrations']}",
                              "in", registry=self._reg(),
                              trace_id=r.trace_id, rid=r.rid,
                              replica=rep.idx,
                              label=f"replica{rep.idx}")
                if not self._rebalance["rids"]:
                    self._finish_rebalance()

    def _finish_rebalance(self):
        dt_ms = (self._clock() - self._rebalance["t0"]) * 1e3
        self.rebalance_ms.append(dt_ms)
        reg = self._reg()
        reg.event("fleet", "rebalance", latency_ms=round(dt_ms, 3),
                  tick=self.tick)
        reg.gauge("fleet/rebalance_latency_ms").set(dt_ms)
        self._rebalance = None

    # -- completion & tier accounting ---------------------------------------

    def _collect(self, rep):
        while rep.sched.completed:
            c = rep.sched.completed.pop(0)
            rep.completed += 1
            if c.finish_reason == "poisoned":
                rep.evicted += 1
            info = self._rid_info.get(c.rid)
            if info is None:         # a request the fleet never routed
                self.completed.append(c)
                continue
            tokens = info["base_tokens"] + list(c.tokens)
            if np.isfinite(info["base_ttft"]):
                ttft = info["base_ttft"]
            elif np.isfinite(c.ttft_s):
                ttft = info["wait_s"] + c.ttft_s
            else:
                ttft = float("nan")
            self._complete(c.rid, tokens=tokens,
                           reason=c.finish_reason, ttft_s=ttft,
                           mean_lat=c.mean_tok_latency_s)

    def _complete(self, rid, *, tokens, reason, ttft_s,
                  mean_lat=0.0):
        info = self._rid_info[rid]
        info["done"] = True
        rec = CompletedRequest(
            rid=rid, tokens=np.asarray(list(tokens), np.int32),
            ttft_s=float(ttft_s),
            mean_tok_latency_s=float(mean_lat), finish_reason=reason)
        self.completed.append(rec)
        ts = self._tier_stats[info["tier"]]
        ts["requests"] += 1
        if reason in robust_mod.OK_STATUSES:
            ts["ok"] += 1
            ts["goodput_tokens"] += len(rec.tokens)
        reg = self._reg()
        if np.isfinite(rec.ttft_s):
            ts["ttft_ms"].append(rec.ttft_s * 1e3)
            reg.histogram(f"fleet/ttft_{info['tier']}").observe(
                rec.ttft_s * 1e3)
        return rec

    # -- quarantine, loss & migration ---------------------------------------

    def _lose_replica(self, rep, reason="replica_loss"):
        """Hard loss: the engine is gone — migrate EVERYTHING now,
        then count down to respawn. KV capture runs between the
        scheduler sweep and the re-admission: slot release only
        forgets ids (rows stay resident), so the donor's cache is
        still intact and each active request's state rides out as a
        checksummed host payload."""
        self._collect(rep)
        t0 = self._clock()
        records = rep.sched.extract_unfinished(reason=reason)
        kv_payloads = self._capture_kv(rep, records)
        self._set_state(rep, "quarantined", reason)
        self._migrate(rep, records, t0, reason=reason,
                      kv_payloads=kv_payloads)
        self._drop_engine(rep)
        self._schedule_respawn(rep, reason)

    def _begin_quarantine(self, rep, reason, hard=False):
        """Soft quarantine: the engine still answers, so drain — close
        admissions, migrate the queue immediately, let in-flight slots
        finish inside ``drain_deadline_s`` (stragglers migrate at the
        deadline)."""
        if hard:
            self._lose_replica(rep, reason)
            return
        self._set_state(rep, "quarantined", reason)
        rep.sched.drain(reason)
        rep._drain_started_wall = self._clock()
        t0 = self._clock()
        records = rep.sched.extract_unfinished(reason=reason,
                                               which="pending")
        self._migrate(rep, records, t0, reason=reason)

    def _finish_quarantine(self, rep):
        """Drain complete (or deadline blown): migrate whatever is
        left, drop the engine, schedule the respawn."""
        self._collect(rep)
        t0 = self._clock()
        records = rep.sched.extract_unfinished(reason="quarantine_drain")
        if records:
            kv_payloads = self._capture_kv(rep, records)
            self._migrate(rep, records, t0, reason="quarantine_drain",
                          kv_payloads=kv_payloads)
        self._drop_engine(rep)
        self._schedule_respawn(rep, "quarantine_drain")

    def _drain_deadline_passed(self, rep):
        return (rep._drain_started_wall is not None
                and self._clock() - rep._drain_started_wall
                > self.config.drain_deadline_s)

    def _drop_engine(self, rep):
        t = self._multiplier_totals
        t["prefix_lookups"] += getattr(rep.engine, "prefix_lookups", 0)
        t["prefix_hits"] += getattr(rep.engine, "prefix_hits", 0)
        t["spec_accepted"] += getattr(rep.sched, "spec_accepted", 0)
        t["spec_proposed"] += getattr(rep.sched, "spec_proposed", 0)
        rep.engine = None
        rep.sched = None
        rep._drain_started_wall = None

    def _schedule_respawn(self, rep, reason):
        if not self.config.respawn:
            return
        rep.respawn_at = self.step_count + self.config.respawn_delay_ticks
        self._set_state(rep, "respawning", reason)

    def _max_prefill(self):
        """The widest prefill bucket any replica (serving or
        spawnable) offers — the migration-continuation admission
        bound."""
        widest = 0
        for rep in self.replicas:
            if rep.engine is not None:
                widest = max(widest,
                             rep.engine.config.prefill_buckets[-1])
        if widest == 0 and self._serve_config is not None:
            widest = max(self._serve_config.prefill_buckets)
        return widest or 10 ** 9

    def _capture_kv(self, rep, records):
        """Donor-side half of KV-state handoff: map each ACTIVE
        record's slot to a checksummed host payload
        (``ServeEngine.extract_kv_state``). Returns ``{rid: payload}``
        — empty when the fleet has no shared prefix store (the seeding
        path), the engine has no KV surface (stub engines), or
        extraction itself fails (logged loudly; migration then falls
        back to token re-prefill for every request). The armed
        ``kv_corrupt`` fault flips one byte here, in flight — the
        checksum-fallback drill."""
        from apex_tpu.resilience import faults

        eng = rep.engine
        if (eng is None or self.prefix_store is None
                or not hasattr(eng, "extract_kv_state")):
            return {}
        slots = {r["request"].rid: r["slot"] for r in records
                 if r.get("slot") is not None}
        if not slots:
            return {}
        try:
            payloads = eng.extract_kv_state(sorted(set(slots.values())))
        except Exception as e:  # noqa: BLE001 — degraded, never dead
            reg = self._reg()
            reg.counter("fleet/kv_extract_failures").inc()
            reg.event("fleet", "kv_extract_failed", replica=rep.idx,
                      error=type(e).__name__, detail=str(e)[:200],
                      tick=self.tick)
            return {}
        if faults.kv_corrupt_for(self.step_count) == rep.idx:
            self._corrupt_payload(rep, payloads)
        return {rid: payloads[slot] for rid, slot in slots.items()
                if slot in payloads}

    def _corrupt_payload(self, rep, payloads):
        """The ``kv_corrupt`` injection point: XOR one byte of the
        largest leaf of the first payload's rows — exactly the kind of
        in-flight bit rot the crc32 must catch downstream."""
        import jax

        if not payloads:
            return
        slot = sorted(payloads)[0]
        leaf = max(jax.tree_util.tree_leaves(payloads[slot]["rows"]),
                   key=lambda a: a.nbytes)
        leaf.reshape(-1).view(np.uint8)[0] ^= 0xFF
        reg = self._reg()
        reg.counter("fleet/kv_corrupt_injected").inc()
        reg.event("fleet", "kv_corrupt_injected", replica=rep.idx,
                  slot=int(slot), tick=self.tick)

    def _survivor_template(self, donor):
        """The canonical seed-row layout migrated state must match: a
        serving survivor's template when one exists, else the donor's
        own (layouts are tp-independent by construction, so any engine
        of the same model agrees)."""
        for rep in self.replicas:
            if (rep is not donor and rep.serving()
                    and hasattr(rep.engine, "seed_row_template")):
                return rep.engine.seed_row_template()
        if donor.engine is not None and hasattr(donor.engine,
                                                "seed_row_template"):
            return donor.engine.seed_row_template()
        return None

    @staticmethod
    def _layout_matches(rows, tmpl):
        import jax

        try:
            tl, tdef = jax.tree_util.tree_flatten(tmpl)
            rl, rdef = jax.tree_util.tree_flatten(rows)
            if tdef != rdef:
                return False
            return all(
                np.shape(a) == np.shape(b)
                and np.asarray(a).dtype == np.asarray(b).dtype
                for a, b in zip(rl, tl))
        except Exception:  # noqa: BLE001 — malformed payload = mismatch
            return False

    def _seed_prefix_from_payload(self, rep, rid, cont, payload):
        """Survivor-side half of KV-state handoff: verify the crc32,
        validate the canonical layout against a serving survivor's
        template, then insert the carried rows into the SHARED prefix
        store keyed by the continuation's prefix — the survivor's
        seeded prefill hits it and runs a one-token suffix bucket, so
        migration cost is flat in context length. Any failed check
        falls back LOUDLY (``fleet/kv_fallback_reprefills`` +
        ``kv_fallback`` event) to the token re-prefill the fleet
        always had: degraded, never poisoned, never silent. Returns
        True when the handoff landed."""
        import jax

        from apex_tpu.serving.engine import kv_payload_crc

        reg = self._reg()
        why = None
        try:
            if kv_payload_crc(payload) != payload.get("crc"):
                why = "checksum_mismatch"
        except Exception:  # noqa: BLE001 — unhashable payload = corrupt
            why = "checksum_mismatch"
        if why is None:
            tmpl = self._survivor_template(rep)
            if tmpl is None or not self._layout_matches(
                    payload.get("rows"), tmpl):
                why = "incompatible_layout"
        if why is not None:
            self.kv_fallback_reprefills += 1
            reg.counter("fleet/kv_fallback_reprefills").inc()
            reg.event("fleet", "kv_fallback", rid=rid, replica=rep.idx,
                      reason=why, tick=self.tick,
                      trace_id=cont.trace_id)
            return False
        carry = np.asarray(cont.prompt, np.int32)
        cut = min(int(payload["length"]), len(carry) - 1)
        if cut <= self.prefix_store.min_len:
            # too short to key — a normal miss, not a fallback
            return False
        self.prefix_store.insert(carry[:cut], payload["rows"],
                                 payload.get("draft_rows"),
                                 scope=f"handoff.replica{rep.idx}")
        nbytes = int(sum(
            l.nbytes for l in jax.tree_util.tree_leaves(
                (payload["rows"], payload.get("draft_rows")))))
        self.kv_handoffs += 1
        self.kv_handoff_bytes += nbytes
        reg.counter("fleet/kv_handoffs").inc()
        reg.counter("fleet/kv_handoff_bytes").inc(nbytes)
        reg.event("fleet", "kv_handoff", rid=rid, replica=rep.idx,
                  slot=int(payload.get("slot", -1)),
                  length=int(payload["length"]), cut=int(cut),
                  bytes=nbytes, tick=self.tick,
                  trace_id=cont.trace_id)
        return True

    def _migrate(self, rep, records, t0, reason, kv_payloads=None):
        """Re-admit a dead/draining replica's unfinished requests as
        continuations: prompt + emitted tokens, remaining token
        budget, same tier/deadlines. Greedy continuations are
        token-identical to an unkilled run (the cache_index-rollback
        prefill equivalence); a continuation too long for every
        prefill ladder is a non-silent loss (terminal ``failed`` +
        ``fleet/lost_requests``). With KV payloads in hand
        (``_capture_kv``) each continuation's carried state seeds the
        shared prefix store first, so the survivor re-prefills a
        one-token suffix instead of the whole context."""
        migrated, tokens_carried = 0, 0
        readmitted = []
        max_prefill = self._max_prefill()
        for r in records:
            rid = r["request"].rid
            info = self._rid_info.get(rid)
            if info is None:
                continue
            emitted = info["base_tokens"] + list(r["tokens"])
            if r["tokens"] and not np.isfinite(info["base_ttft"]):
                info["base_ttft"] = info["wait_s"] + r["ttft_s"]
            info["base_latencies"] += list(r["latencies"])
            orig = info["orig"]
            remaining = orig.max_new_tokens - len(emitted)
            if remaining <= 0:
                # the replica died on the final token's doorstep
                self._complete(rid, tokens=emitted, reason="length",
                               ttft_s=info["base_ttft"])
                continue
            prompt = np.asarray(orig.prompt, np.int32)
            if emitted:
                prompt = np.concatenate(
                    [prompt, np.asarray(emitted, np.int32)])
            if len(prompt) > max_prefill:
                self.lost_requests += 1
                reg = self._reg()
                reg.counter("fleet/lost_requests").inc()
                reg.event("fleet", "migration_failed", rid=rid,
                          replica=rep.idx,
                          prompt_len=int(len(prompt)),
                          max_prefill=int(max_prefill), tick=self.tick)
                self._complete(rid, tokens=emitted, reason="failed",
                               ttft_s=info["base_ttft"])
                continue
            info["base_tokens"] = list(emitted)
            info["migrations"] += 1
            self.migrated_rids.add(rid)
            info["eligible_wall"] = self._clock()
            cont = dataclasses.replace(
                orig, prompt=prompt, max_new_tokens=remaining,
                arrival=self.tick)
            kv = bool(kv_payloads and rid in kv_payloads)
            if kv:
                self._seed_prefix_from_payload(rep, rid, cont,
                                               kv_payloads[rid])
            if cont.trace_id is not None:
                # donor-side handoff: a serve/migrate span covering
                # extract -> re-admission plus the "out" end of the
                # flow arrow the survivor's dispatch closes
                now_p = time.perf_counter()
                start_p = (t0 if self._clock is time.perf_counter
                           else now_p)
                emit_span("serve/migrate", start_p, now_p,
                          registry=self._reg(),
                          trace_id=cont.trace_id, rid=rid,
                          reason=reason, kv_handoff=kv,
                          replica=f"replica{rep.idx}")
                emit_flow("migrate",
                          f"{cont.trace_id}:m{info['migrations']}",
                          "out", registry=self._reg(),
                          trace_id=cont.trace_id, rid=rid,
                          replica=rep.idx, label=f"replica{rep.idx}")
            self.pending.append(cont)
            readmitted.append(rid)
            migrated += 1
            tokens_carried += len(emitted)
        self.pending.sort(key=lambda r: (r.arrival, r.rid))
        reg = self._reg()
        reg.counter("fleet/migrated").inc(migrated)
        reg.event("fleet", "migration", replica=rep.idx,
                  requests=migrated, tokens_carried=tokens_carried,
                  reason=reason, tick=self.tick,
                  extract_ms=round((self._clock() - t0) * 1e3, 3))
        if readmitted:
            if self._rebalance is None:
                self._rebalance = {"t0": t0, "rids": set()}
            self._rebalance["rids"].update(readmitted)

    # -- health -------------------------------------------------------------

    def _health_check(self, rep):
        """Drive the state machine off the replica scheduler's
        ServeHealth counter deltas: poisoned slots, failed requests
        and exhausted-retry decode failures accumulate a bad score;
        ``all_slots_nonfinite`` (model-level poison) quarantines
        immediately."""
        h = rep.sched.health.snapshot()
        seen = rep._health_seen
        rep._health_seen = dict(h)
        bad = sum(h.get(k, 0) - seen.get(k, 0)
                  for k in ("quarantined", "failed", "decode_failures"))
        if h.get("all_slots_nonfinite", 0) > seen.get(
                "all_slots_nonfinite", 0):
            bad += self.config.quarantine_after
        if bad == 0:
            if rep.state == "degraded":
                rep._clean_ticks += 1
                if rep._clean_ticks >= self.config.recover_after_ticks:
                    rep._bad_score = 0
                    rep._clean_ticks = 0
                    self._set_state(rep, "healthy", "recovered")
            return
        rep._clean_ticks = 0
        rep._bad_score += bad
        if rep._bad_score >= self.config.quarantine_after:
            self._begin_quarantine(rep, "unhealthy")
        elif rep._bad_score >= self.config.degraded_after \
                and rep.state == "healthy":
            self._set_state(rep, "degraded", "health_counters")

    # -- elastic scale ------------------------------------------------------

    def pending_depth(self):
        """Total backlog: the fleet queue plus every replica queue —
        the autoscale signal (and the ``fleet/pending_depth`` gauge)."""
        return len(self.pending) + sum(
            len(rep.sched.pending) for rep in self.replicas
            if rep.sched is not None)

    def _serving_count(self):
        return sum(1 for rep in self.replicas if rep.serving())

    def _expected_count(self):
        """Replicas that *should* be serving right now: everything but
        empty slots and deliberate retirements. ``expected - serving``
        is therefore the count of replicas currently lost to faults —
        the live monitor's replica-health signal (a
        ``fleet/replicas_serving < fleet/replicas_expected`` window
        breach), and it self-resolves on respawn without the monitor
        knowing the fleet's scale policy."""
        return sum(1 for rep in self.replicas
                   if rep.state not in ("idle", "retiring"))

    def health_snapshot(self):
        """Point-in-time fleet health view (host-side, registry-free) —
        the feed ``telemetry.monitor`` and ``tools/monitor_dash.py``
        render: queue depth, serving/expected counts, the per-replica
        state table, and the per-tier SLO rollup."""
        return {
            "tick": self.tick,
            "pending": self.pending_depth(),
            "serving": self._serving_count(),
            "expected": self._expected_count(),
            "replicas": [rep.table_row() for rep in self.replicas],
            "tiers": self._tier_rollup(),
        }

    def _autoscale(self):
        cfg = self.config
        depth = self.pending_depth()
        if cfg.scale_up_pending is not None \
                and depth > cfg.scale_up_pending:
            self._above += 1
        else:
            self._above = 0
        if cfg.scale_down_pending is not None \
                and depth <= cfg.scale_down_pending:
            self._below += 1
        else:
            self._below = 0
        reg = self._reg()
        if self._above >= cfg.scale_sustain_ticks \
                and self._serving_count() < self.max_replicas:
            idle = next((r for r in self.replicas
                         if r.state == "idle"), None)
            if idle is not None:
                self._spawn(idle, reason="scale_up")
                self.scale_ups += 1
                self._above = 0
                reg.counter("fleet/scale_ups").inc()
                reg.event("fleet", "scale_up", replica=idle.idx,
                          pending_depth=depth, tick=self.tick)
        if self._below >= cfg.scale_sustain_ticks \
                and self._serving_count() > cfg.min_replicas \
                and not self.pending:
            serving = [r for r in self.replicas if r.serving()]
            victim = min(serving, key=lambda r: (
                len(r.sched.active), len(r.sched.pending), r.idx))
            self._begin_retire(victim, depth)
            self._below = 0

    def _begin_retire(self, rep, depth):
        """Graceful scale-down: stop routing to the replica, migrate
        its queue, let in-flight work finish, then drop the engine
        back to an idle slot."""
        self.scale_downs += 1
        reg = self._reg()
        reg.counter("fleet/scale_downs").inc()
        reg.event("fleet", "scale_down", replica=rep.idx,
                  pending_depth=depth, tick=self.tick)
        self._set_state(rep, "retiring", "scale_down")
        rep.sched.drain("scale_down")
        rep._drain_started_wall = self._clock()
        t0 = self._clock()
        records = rep.sched.extract_unfinished(reason="scale_down",
                                               which="pending")
        if records:
            self._migrate(rep, records, t0, reason="scale_down")

    def _finish_retire(self, rep):
        self._collect(rep)
        t0 = self._clock()
        records = rep.sched.extract_unfinished(reason="scale_down")
        if records:
            self._migrate(rep, records, t0, reason="scale_down")
        self._drop_engine(rep)
        self._set_state(rep, "idle", "retired")

    # -- driving ------------------------------------------------------------

    def step(self):
        """One fleet tick: fire any armed replica-loss fault, dispatch
        eligible requests, step every live replica scheduler (health
        transitions ride on the counters), respawn what is due, and
        evaluate the autoscale thresholds."""
        from apex_tpu.resilience import NonFiniteError, faults

        if self._t_start is None:
            self._t_start = self._clock()
        victim = faults.replica_loss_for(self.step_count)
        if victim is not None and 0 <= victim < len(self.replicas) \
                and self.replicas[victim].serving():
            self._lose_replica(self.replicas[victim])
        self._dispatch()
        for rep in self.replicas:
            if rep.sched is None:
                continue
            if rep.busy():
                try:
                    rep.sched.step()
                except NonFiniteError:
                    # the whole-batch guard fired: model-level poison
                    # on THIS replica — the implicated requests were
                    # already terminal'd ``poisoned``; everything else
                    # migrates and the replica respawns with fresh
                    # state (the fleet-level restore)
                    self._collect(rep)
                    self._begin_quarantine(rep, "model_poison",
                                           hard=True)
                    continue
                self._collect(rep)
            if rep.state == "quarantined" and (
                    not rep.sched or not rep.sched.active
                    or self._drain_deadline_passed(rep)):
                if rep.sched is not None:
                    self._finish_quarantine(rep)
            elif rep.state == "retiring" and (
                    not rep.busy() or self._drain_deadline_passed(rep)):
                self._finish_retire(rep)
            elif rep.serving():
                self._health_check(rep)
        for rep in self.replicas:
            if rep.state == "respawning" and rep.respawn_at is not None \
                    and self.step_count >= rep.respawn_at:
                self._spawn(rep, reason="respawn")
        self._autoscale()
        reg = self._reg()
        reg.gauge("fleet/pending_depth").set(self.pending_depth())
        reg.gauge("fleet/replicas_serving").set(self._serving_count())
        reg.gauge("fleet/replicas_expected").set(self._expected_count())
        every = self.config.health_event_every
        if every and self.step_count % every == 0 and reg.enabled:
            reg.event("fleet", "health", **self.health_snapshot())
        self.tick += 1.0
        self.step_count += 1

    def _work_remaining(self):
        if self.pending:
            return True
        if any(rep.busy() for rep in self.replicas):
            return True
        return False

    def run(self, requests=None, *, max_steps=100_000):
        """Drive ``requests`` (plus anything already submitted) to a
        terminal state across the fleet; returns the completed list in
        finish order. Mirrors ``Scheduler.run``: idle gaps fast-forward
        the virtual clock, ``max_steps`` exhaustion cancels loudly."""
        for r in requests or ():
            self.submit(r)
        steps = 0
        while self._work_remaining():
            if not any(rep.sched is not None and rep.sched.active
                       for rep in self.replicas) \
                    and self.pending \
                    and min(r.arrival for r in self.pending) > self.tick:
                self.tick = min(r.arrival for r in self.pending)
            self.step()
            steps += 1
            if steps > max_steps:
                self._exhaust_max_steps(max_steps)
                break
        self._t_end = self._clock()
        self._fleet_report()
        return self.completed

    def _exhaust_max_steps(self, max_steps):
        stranded = 0
        for rep in self.replicas:
            if rep.sched is None:
                continue
            for rec in rep.sched.extract_unfinished(reason="max_steps"):
                info = self._rid_info.get(rec["request"].rid)
                if info is None:
                    continue
                self._complete(
                    rec["request"].rid,
                    tokens=info["base_tokens"] + list(rec["tokens"]),
                    reason="max_steps", ttft_s=info["base_ttft"])
                stranded += 1
        for r in list(self.pending):
            self.pending.remove(r)
            info = self._rid_info[r.rid]
            self._complete(r.rid, tokens=info["base_tokens"],
                           reason="max_steps",
                           ttft_s=info["base_ttft"])
            stranded += 1
        self._reg().event("fleet", "max_steps_exhausted",
                          max_steps=max_steps, cancelled=stranded,
                          tick=self.tick)
        warnings.warn(
            f"fleet exhausted max_steps ({max_steps}) with {stranded} "
            f"request(s) left — all cancelled with terminal status "
            f"'max_steps'", stacklevel=3)

    # -- accounting ---------------------------------------------------------

    @staticmethod
    def _pct(samples, q):
        return float(np.percentile(samples, q)) if samples else None

    def _tier_rollup(self):
        out = {}
        for tier, ts in self._tier_stats.items():
            out[tier] = {
                "requests": ts["requests"],
                "ok": ts["ok"],
                "goodput_tokens": ts["goodput_tokens"],
                "ttft_p50_ms": self._pct(ts["ttft_ms"], 50),
                "ttft_p99_ms": self._pct(ts["ttft_ms"], 99),
            }
        return out

    def stats(self):
        """Host-side fleet summary — the ``serve_fleet`` bench's
        emission source: aggregate + per-tier goodput and tail
        latency, migration/rebalance accounting, per-replica table."""
        now = self._clock()
        wall = (self._t_end or now) - (self._t_start or now)
        by_reason = {}
        goodput_tokens = 0
        total_tokens = 0
        for c in self.completed:
            by_reason[c.finish_reason] = \
                by_reason.get(c.finish_reason, 0) + 1
            total_tokens += len(c.tokens)
            if c.finish_reason in robust_mod.OK_STATUSES:
                goodput_tokens += len(c.tokens)
        tiers = self._tier_rollup()
        mult = dict(self._multiplier_totals)
        for rep in self.replicas:
            mult["prefix_lookups"] += getattr(rep.engine,
                                              "prefix_lookups", 0)
            mult["prefix_hits"] += getattr(rep.engine, "prefix_hits", 0)
            mult["spec_accepted"] += getattr(rep.sched,
                                             "spec_accepted", 0)
            mult["spec_proposed"] += getattr(rep.sched,
                                             "spec_proposed", 0)
        return {
            "prefix_hits": mult["prefix_hits"],
            "prefix_hit_rate": round(
                mult["prefix_hits"] / mult["prefix_lookups"], 4)
            if mult["prefix_lookups"] else None,
            "spec_acceptance_rate": round(
                mult["spec_accepted"] / mult["spec_proposed"], 4)
            if mult["spec_proposed"] else None,
            "requests_completed": len(self.completed),
            "requests_ok": sum(by_reason.get(r, 0)
                               for r in robust_mod.OK_STATUSES),
            "requests_by_reason": by_reason,
            "requests_rejected": len(self.rejected),
            "tokens_generated": total_tokens,
            "goodput_tokens": goodput_tokens,
            "wall_s": wall,
            "tokens_per_sec": (total_tokens / wall) if wall > 0
            else None,
            "goodput_tokens_per_sec": (goodput_tokens / wall)
            if wall > 0 else None,
            "by_tier": tiers,
            "ttft_p99_ms_interactive":
                tiers.get("interactive", {}).get("ttft_p99_ms"),
            "ttft_p99_ms_batch":
                tiers.get("batch", {}).get("ttft_p99_ms"),
            "migrated_requests": len(self.migrated_rids),
            "lost_requests": self.lost_requests,
            "kv_handoffs": self.kv_handoffs,
            "kv_handoff_bytes": self.kv_handoff_bytes,
            "kv_fallback_reprefills": self.kv_fallback_reprefills,
            # the SHARED store's global hit rate: cross-replica reuse
            # included, which is exactly what per-replica accounting
            # can't see (None when the fleet runs without the store)
            "fleet_prefix_hit_rate": (
                round(self.prefix_store.hits
                      / self.prefix_store.lookups, 4)
                if self.prefix_store is not None
                and self.prefix_store.lookups else None),
            "rebalance_latency_ms": (round(self.rebalance_ms[-1], 3)
                                     if self.rebalance_ms else None),
            "replicas_quarantined": self.quarantine_count,
            "replicas_respawned": self.respawn_count,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "dispatched": sum(rep.dispatched for rep in self.replicas),
            "pending_depth_last": self.pending_depth(),
            "replicas": [rep.table_row() for rep in self.replicas],
        }

    def _fleet_report(self):
        reg = self._reg()
        if not reg.enabled:
            return
        s = self.stats()
        reg.event("fleet", "fleet_report",
                  **{k: s[k] for k in (
                      "requests_completed", "requests_ok",
                      "goodput_tokens", "migrated_requests",
                      "lost_requests", "rebalance_latency_ms",
                      "replicas_quarantined", "replicas_respawned",
                      "scale_ups", "scale_downs", "dispatched")},
                  by_tier=s["by_tier"], replicas=s["replicas"],
                  tick=self.tick)
