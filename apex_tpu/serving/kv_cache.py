"""Slotted KV-cache store for continuous-batching decode.

The serving cache is an **explicit pytree**, not a flax variable
collection: one *slot* per concurrent sequence, preallocated for the
model's full ``max_position_embeddings`` window, with every leaf
carrying a leading ``[num_slots]`` axis. The layout is derived from the
model itself (``jax.eval_shape`` of its ``decode=True`` init — no
parameters materialize), so any architecture the incremental-decode
path supports (MHA/GQA, rope/learned positions, ``scan_layers``) gets
a correct store for free.

Why slots: continuous batching admits and evicts *individual*
sequences while the decode step keeps one static shape. The engine
gathers a bucket of slot rows, runs the model's own decode attention
per row (each slot carries its own scalar ``cache_index``, so mixed
sequence lengths coexist), and scatters the rows back — admission is a
prefill-scatter into free slots, eviction is just forgetting a slot id.

Sharding: every leaf's leading axis is the slot axis, so one
``NamedSharding(mesh, P(data_axis))`` spreads the store — byte-for-byte
the dominant HBM cost of serving — across the data axis of the mesh.

int8 mode (``mode="int8"``): K/V leaves are stored as blockwise
symmetric int8 with fp32 scales per ``block_size``-lane block —
``parallel.compression``'s gradient-collective scheme pointed at the
cache (EQuARX-adjacent: the quantized-block layout stays collective-
friendly). Each cache *position* quantizes independently
(:func:`~apex_tpu.parallel.compression.quantize_rows_blockwise` over
the flattened ``[groups * head_dim]`` feature lanes), so appending one
token's K/V never re-quantizes — and never drifts — previously written
positions. Reads dequantize on the fly inside the compiled decode step
(:meth:`KVCacheSpec.materialize_rows`); the error per lane is bounded
by half a quantization step, ``absmax_block / 254`` — the same
per-block bound the compression tests pin, inherited verbatim here
(tests/L0/test_serving.py holds a 64-token decode to it).
"""

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.parallel import compression

# flax decode-cache leaf naming (transformer_lm._decode_attention):
# cached_key / cached_value hold K/V, cache_index the scalar fill level.
KV_LEAF_PREFIX = "cached_"
CACHE_INDEX = "cache_index"

CACHE_MODES = ("bf16", "int8")


def _names(path):
    return tuple(str(getattr(e, "key", getattr(e, "idx", e)))
                 for e in path)


def _is_kv(names):
    return bool(names) and names[-1].startswith(KV_LEAF_PREFIX)


def row_template(model, token_dtype=jnp.int32):
    """ShapeDtypeStruct pytree of ONE slot's cache (batch 1) for a
    ``decode=True`` model — a shape-only trace, no params materialize
    (the serving sibling of ``generation.init_cache``)."""
    dummy = jnp.zeros((1, 1), token_dtype)
    return jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), dummy))["cache"]


def zero_row(template):
    """Concrete zeroed cache row from a :func:`row_template` tree
    (trace-friendly: the serving prefill builds fresh rows in-graph)."""
    return jax.tree_util.tree_map(
        lambda sd: jnp.zeros(sd.shape, sd.dtype), template)


def store_lengths(store):
    """Per-slot fill level ``[num_slots] i32`` from the first
    ``cache_index`` leaf (all layers agree — the engine keeps them in
    lockstep, like ``generation._set_cache_index``)."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(store)[0]:
        names = _names(path)
        if names and names[-1] == CACHE_INDEX:
            return leaf.reshape(leaf.shape[0], -1)[:, 0]
    raise ValueError("store has no cache_index leaf — not a decode "
                     "cache pytree")


class KVCacheSpec:
    """Host-side layout descriptor + the pure in-graph conversion
    helpers between the slotted store and model-ready cache rows.

    Everything here is trace-friendly (pure jnp): the engine calls
    these inside its AOT-compiled prefill/decode steps. The spec itself
    holds only shapes and static config — it never owns device memory
    (the engine owns the store array it allocates here).
    """

    def __init__(self, model, num_slots, *, mode="bf16",
                 block_size=compression.BLOCK_SIZE,
                 token_dtype=jnp.int32):
        if mode not in CACHE_MODES:
            raise ValueError(f"cache mode {mode!r} not in {CACHE_MODES}")
        if num_slots < 1:
            raise ValueError(f"num_slots ({num_slots}) must be >= 1")
        self.model = model
        self.num_slots = int(num_slots)
        self.mode = mode
        self.block_size = int(block_size)
        self.template = row_template(model, token_dtype)
        # path -> template ShapeDtypeStruct, for shape/dtype recovery
        # when materializing quantized leaves
        self._by_path = {
            _names(p): sd for p, sd in
            jax.tree_util.tree_flatten_with_path(self.template)[0]}

    # -- layout ------------------------------------------------------------

    def _kv_feature_width(self, sd):
        """Lanes per cache position: the trailing (batch=1, groups,
        head_dim) axes flattened — the blockwise quantization row."""
        return int(np.prod(sd.shape[-3:]))

    def _block_size(self, sd):
        """Effective block for this leaf: the configured 256-lane grid,
        clamped to the feature width — a model whose per-position K/V
        row is narrower than one block would otherwise store zero-
        padded lanes at full price (observed 2x blowup on toy heads)."""
        return min(self.block_size, self._kv_feature_width(sd))

    def _num_blocks(self, sd):
        return compression.num_blocks(self._kv_feature_width(sd),
                                      self._block_size(sd))

    def allocate(self):
        """Zeroed slotted store: every template leaf stacked to
        ``[num_slots, ...]``; in int8 mode K/V leaves become
        ``{"q": int8 [..., nb, block], "scale": f32 [..., nb, 1]}``
        subtrees (positions axis preserved, feature lanes blocked)."""
        def leaf(path, sd):
            names = _names(path)
            if self.mode == "int8" and _is_kv(names):
                lead = (self.num_slots,) + tuple(sd.shape[:-3])
                nb = self._num_blocks(sd)
                return {
                    "q": jnp.zeros(lead + (nb, self._block_size(sd)),
                                   jnp.int8),
                    "scale": jnp.zeros(lead + (nb, 1), jnp.float32),
                }
            return jnp.zeros((self.num_slots,) + tuple(sd.shape),
                             sd.dtype)

        return jax.tree_util.tree_map_with_path(leaf, self.template)

    def host_zero_row(self):
        """Host numpy zero row in MODEL layout (one slot, no leading
        axis, full-precision K/V even in int8 mode) — the
        prefix-cache's seed template: cached entries are RAW rows (a
        hit's suffix forward must attend over exactly the
        full-precision prefix K/V a cold prefill computed — seeding
        dequantized int8 would perturb every suffix K/V), and a miss
        seeds from these zeros (``cache_index`` 0 masks every
        position, so the content is never attended)."""
        # sd.dtype is numpy-compatible (ml_dtypes registers bf16)
        return jax.tree_util.tree_map(
            lambda sd: np.zeros(tuple(sd.shape), sd.dtype),
            self.template)

    # -- bytes accounting --------------------------------------------------

    def _leaf_bytes(self, sd, *, kv_itemsize=None):
        if kv_itemsize is None:
            kv_itemsize = jnp.dtype(sd.dtype).itemsize
        return int(np.prod(sd.shape)) * kv_itemsize

    def bytes_per_slot(self, *, kv_itemsize=None):
        """Device bytes one slot occupies. ``kv_itemsize`` overrides
        the K/V element width (e.g. 4 for the fp32-equivalent model in
        docs/serving.md); int8 mode counts 1 byte per lane PLUS the
        fp32 scale per ``block_size`` lanes — the honest,
        scale-inclusive figure."""
        total = 0
        for names, sd in self._by_path.items():
            if _is_kv(names):
                if self.mode == "int8" and kv_itemsize is None:
                    positions = int(np.prod(sd.shape[:-3]))
                    nb = self._num_blocks(sd)
                    total += positions * nb * (self._block_size(sd) + 4)
                else:
                    total += self._leaf_bytes(sd, kv_itemsize=kv_itemsize)
            else:
                total += self._leaf_bytes(sd)
        return total

    def total_bytes(self, **kw):
        return self.num_slots * self.bytes_per_slot(**kw)

    def cache_dtype_name(self):
        if self.mode == "int8":
            return "int8"
        for names, sd in self._by_path.items():
            if _is_kv(names):
                return jnp.dtype(sd.dtype).name
        return "bf16"

    # -- store <-> model-row conversion (pure, in-graph) -------------------

    def materialize_rows(self, rows):
        """Quantized store rows -> the model-ready cache tree (K/V at
        the template dtype, dequantized on read). Identity in bf16
        mode. Works on a gathered bucket ``[B, ...]`` or a single row
        alike (shapes come from the leading dims of ``q``)."""
        if self.mode != "int8":
            return rows

        def fix(path, leaf):
            if not (isinstance(leaf, dict) and "q" in leaf):
                return leaf
            sd = self._by_path[_names(path)]
            n = self._kv_feature_width(sd)
            out = compression.dequantize_rows_blockwise(
                leaf["q"], leaf["scale"], n=n)
            return out.reshape(leaf["q"].shape[:-2] + tuple(sd.shape[-3:])
                               ).astype(sd.dtype)

        return jax.tree_util.tree_map_with_path(
            fix, rows,
            is_leaf=lambda l: isinstance(l, dict) and "q" in l)

    def quantize_rows(self, rows):
        """Model-ready cache rows -> store layout (full-row quantize).
        Only correct for FRESH rows (admission prefill): every position
        gets new scales, so calling this on a row holding previously
        quantized content would re-quantize it against a drifted grid —
        the decode hot path uses :meth:`update_rows_at` instead."""
        if self.mode != "int8":
            return rows

        def fix(path, leaf):
            if not _is_kv(_names(path)):
                return leaf
            lead = leaf.shape[:-3]
            q, s = compression.quantize_rows_blockwise(
                leaf.reshape(lead + (-1,)),
                self._block_size(self._by_path[_names(path)]))
            return {"q": q, "scale": s}

        return jax.tree_util.tree_map_with_path(fix, rows)

    def update_rows_at(self, store_rows, new_rows, positions):
        """Merge one decode step's K/V append back into quantized rows.

        ``store_rows`` is the gathered (still-quantized) bucket,
        ``new_rows`` the model-ready rows after the decode forward
        (each row's K/V updated at its own ``positions[i]``), and only
        that single position is (re)quantized per row — every other
        block's int8 payload and scale pass through bit-identical, the
        no-drift invariant the parity test pins. bf16 mode returns
        ``new_rows`` unchanged."""
        if self.mode != "int8":
            return new_rows
        flat_store, treedef = jax.tree_util.tree_flatten_with_path(
            store_rows,
            is_leaf=lambda l: isinstance(l, dict) and "q" in l)
        new_by_path = {
            _names(p): leaf for p, leaf in
            jax.tree_util.tree_flatten_with_path(new_rows)[0]}
        b = positions.shape[0]
        out = []
        for path, leaf in flat_store:
            names = _names(path)
            if not (isinstance(leaf, dict) and "q" in leaf):
                out.append(new_by_path[names])
                continue
            sd = self._by_path[names]
            x = new_by_path[names]                       # [B, *mid, T,1,g,d]
            flat = x.reshape(x.shape[:-3] + (-1,))       # [B, *mid, T, F]
            idx = positions.reshape((b,) + (1,) * (flat.ndim - 1))
            sel = jnp.take_along_axis(flat, idx, axis=-2)  # [B, *mid, 1, F]
            q_new, s_new = compression.quantize_rows_blockwise(
                sel, self._block_size(sd))               # [B,*mid,1,nb,*]
            q_old, s_old = leaf["q"], leaf["scale"]
            t = q_old.shape[-3]
            mask = (jnp.arange(t).reshape((t, 1, 1))
                    == positions.reshape((b,) + (1,) * (q_old.ndim - 1)))
            out.append({
                "q": jnp.where(mask, q_new, q_old),
                "scale": jnp.where(mask, s_new, s_old),
            })
        return jax.tree_util.tree_unflatten(treedef, out)

    def update_rows_span(self, store_rows, new_rows, start, span):
        """Merge a ``span``-position K/V append back into quantized
        rows — the multi-position sibling of :meth:`update_rows_at`.

        ``start`` is ``[B] i32`` (each row's first written position),
        ``span`` a STATIC int: positions ``[start[i], start[i] +
        span)`` are (re)quantized per row, every other block's int8
        payload and scale pass through bit-identical — the same
        no-drift invariant, widened for the speculative-decode window
        (one draft-k round appends ``k + 1`` positions) and the
        prefix-cache suffix prefill (a seeded slot re-quantizes only
        its suffix bucket; the inherited prefix blocks copy
        bit-identically). ``span == 1`` degenerates to
        :meth:`update_rows_at`. bf16 mode returns ``new_rows``
        unchanged."""
        if self.mode != "int8":
            return new_rows
        span = int(span)
        # quantize every position of the fresh rows (the positions axis
        # is preserved, so each position's blocks are independent), then
        # select per position: inside the span the fresh blocks land,
        # outside the OLD int8 payload + scale pass through bit-exactly
        # — the jnp.where never touches their bits. The extra quantize
        # work outside the span is discarded by the select; the span
        # paths (speculative window, suffix prefill) are not the
        # per-token hot loop, which keeps update_rows_at's 1-position
        # form.
        fresh = self.quantize_rows(new_rows)
        flat_store, treedef = jax.tree_util.tree_flatten_with_path(
            store_rows,
            is_leaf=lambda l: isinstance(l, dict) and "q" in l)
        fresh_by_path = {
            _names(p): leaf for p, leaf in
            jax.tree_util.tree_flatten_with_path(
                fresh,
                is_leaf=lambda l: isinstance(l, dict) and "q" in l)[0]}
        b = start.shape[0]
        out = []
        for path, leaf in flat_store:
            names = _names(path)
            new_leaf = fresh_by_path[names]
            if not (isinstance(leaf, dict) and "q" in leaf):
                out.append(new_leaf)
                continue
            q_old, s_old = leaf["q"], leaf["scale"]
            t = q_old.shape[-3]
            pos = jnp.arange(t).reshape((t, 1, 1))
            lo = start.reshape((b,) + (1,) * (q_old.ndim - 1))
            mask = (pos >= lo) & (pos < lo + span)
            out.append({
                "q": jnp.where(mask, new_leaf["q"], q_old),
                "scale": jnp.where(mask, new_leaf["scale"], s_old),
            })
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- per-block parity bound --------------------------------------------

    def quantization_bound(self, kv_absmax):
        """Worst-case per-lane dequantization error for a block whose
        absmax is ``kv_absmax``: half a grid step, ``absmax / 254``
        (the symmetric int8 grid spans [-127, 127]). The documented
        bound the int8-vs-bf16 decode parity test holds per read."""
        return float(kv_absmax) / (2.0 * 127.0)
