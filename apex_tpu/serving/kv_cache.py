"""Slotted KV-cache store for continuous-batching decode.

The serving cache is an **explicit pytree**, not a flax variable
collection: one *slot* per concurrent sequence, preallocated for the
model's full ``max_position_embeddings`` window, with every leaf
carrying a leading ``[num_slots]`` axis. The layout is derived from the
model itself (``jax.eval_shape`` of its ``decode=True`` init — no
parameters materialize), so any architecture the incremental-decode
path supports (MHA/GQA, rope/learned positions, ``scan_layers``) gets
a correct store for free.

Why slots: continuous batching admits and evicts *individual*
sequences while the decode step keeps one static shape. The engine
gathers a bucket of slot rows, runs the model's own decode attention
per row (each slot carries its own scalar ``cache_index``, so mixed
sequence lengths coexist), and scatters the rows back — admission is a
prefill-scatter into free slots, eviction is just forgetting a slot id.

Sharding: every leaf's leading axis is the slot axis, so on a 1-D data
mesh one ``NamedSharding(mesh, P(data_axis))`` spreads the store —
byte-for-byte the dominant HBM cost of serving — across the data axis.
On a 2-D ``(data, model)`` mesh (:meth:`KVCacheSpec.store_pspecs`) the
K/V leaves shard over the *model* axis on the head dimension instead:
the spec is built under ``parallel_state`` tp=m, so its template is the
LOCAL (``groups/m``) layout, and the global store is the rank shards
concatenated in head order (bf16: the groups axis; int8: the blocks
axis — per-rank block grids, so the blockwise quantization stays
rank-local and collective-free). Slots replicate across ``data`` in
that mode (the fleet gives a TP replica its own ``(1, m)`` slice).

Migration (:meth:`KVCacheSpec.consolidate_host_rows` +
:func:`payload_checksum`): one slot's host-fetched store rows
consolidate into canonical RAW model-layout rows — per-rank int8
blocks dequantize and the head shards concatenate, mirroring the
consolidate half of ``reshard_zero_state_2d`` — so a survivor of ANY
tp size re-slices the same canonical payload through its own prefill
``in_specs`` (the reshard half). The crc32 checksum over the canonical
leaves is what the fleet verifies before seeding; a mismatch falls
back loudly to token re-prefill.

int8 mode (``mode="int8"``): K/V leaves are stored as blockwise
symmetric int8 with fp32 scales per ``block_size``-lane block —
``parallel.compression``'s gradient-collective scheme pointed at the
cache (EQuARX-adjacent: the quantized-block layout stays collective-
friendly). Each cache *position* quantizes independently
(:func:`~apex_tpu.parallel.compression.quantize_rows_blockwise` over
the flattened ``[groups * head_dim]`` feature lanes), so appending one
token's K/V never re-quantizes — and never drifts — previously written
positions. Reads dequantize on the fly inside the compiled decode step
(:meth:`KVCacheSpec.materialize_rows`); the error per lane is bounded
by half a quantization step, ``absmax_block / 254`` — the same
per-block bound the compression tests pin, inherited verbatim here
(tests/L0/test_serving.py holds a 64-token decode to it).
"""

import zlib

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.parallel import compression

# flax decode-cache leaf naming (transformer_lm._decode_attention):
# cached_key / cached_value hold K/V, cache_index the scalar fill level.
KV_LEAF_PREFIX = "cached_"
CACHE_INDEX = "cache_index"

CACHE_MODES = ("bf16", "int8")


def _names(path):
    return tuple(str(getattr(e, "key", getattr(e, "idx", e)))
                 for e in path)


def _is_kv(names):
    return bool(names) and names[-1].startswith(KV_LEAF_PREFIX)


def row_template(model, token_dtype=jnp.int32):
    """ShapeDtypeStruct pytree of ONE slot's cache (batch 1) for a
    ``decode=True`` model — a shape-only trace, no params materialize
    (the serving sibling of ``generation.init_cache``)."""
    dummy = jnp.zeros((1, 1), token_dtype)
    return jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), dummy))["cache"]


def zero_row(template):
    """Concrete zeroed cache row from a :func:`row_template` tree
    (trace-friendly: the serving prefill builds fresh rows in-graph)."""
    return jax.tree_util.tree_map(
        lambda sd: jnp.zeros(sd.shape, sd.dtype), template)


def payload_checksum(tree, crc=0):
    """crc32 over every leaf of a host pytree, in flatten order — the
    migration payload's integrity check (same zlib.crc32 convention as
    ``apex_tpu.checkpoint``). Chainable: pass a previous checksum as
    ``crc`` to fold several trees (target + draft rows) into one."""
    for leaf in jax.tree_util.tree_leaves(tree):
        crc = zlib.crc32(
            np.ascontiguousarray(np.asarray(leaf)).tobytes(), crc)
    return int(crc)


def store_lengths(store):
    """Per-slot fill level ``[num_slots] i32`` from the first
    ``cache_index`` leaf (all layers agree — the engine keeps them in
    lockstep, like ``generation._set_cache_index``)."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(store)[0]:
        names = _names(path)
        if names and names[-1] == CACHE_INDEX:
            return leaf.reshape(leaf.shape[0], -1)[:, 0]
    raise ValueError("store has no cache_index leaf — not a decode "
                     "cache pytree")


class KVCacheSpec:
    """Host-side layout descriptor + the pure in-graph conversion
    helpers between the slotted store and model-ready cache rows.

    Everything here is trace-friendly (pure jnp): the engine calls
    these inside its AOT-compiled prefill/decode steps. The spec itself
    holds only shapes and static config — it never owns device memory
    (the engine owns the store array it allocates here).
    """

    def __init__(self, model, num_slots, *, mode="bf16",
                 block_size=compression.BLOCK_SIZE,
                 token_dtype=jnp.int32):
        if mode not in CACHE_MODES:
            raise ValueError(f"cache mode {mode!r} not in {CACHE_MODES}")
        if num_slots < 1:
            raise ValueError(f"num_slots ({num_slots}) must be >= 1")
        self.model = model
        self.num_slots = int(num_slots)
        self.mode = mode
        self.block_size = int(block_size)
        self.template = row_template(model, token_dtype)
        # path -> template ShapeDtypeStruct, for shape/dtype recovery
        # when materializing quantized leaves
        self._by_path = {
            _names(p): sd for p, sd in
            jax.tree_util.tree_flatten_with_path(self.template)[0]}

    # -- layout ------------------------------------------------------------

    def _kv_feature_width(self, sd):
        """Lanes per cache position: the trailing (batch=1, groups,
        head_dim) axes flattened — the blockwise quantization row."""
        return int(np.prod(sd.shape[-3:]))

    def _block_size(self, sd):
        """Effective block for this leaf: the configured 256-lane grid,
        clamped to the feature width — a model whose per-position K/V
        row is narrower than one block would otherwise store zero-
        padded lanes at full price (observed 2x blowup on toy heads)."""
        return min(self.block_size, self._kv_feature_width(sd))

    def _num_blocks(self, sd):
        return compression.num_blocks(self._kv_feature_width(sd),
                                      self._block_size(sd))

    def allocate(self):
        """Zeroed slotted store: every template leaf stacked to
        ``[num_slots, ...]``; in int8 mode K/V leaves become
        ``{"q": int8 [..., nb, block], "scale": f32 [..., nb, 1]}``
        subtrees (positions axis preserved, feature lanes blocked)."""
        def leaf(path, sd):
            names = _names(path)
            if self.mode == "int8" and _is_kv(names):
                lead = (self.num_slots,) + tuple(sd.shape[:-3])
                nb = self._num_blocks(sd)
                return {
                    "q": jnp.zeros(lead + (nb, self._block_size(sd)),
                                   jnp.int8),
                    "scale": jnp.zeros(lead + (nb, 1), jnp.float32),
                }
            return jnp.zeros((self.num_slots,) + tuple(sd.shape),
                             sd.dtype)

        return jax.tree_util.tree_map_with_path(leaf, self.template)

    def host_zero_row(self, tp=1):
        """Host numpy zero row in MODEL layout (one slot, no leading
        axis, full-precision K/V even in int8 mode) — the
        prefix-cache's seed template: cached entries are RAW rows (a
        hit's suffix forward must attend over exactly the
        full-precision prefix K/V a cold prefill computed — seeding
        dequantized int8 would perturb every suffix K/V), and a miss
        seeds from these zeros (``cache_index`` 0 masks every
        position, so the content is never attended).

        ``tp > 1`` returns the CANONICAL (cross-rank) layout for a
        tensor-parallel engine: the local template's groups axis scaled
        by ``tp`` — the wire format the fleet-wide prefix store and
        the migration payload both speak, so engines of different tp
        sizes seed from the same entries (each re-slices through its
        prefill ``in_specs``)."""
        # sd.dtype is numpy-compatible (ml_dtypes registers bf16)
        return jax.tree_util.tree_map_with_path(
            lambda p, sd: np.zeros(
                self._canonical_shape(p, sd, tp), sd.dtype),
            self.template)

    def _canonical_shape(self, path, sd, tp):
        """One template leaf's cross-rank shape: K/V leaves scale the
        groups axis (-2) by ``tp``; everything else is rank-replicated
        (``cache_index`` scalars agree across ranks)."""
        shape = tuple(sd.shape)
        if int(tp) > 1 and _is_kv(_names(path)):
            shape = shape[:-2] + (shape[-2] * int(tp),) + shape[-1:]
        return shape

    def store_pspecs(self, data_axis="data", model_axis=None):
        """Per-leaf ``PartitionSpec`` tree for the slotted store.

        Without ``model_axis`` this is the classic 1-D design: every
        leaf shards its leading slot axis over ``data_axis``. With
        ``model_axis`` set (tensor-parallel serving) the K/V leaves
        shard over the model axis on the head dimension — the groups
        axis in bf16 mode, the blocks axis in int8 mode (both axis -2
        of the store leaf, so per-rank block grids stay rank-local) —
        and every other leaf (``cache_index``, slots) replicates: the
        fleet gives each TP replica a ``(data=1, model=m)`` slice, so
        global slot ids gather locally on every rank."""
        from jax.sharding import PartitionSpec as P

        def leaf(path, sd):
            names = _names(path)
            if model_axis is None:
                spec = P(data_axis)
                if self.mode == "int8" and _is_kv(names):
                    return {"q": spec, "scale": spec}
                return spec
            if not _is_kv(names):
                return P()
            if self.mode == "int8":
                # q: [slots, *mid, T, nb, block]; scale shares nb at -2
                nd = 1 + len(sd.shape[:-3]) + 2
                spec = P(*((None,) * (nd - 2) + (model_axis,)))
                return {"q": spec, "scale": spec}
            # bf16: [slots, *sd.shape]; groups axis at -2
            nd = 1 + len(sd.shape)
            return P(*((None,) * (nd - 2) + (model_axis,)))

        return jax.tree_util.tree_map_with_path(leaf, self.template)

    def row_pspecs(self, model_axis, lead=1):
        """``PartitionSpec`` tree for RAW model-layout rows with
        ``lead`` extra leading axes (the batch-stacked seed/raw rows a
        tensor-parallel prefill moves): K/V leaves shard their groups
        axis over ``model_axis``, everything else replicates — the
        in/out_specs that re-slice a canonical row into rank shards
        (and reassemble the raw outputs into canonical host rows)."""
        from jax.sharding import PartitionSpec as P

        def leaf(path, sd):
            if not _is_kv(_names(path)):
                return P()
            nd = int(lead) + len(sd.shape)
            return P(*((None,) * (nd - 2) + (model_axis,)))

        return jax.tree_util.tree_map_with_path(leaf, self.template)

    def host_global_store(self, tp=1):
        """Host numpy zeroed GLOBAL store for a ``tp``-way engine:
        :meth:`allocate`'s layout with every K/V leaf's sharded axis
        (groups in bf16, blocks in int8) scaled by ``tp``. Zeros are
        rank-independent, so one ``device_put`` against
        :meth:`store_pspecs` places it with no traced allocation (an
        in-graph per-rank allocate would register a compile outside
        the AOT ladder)."""
        tp = int(tp)

        def leaf(path, sd):
            names = _names(path)
            if self.mode == "int8" and _is_kv(names):
                lead = (self.num_slots,) + tuple(sd.shape[:-3])
                nb = self._num_blocks(sd) * tp
                return {
                    "q": np.zeros(lead + (nb, self._block_size(sd)),
                                  np.int8),
                    "scale": np.zeros(lead + (nb, 1), np.float32),
                }
            shape = self._canonical_shape(path, sd, tp)
            return np.zeros((self.num_slots,) + shape, sd.dtype)

        return jax.tree_util.tree_map_with_path(leaf, self.template)

    def consolidate_host_rows(self, rows, tp=1):
        """Host-side consolidation of one slot's device-fetched STORE
        rows into canonical RAW model-layout rows — the migration
        payload's wire format (and the fleet-wide prefix store's entry
        layout). Mirrors the consolidate half of
        ``reshard_zero_state_2d``: per-rank int8 blocks dequantize
        against their own scales and trim their own zero-pad, then the
        head shards concatenate in rank order; bf16 shards are already
        head-concatenated by the global view, so consolidation is a
        dtype-checked pass-through. The reshard half is the survivor's
        prefill ``in_specs`` (:meth:`row_pspecs`), which re-slice the
        canonical rows for ANY tp size whose head count divides.

        Raises ``ValueError`` on any leaf whose shape or dtype does
        not match this spec's ``tp``-scaled layout — the incompatible-
        layout signal the fleet turns into a LOUD re-prefill fallback,
        never a silently mis-seeded slot."""
        tp = int(tp)

        def fix(path, leaf):
            names = _names(path)
            sd = self._by_path.get(names)
            if sd is None:
                raise ValueError(
                    f"kv payload leaf {names!r} is not in this engine's "
                    f"cache layout")
            if self.mode == "int8" and _is_kv(names):
                if not (isinstance(leaf, dict) and "q" in leaf):
                    raise ValueError(
                        f"kv payload leaf {names!r}: expected an int8 "
                        f"q/scale subtree, got {type(leaf).__name__}")
                q = np.asarray(leaf["q"])
                s = np.asarray(leaf["scale"], np.float32)
                nb = self._num_blocks(sd)
                block = self._block_size(sd)
                lead = tuple(sd.shape[:-3])
                if q.shape != lead + (tp * nb, block) or q.dtype != np.int8:
                    raise ValueError(
                        f"kv payload leaf {names!r}: int8 blocks "
                        f"{q.shape}/{q.dtype} do not match the "
                        f"tp={tp} layout {lead + (tp * nb, block)}")
                width = self._kv_feature_width(sd)
                deq = (q.astype(np.float32)
                       * s.reshape(lead + (tp, nb, 1)).astype(np.float32)
                       .reshape(lead + (tp * nb, 1)))
                deq = deq.reshape(lead + (tp, nb * block))[..., :width]
                # local flattened lanes -> (1, g_local, hd), ranks
                # concatenated on the groups axis in head order
                deq = deq.reshape(lead + (tp,) + tuple(sd.shape[-3:]))
                deq = np.moveaxis(deq, len(lead), len(lead) + 1)
                out = deq.reshape(
                    lead + self._canonical_shape(path, sd, tp)[-3:])
                return out.astype(sd.dtype)
            want = self._canonical_shape(path, sd, tp) if _is_kv(names) \
                else tuple(sd.shape)
            arr = np.asarray(leaf)
            if arr.shape != want or arr.dtype != np.dtype(sd.dtype):
                raise ValueError(
                    f"kv payload leaf {names!r}: {arr.shape}/{arr.dtype} "
                    f"does not match the tp={tp} canonical layout "
                    f"{want}/{np.dtype(sd.dtype)}")
            return np.copy(arr)

        return jax.tree_util.tree_map_with_path(
            fix, rows,
            is_leaf=lambda l: isinstance(l, dict) and "q" in l)

    # -- bytes accounting --------------------------------------------------

    def _leaf_bytes(self, sd, *, kv_itemsize=None):
        if kv_itemsize is None:
            kv_itemsize = jnp.dtype(sd.dtype).itemsize
        return int(np.prod(sd.shape)) * kv_itemsize

    def bytes_per_slot(self, *, kv_itemsize=None):
        """Device bytes one slot occupies. ``kv_itemsize`` overrides
        the K/V element width (e.g. 4 for the fp32-equivalent model in
        docs/serving.md); int8 mode counts 1 byte per lane PLUS the
        fp32 scale per ``block_size`` lanes — the honest,
        scale-inclusive figure."""
        total = 0
        for names, sd in self._by_path.items():
            if _is_kv(names):
                if self.mode == "int8" and kv_itemsize is None:
                    positions = int(np.prod(sd.shape[:-3]))
                    nb = self._num_blocks(sd)
                    total += positions * nb * (self._block_size(sd) + 4)
                else:
                    total += self._leaf_bytes(sd, kv_itemsize=kv_itemsize)
            else:
                total += self._leaf_bytes(sd)
        return total

    def total_bytes(self, **kw):
        return self.num_slots * self.bytes_per_slot(**kw)

    def cache_dtype_name(self):
        if self.mode == "int8":
            return "int8"
        for names, sd in self._by_path.items():
            if _is_kv(names):
                return jnp.dtype(sd.dtype).name
        return "bf16"

    # -- store <-> model-row conversion (pure, in-graph) -------------------

    def materialize_rows(self, rows):
        """Quantized store rows -> the model-ready cache tree (K/V at
        the template dtype, dequantized on read). Identity in bf16
        mode. Works on a gathered bucket ``[B, ...]`` or a single row
        alike (shapes come from the leading dims of ``q``)."""
        if self.mode != "int8":
            return rows

        def fix(path, leaf):
            if not (isinstance(leaf, dict) and "q" in leaf):
                return leaf
            sd = self._by_path[_names(path)]
            n = self._kv_feature_width(sd)
            out = compression.dequantize_rows_blockwise(
                leaf["q"], leaf["scale"], n=n)
            return out.reshape(leaf["q"].shape[:-2] + tuple(sd.shape[-3:])
                               ).astype(sd.dtype)

        return jax.tree_util.tree_map_with_path(
            fix, rows,
            is_leaf=lambda l: isinstance(l, dict) and "q" in l)

    def quantize_rows(self, rows):
        """Model-ready cache rows -> store layout (full-row quantize).
        Only correct for FRESH rows (admission prefill): every position
        gets new scales, so calling this on a row holding previously
        quantized content would re-quantize it against a drifted grid —
        the decode hot path uses :meth:`update_rows_at` instead."""
        if self.mode != "int8":
            return rows

        def fix(path, leaf):
            if not _is_kv(_names(path)):
                return leaf
            lead = leaf.shape[:-3]
            q, s = compression.quantize_rows_blockwise(
                leaf.reshape(lead + (-1,)),
                self._block_size(self._by_path[_names(path)]))
            return {"q": q, "scale": s}

        return jax.tree_util.tree_map_with_path(fix, rows)

    def update_rows_at(self, store_rows, new_rows, positions):
        """Merge one decode step's K/V append back into quantized rows.

        ``store_rows`` is the gathered (still-quantized) bucket,
        ``new_rows`` the model-ready rows after the decode forward
        (each row's K/V updated at its own ``positions[i]``), and only
        that single position is (re)quantized per row — every other
        block's int8 payload and scale pass through bit-identical, the
        no-drift invariant the parity test pins. bf16 mode returns
        ``new_rows`` unchanged."""
        if self.mode != "int8":
            return new_rows
        flat_store, treedef = jax.tree_util.tree_flatten_with_path(
            store_rows,
            is_leaf=lambda l: isinstance(l, dict) and "q" in l)
        new_by_path = {
            _names(p): leaf for p, leaf in
            jax.tree_util.tree_flatten_with_path(new_rows)[0]}
        b = positions.shape[0]
        out = []
        for path, leaf in flat_store:
            names = _names(path)
            if not (isinstance(leaf, dict) and "q" in leaf):
                out.append(new_by_path[names])
                continue
            sd = self._by_path[names]
            x = new_by_path[names]                       # [B, *mid, T,1,g,d]
            flat = x.reshape(x.shape[:-3] + (-1,))       # [B, *mid, T, F]
            idx = positions.reshape((b,) + (1,) * (flat.ndim - 1))
            sel = jnp.take_along_axis(flat, idx, axis=-2)  # [B, *mid, 1, F]
            q_new, s_new = compression.quantize_rows_blockwise(
                sel, self._block_size(sd))               # [B,*mid,1,nb,*]
            q_old, s_old = leaf["q"], leaf["scale"]
            t = q_old.shape[-3]
            mask = (jnp.arange(t).reshape((t, 1, 1))
                    == positions.reshape((b,) + (1,) * (q_old.ndim - 1)))
            out.append({
                "q": jnp.where(mask, q_new, q_old),
                "scale": jnp.where(mask, s_new, s_old),
            })
        return jax.tree_util.tree_unflatten(treedef, out)

    def update_rows_span(self, store_rows, new_rows, start, span):
        """Merge a ``span``-position K/V append back into quantized
        rows — the multi-position sibling of :meth:`update_rows_at`.

        ``start`` is ``[B] i32`` (each row's first written position),
        ``span`` a STATIC int: positions ``[start[i], start[i] +
        span)`` are (re)quantized per row, every other block's int8
        payload and scale pass through bit-identical — the same
        no-drift invariant, widened for the speculative-decode window
        (one draft-k round appends ``k + 1`` positions) and the
        prefix-cache suffix prefill (a seeded slot re-quantizes only
        its suffix bucket; the inherited prefix blocks copy
        bit-identically). ``span == 1`` degenerates to
        :meth:`update_rows_at`. bf16 mode returns ``new_rows``
        unchanged."""
        if self.mode != "int8":
            return new_rows
        span = int(span)
        # quantize every position of the fresh rows (the positions axis
        # is preserved, so each position's blocks are independent), then
        # select per position: inside the span the fresh blocks land,
        # outside the OLD int8 payload + scale pass through bit-exactly
        # — the jnp.where never touches their bits. The extra quantize
        # work outside the span is discarded by the select; the span
        # paths (speculative window, suffix prefill) are not the
        # per-token hot loop, which keeps update_rows_at's 1-position
        # form.
        fresh = self.quantize_rows(new_rows)
        flat_store, treedef = jax.tree_util.tree_flatten_with_path(
            store_rows,
            is_leaf=lambda l: isinstance(l, dict) and "q" in l)
        fresh_by_path = {
            _names(p): leaf for p, leaf in
            jax.tree_util.tree_flatten_with_path(
                fresh,
                is_leaf=lambda l: isinstance(l, dict) and "q" in l)[0]}
        b = start.shape[0]
        out = []
        for path, leaf in flat_store:
            names = _names(path)
            new_leaf = fresh_by_path[names]
            if not (isinstance(leaf, dict) and "q" in leaf):
                out.append(new_leaf)
                continue
            q_old, s_old = leaf["q"], leaf["scale"]
            t = q_old.shape[-3]
            pos = jnp.arange(t).reshape((t, 1, 1))
            lo = start.reshape((b,) + (1,) * (q_old.ndim - 1))
            mask = (pos >= lo) & (pos < lo + span)
            out.append({
                "q": jnp.where(mask, new_leaf["q"], q_old),
                "scale": jnp.where(mask, new_leaf["scale"], s_old),
            })
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- per-block parity bound --------------------------------------------

    def quantization_bound(self, kv_absmax):
        """Worst-case per-lane dequantization error for a block whose
        absmax is ``kv_absmax``: half a grid step, ``absmax / 254``
        (the symmetric int8 grid spans [-127, 127]). The documented
        bound the int8-vs-bf16 decode parity test holds per read."""
        return float(kv_absmax) / (2.0 * 127.0)
