"""ServeEngine — AOT-compiled, bucketed, continuous-batching decode.

The forward-only production path the ROADMAP's open item 3 asks for.
Shape discipline is the whole design: at startup the engine
ahead-of-time compiles (``jax.jit(...).lower(...).compile()``) exactly
ONE prefill executable per (batch-bucket, seq-bucket) pair and ONE
decode executable per batch-bucket, registers every compile with the
:class:`~apex_tpu.telemetry.compile_watch.CompileWatcher`, and from
then on steady-state traffic — whatever its arrival pattern — only
ever *calls* those executables. ``assert_no_recompiles`` around the
serving loop is therefore a hard invariant, not a hope: the compile
count equals the bucket-ladder size and stays flat as traffic varies
(the compile watcher was built for exactly this; see
docs/observability.md).

The decode step reuses the model's own incremental-decode semantics:
``generation.prefill`` / ``generation.decode_step`` vmapped over cache
slots, each slot carrying its own ``cache_index`` so mixed sequence
lengths coexist in one batch (greedy output is token-identical to
``generation.generate`` for the bf16 cache — pinned in
tests/L0/test_serving.py). The KV cache is the slotted store of
:mod:`apex_tpu.serving.kv_cache`: sharded over the data axis,
optionally int8-quantized with dequant-on-read inside the compiled
step.

Resource discipline mirrors the training substrate: cache preallocation
(the dominant HBM cost) runs under ``telemetry.memory.oom_guard``, the
decode step's budget is preflighted before any traffic, and every
decode dispatch goes through ``resilience.guarded_call`` so a real (or
injected) RESOURCE_EXHAUSTED writes a memory post-mortem instead of a
bare traceback. See docs/serving.md for the operational tour.
"""

import dataclasses
import time
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models import generation
from apex_tpu.parallel import compression
from apex_tpu.serving import kv_cache as kvc
from apex_tpu.serving.prefix_cache import PrefixStore
from apex_tpu.telemetry import compile_watch
from apex_tpu.telemetry import memory as tmemory
from apex_tpu.telemetry.registry import get_registry


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static serving knobs — everything that shapes an executable.

    ``batch_buckets`` is the decode ladder (active sequences pad up to
    the smallest bucket that fits); ``prefill_buckets`` the prompt-
    length ladder (prompts right-pad up to a bucket, the pad positions
    stay masked by the cache's absolute-position attention). The AOT
    compile count is ``len(batch_buckets) * len(prefill_buckets) +
    len(batch_buckets)`` — fixed at startup, flat under any traffic,
    and UNCHANGED by the two serving multipliers below (each swaps an
    executable's body, never grows the ladder).

    ``draft_model`` (+ ``draft_params``) turns every decode dispatch
    into one speculative round: the draft proposes
    ``num_draft_tokens`` greedily, the target verifies the whole
    window in ONE chunked forward with a fused in-graph sampling /
    acceptance / rollback epilogue (no host round-trip between draft
    and verify), and each slot emits its own accepted prefix plus one
    target token — greedy-only (``temperature`` must stay 0.0; the
    token-exactness contract of ``speculative_generate``).

    ``prefix_cache`` keeps a per-engine host-side
    :class:`~apex_tpu.serving.prefix_cache.PrefixStore`: a prompt
    whose prefix was prefilled before seeds its slot's KV rows from
    the cached copy and prefills only the suffix bucket.
    """

    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    prefill_buckets: Tuple[int, ...] = (16, 32, 64)
    num_slots: int = 8
    cache_mode: str = "bf16"            # "bf16" | "int8"
    block_size: int = compression.BLOCK_SIZE
    temperature: float = 0.0            # 0 = greedy
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    data_axis: str = "data"             # mesh axis the slot dim shards over
    # mesh axis the model shards over in tensor-parallel serving. Must
    # stay "tp": GPTModel hardwires its collectives to axis name "tp",
    # and an unbound axis makes those psums silently vanish (axis size
    # 1) — wrong results, not an error — so the engine validates the
    # name loudly instead of accepting an alias.
    model_axis: str = "tp"
    donate: bool = True                 # donate the store through the step
    preflight: bool = True
    preflight_strict: bool = False
    # speculative decode (None = plain one-token decode)
    draft_model: Any = dataclasses.field(default=None, repr=False,
                                         compare=False)
    draft_params: Any = dataclasses.field(default=None, repr=False,
                                          compare=False)
    num_draft_tokens: int = 4
    # cross-request prefix cache (host-side, per engine/replica)
    prefix_cache: bool = False
    prefix_min_len: int = 4
    prefix_max_entries: int = 8
    # route the k+1-position verify attention through the fused
    # flash-window kernel (kernels/fused_cc.window_attention) when the
    # fused_cc gate is live; False pins the einsum formulation for
    # this engine's traced executables regardless of the gate
    fused_verify: bool = True


def kv_payload_crc(payload):
    """Recompute a migration payload's checksum from its contents —
    the verification side of :meth:`ServeEngine.extract_kv_state`.
    Folds the target rows, the draft rows (when present), and the
    fill length into one crc32; any flipped byte anywhere in the
    pytree (or a tampered length) changes the result."""
    crc = kvc.payload_checksum(payload["rows"])
    if payload.get("draft_rows") is not None:
        crc = kvc.payload_checksum(payload["draft_rows"], crc)
    return kvc.payload_checksum(
        [np.asarray(int(payload["length"]), np.int64)], crc)


class ServeEngine:
    """AOT-compiled prefill/decode over a slotted KV cache.

    The engine owns the device store and the compiled executables; it
    is deliberately ignorant of *requests* — admission, eviction, and
    latency accounting live in
    :class:`~apex_tpu.serving.scheduler.Scheduler` (which
    :meth:`serve` constructs for the common case). ``slot_ids`` in the
    host API are plain Python ints; padding a bucket uses caller-
    provided FREE slots (distinct ids — a duplicate scatter would
    collide), which the scheduler always has by construction.
    """

    def __init__(self, model, params, config: ServeConfig = None, *,
                 mesh=None, watcher=None, registry=None, name=None):
        from apex_tpu.transformer.parallel_state import (
            get_tensor_model_parallel_world_size,
        )

        tp = get_tensor_model_parallel_world_size()
        self._tp = int(tp)
        if not getattr(model, "decode", False):
            raise ValueError("ServeEngine needs a model built with "
                             "decode=True")
        config = config or ServeConfig()
        if not config.batch_buckets or not config.prefill_buckets:
            raise ValueError("empty bucket ladder")
        bb = tuple(sorted(set(int(b) for b in config.batch_buckets)))
        sb = tuple(sorted(set(int(s) for s in config.prefill_buckets)))
        if bb[-1] > config.num_slots:
            raise ValueError(
                f"largest batch bucket ({bb[-1]}) exceeds num_slots "
                f"({config.num_slots}) — a bucket gathers distinct slots")
        limit = model.config.max_position_embeddings
        if sb[-1] > limit:
            raise ValueError(
                f"largest prefill bucket ({sb[-1]}) exceeds "
                f"max_position_embeddings ({limit})")
        if tp > 1:
            # tensor-parallel serving: the model was built under
            # parallel_state tp=m, so its cache template is the LOCAL
            # per-rank layout and its collectives name axis "tp" — the
            # engine's job is to give that axis a mesh to live on and
            # shard the store's head dimension over it.
            if mesh is None:
                raise ValueError(
                    f"tensor parallel serving (tp={tp}) needs a (data, "
                    f"model) mesh — pass mesh=Mesh(devs.reshape(1, "
                    f"{tp}), ('{config.data_axis}', "
                    f"'{config.model_axis}'))")
            if config.model_axis != "tp":
                raise ValueError(
                    f"model_axis ({config.model_axis!r}) must be 'tp': "
                    f"the model's collectives are hardwired to that "
                    f"axis name, and an unbound axis would silently "
                    f"skip every psum (axis size 1) instead of failing")
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            if sizes.get(config.model_axis) != tp:
                raise ValueError(
                    f"mesh axis {config.model_axis!r} has size "
                    f"{sizes.get(config.model_axis)} but "
                    f"parallel_state says tp={tp} — the mesh must "
                    f"match the process-group layout the model was "
                    f"built under")
            if sizes.get(config.data_axis, 1) != 1:
                raise ValueError(
                    f"a TP-sharded engine serves one replica: the "
                    f"{config.data_axis!r} axis must have size 1 "
                    f"(got {sizes.get(config.data_axis)}) — scale out "
                    f"with fleet replicas, not a wide data axis")
        elif mesh is not None and config.num_slots % mesh.devices.size:
            raise ValueError(
                f"num_slots ({config.num_slots}) must divide evenly "
                f"over the {mesh.devices.size}-device mesh")
        self._spec_decode = config.draft_model is not None
        if self._spec_decode:
            draft = config.draft_model
            if config.draft_params is None:
                raise ValueError("draft_model needs draft_params")
            if not getattr(draft, "decode", False):
                raise ValueError("draft_model must be built with "
                                 "decode=True")
            if draft.config.vocab_size != model.config.vocab_size:
                raise ValueError(
                    f"draft vocab ({draft.config.vocab_size}) != target "
                    f"vocab ({model.config.vocab_size}): the models "
                    f"must share a tokenizer")
            if config.temperature:
                raise ValueError(
                    "speculative serving is greedy-only (temperature "
                    "must be 0.0): verification proves token-exactness "
                    "against target argmax, which sampling breaks")
            if config.num_draft_tokens < 1:
                raise ValueError(
                    f"num_draft_tokens ({config.num_draft_tokens}) "
                    f"must be >= 1")
            limit = min(limit, draft.config.max_position_embeddings)
            if sb[-1] > limit:
                raise ValueError(
                    f"largest prefill bucket ({sb[-1]}) exceeds the "
                    f"draft model's position budget ({limit})")
        self.model = model
        self.config = dataclasses.replace(config, batch_buckets=bb,
                                          prefill_buckets=sb)
        self._prefix = bool(config.prefix_cache)
        # per-caller attribution on a possibly-shared store: the
        # fleet swaps in one fleet-scoped PrefixStore via
        # adopt_prefix_store, and each engine generation's distinct
        # name keeps its hit columns separate from its predecessors'
        self._scope = name or "engine"
        self.prefix_store = PrefixStore(
            max_entries=config.prefix_max_entries,
            min_len=config.prefix_min_len) if self._prefix else None
        self.last_prefill_hits = []
        # ``name`` prefixes every AOT registration with the compile
        # watcher: two fleet replicas compile the same ladder with
        # DIFFERENT NamedShardings (distinct device slices), so without
        # distinct names the second registration would be flagged as a
        # signature-diffed recompile — and a respawned replica must use
        # a fresh name for the same reason (serving.fleet appends the
        # generation).
        self.name = name
        self.mesh = mesh
        self.max_len = limit
        self._watcher = watcher if watcher is not None \
            else compile_watch.get_watcher()
        self._registry = registry
        self.spec = kvc.KVCacheSpec(model, config.num_slots,
                                    mode=config.cache_mode,
                                    block_size=config.block_size)
        self.draft_spec = kvc.KVCacheSpec(
            config.draft_model, config.num_slots,
            mode=config.cache_mode, block_size=config.block_size) \
            if self._spec_decode else None

        # --- allocate the store(s) (THE serving HBM cost) under the OOM
        # post-mortem handler, then commit shardings ---------------------
        labels = {"params": params}
        dstore = dparams = None
        self._row_shardings = {}
        with tmemory.oom_guard(registry=registry, labels=labels):
            if self._tp > 1:
                # TP placement: params stacked [tp, ...] in tp_split's
                # column/row-parallel layout and sharded over the model
                # axis; the store allocated as host numpy GLOBAL zeros
                # and device_put against the per-leaf spec tree — a
                # traced per-rank allocate would register a compile
                # OUTSIDE the AOT ladder and poison the fleet's
                # recompile accounting on respawn.
                from jax.sharding import NamedSharding, PartitionSpec
                from apex_tpu.models.tp_split import split_params_for_tp

                def shardings(pspecs):
                    return jax.tree_util.tree_map(
                        lambda ps: NamedSharding(mesh, ps), pspecs,
                        is_leaf=lambda l: isinstance(l, PartitionSpec))

                ax = config.model_axis
                self._replicated = NamedSharding(mesh, PartitionSpec())
                self._param_sharding = NamedSharding(
                    mesh, PartitionSpec(ax))
                self._sharded = shardings(
                    self.spec.store_pspecs(config.data_axis, ax))
                store = jax.device_put(
                    self.spec.host_global_store(self._tp), self._sharded)
                params = jax.device_put(
                    split_params_for_tp(model.config, params, self._tp),
                    self._param_sharding)
                self._row_shardings["target"] = shardings(
                    self.spec.row_pspecs(ax, lead=1))
                if self._spec_decode:
                    self._draft_sharded = shardings(
                        self.draft_spec.store_pspecs(config.data_axis,
                                                     ax))
                    dstore = jax.device_put(
                        self.draft_spec.host_global_store(self._tp),
                        self._draft_sharded)
                    dparams = jax.device_put(
                        split_params_for_tp(config.draft_model.config,
                                            config.draft_params,
                                            self._tp),
                        self._param_sharding)
                    self._row_shardings["draft"] = shardings(
                        self.draft_spec.row_pspecs(ax, lead=1))
            else:
                store = self.spec.allocate()
                if self._spec_decode:
                    dstore = self.draft_spec.allocate()
                    dparams = config.draft_params
                if mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec

                    self._sharded = NamedSharding(
                        mesh, PartitionSpec(config.data_axis))
                    self._replicated = NamedSharding(mesh,
                                                     PartitionSpec())
                    store = jax.device_put(store, self._sharded)
                    params = jax.device_put(params, self._replicated)
                    if self._spec_decode:
                        dstore = jax.device_put(dstore, self._sharded)
                        dparams = jax.device_put(dparams,
                                                 self._replicated)
                else:
                    self._sharded = self._replicated = None
        self._store = store
        self._draft_store = dstore
        self._params = params
        self._draft_params = dparams
        self._key0 = jax.random.PRNGKey(0)
        self._step_counter = 0
        self._decode_calls = 0
        self.decode_retries_total = 0
        self._zero_rows_np = {}      # (bucket, which) -> host zero stack
        self._zero_rows_dev = {}     # same, pre-device-put (miss fast path)
        # census attribution for every OOM post-mortem from here on:
        # a serve-time death names KV-cache slots (and the draft
        # model's, when speculating), not anonymous buffers
        labels.update(self.census_labels())

        # --- AOT compile the whole ladder, registered with the watcher --
        # The ladder SIZE is invariant to the serving multipliers: a
        # draft model swaps each decode executable's body for the
        # fused draft-k -> verify -> rollback round, the prefix cache
        # swaps each prefill's for the seeded suffix form — every
        # draft/verify executable registers under the engine's
        # ``name=`` prefix like the rest of the ladder, so fleet
        # respawn recompile accounting stays exact.
        self._decode_exec = {}
        self._prefill_exec = {}
        self.aot_compile_seconds = 0.0
        decode_lowered = None
        aot = f"{name}/serve" if name else "serve"
        decode_body = self._spec_decode_fn if self._spec_decode \
            else self._decode_fn
        prefill_body = self._prefill_fn
        if self._tp > 1:
            # manual-SPMD ladder: every executable is jit(shard_map)
            # over the (data=1, tp=m) mesh — the model's 'tp' psums
            # bind inside, the store stays head-sharded through the
            # step, and nothing ever lowers through GSPMD propagation
            decode_body = self._tp_decode_body()
            prefill_body = self._tp_prefill_body()
        decode_tag = "spec_decode" if self._spec_decode else "decode"
        prefill_tag = "seeded_prefill" if self._prefix else "prefill"
        donate = ((0, 1) if self._spec_decode else (0,)) \
            if config.donate else ()
        from apex_tpu.kernels import fused_cc as _fused_cc

        with tmemory.oom_guard(registry=registry, labels=labels), \
                _fused_cc.verify_scope(config.fused_verify):
            for b in self.config.batch_buckets:
                args = self._decode_args(
                    self._ids_aval(b), self._ids_aval(b), self._key0,
                    self._put(np.int32(-1)))
                lowered = jax.jit(
                    decode_body, donate_argnums=donate).lower(*args)
                self._decode_exec[b] = self._compile(
                    lowered,
                    f"{aot}/{config.cache_mode}/{decode_tag}_b{b}", args)
                decode_lowered = lowered
                for s in self.config.prefill_buckets:
                    pargs = self._prefill_args(
                        self._ids_aval(b), self._tokens_aval(b, s),
                        self._ids_aval(b), self._ids_aval(b),
                        self._seed_rows_dev(b, "target"),
                        self._seed_rows_dev(b, "draft"), self._key0)
                    plow = jax.jit(
                        prefill_body, donate_argnums=donate
                    ).lower(*pargs)
                    self._prefill_exec[(b, s)] = self._compile(
                        plow,
                        f"{aot}/{config.cache_mode}/{prefill_tag}"
                        f"_b{b}_s{s}", pargs)
        if config.temperature:
            # warm the host-side PRNG fold so the first sampled step
            # inside an assert_no_recompiles window compiles nothing
            jax.random.fold_in(self._key0, 0).block_until_ready()

        # --- HBM accounting: the decode step IS the steady state --------
        self.memory_report = None
        if config.preflight and decode_lowered is not None:
            self.memory_report = tmemory.report_from_lowered(
                decode_lowered, registry=registry, name="serve/decode")
            rep = self.memory_report
            if rep is not None and rep.get("headroom_frac") is not None \
                    and rep["headroom_frac"] < 0.0:
                msg = (f"serve decode step peak "
                       f"{rep['peak_bytes'] / 1e9:.2f} GB exceeds HBM "
                       f"capacity {rep['capacity_bytes'] / 1e9:.2f} GB "
                       f"— shrink num_slots, the bucket ladder, or "
                       f"switch cache_mode='int8'")
                if config.preflight_strict:
                    raise tmemory.MemoryBudgetError(msg)
                import warnings

                warnings.warn(msg, stacklevel=2)

        reg = self._reg()
        if reg.enabled:
            reg.gauge("serve/kv_cache_bytes").set(self.kv_cache_bytes())
            reg.counter("serve/aot_compiles").inc(self.compile_count)
            reg.event("serve", "engine_start",
                      engine=name,
                      batch_buckets=list(self.config.batch_buckets),
                      prefill_buckets=list(self.config.prefill_buckets),
                      num_slots=config.num_slots,
                      cache_dtype=self.spec.cache_dtype_name(),
                      kv_cache_bytes=self.kv_cache_bytes(),
                      compile_count=self.compile_count,
                      speculative=self._spec_decode,
                      num_draft_tokens=(config.num_draft_tokens
                                        if self._spec_decode else None),
                      draft_kv_cache_bytes=(self.draft_kv_cache_bytes()
                                            if self._spec_decode
                                            else None),
                      prefix_cache=self._prefix,
                      aot_compile_seconds=round(
                          self.aot_compile_seconds, 4))

    # -- small helpers -----------------------------------------------------

    def _reg(self):
        return self._registry or get_registry()

    def _compile(self, lowered, name, args):
        t0 = time.perf_counter()
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
        self.aot_compile_seconds += dt
        # lowered rides along so APEX_TPU_HLO_LINT=1 lints every ladder
        # executable (apex_tpu.analysis) without a second trace
        self._watcher.record_aot(name, args, seconds=dt, lowered=lowered)
        return compiled

    def _ids_aval(self, b):
        return self._put(np.zeros((b,), np.int32))

    def _tokens_aval(self, b, s):
        return self._put(np.zeros((b, s), np.int32))

    def _put(self, x):
        x = np.asarray(x)
        if self._replicated is not None:
            return jax.device_put(x, self._replicated)
        return jnp.asarray(x)

    def _key(self):
        if not self.config.temperature:
            return self._key0
        self._step_counter += 1
        return jax.random.fold_in(self._key0, self._step_counter)

    # -- argument assembly (AOT lowering and host dispatch share it) -------

    def _decode_args(self, slot_ids, tokens, key, poison):
        if self._spec_decode:
            return (self._store, self._draft_store, self._params,
                    self._draft_params, slot_ids, tokens, key, poison)
        return (self._store, self._params, slot_ids, tokens, key,
                poison)

    def _prefill_args(self, slot_ids, tokens, true_len, start,
                      prefix_rows, draft_prefix_rows, key):
        args = [self._store]
        if self._spec_decode:
            args.append(self._draft_store)
        args.append(self._params)
        if self._spec_decode:
            args.append(self._draft_params)
        args += [slot_ids, tokens, true_len]
        if self._prefix:
            args += [start, prefix_rows]
            if self._spec_decode:
                args.append(draft_prefix_rows)
        args.append(key)
        return tuple(args)

    def _host_zero_rows(self, b, which):
        """Host zero seed stack ``[b, ...]`` in store layout, cached
        per bucket — the prefix-cache miss filler (and the template
        the hit path stacks entries into)."""
        if not self._prefix or (which == "draft"
                                and not self._spec_decode):
            return None
        key = (b, which)
        if key not in self._zero_rows_np:
            spec = self.spec if which == "target" else self.draft_spec
            zero = spec.host_zero_row(tp=self._tp)
            self._zero_rows_np[key] = jax.tree_util.tree_map(
                lambda l: np.zeros((b,) + l.shape, l.dtype), zero)
        return self._zero_rows_np[key]

    def _seed_rows_dev(self, b, which):
        """Pre-placed all-miss seed stack (device arrays are
        immutable, so one placement serves every miss-only prefill)."""
        rows = self._host_zero_rows(b, which)
        if rows is None:
            return None
        key = (b, which)
        if key not in self._zero_rows_dev:
            self._zero_rows_dev[key] = self._put_rows(rows, which)
        return self._zero_rows_dev[key]

    def _put_rows(self, rows, which):
        """Place a [b]-stacked CANONICAL seed-row tree: in TP mode the
        K/V groups axis shards over the model axis (each rank receives
        exactly its head slice — the reshard half of the migration
        pair); otherwise replicated like every other host operand."""
        if self._tp > 1:
            return jax.device_put(rows, self._row_shardings[which])
        return jax.tree_util.tree_map(self._put, rows)

    # -- tensor-parallel ladder bodies (jit(shard_map) manual SPMD) --------

    def _tp_decode_body(self):
        """The decode body wrapped in one ``shard_map`` over the whole
        step: store rows arrive head-sharded, params arrive as each
        rank's stacked slice (unstacked inside, the
        ``tensor_parallel_generate`` idiom), and the model's own 'tp'
        collectives — attention/MLP psums, the vocab gather before
        sampling — bind against the mesh axis. Everything downstream
        of the gather is rank-identical (shared key), so tokens and
        flags leave as replicated outputs."""
        from jax.sharding import PartitionSpec as P

        cfg = self.config
        ax = cfg.model_axis
        store_ps = self.spec.store_pspecs(cfg.data_axis, ax)
        unstack = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)  # noqa: E731
        if self._spec_decode:
            dstore_ps = self.draft_spec.store_pspecs(cfg.data_axis, ax)

            def body(store, dstore, params, dparams, slot_ids, tokens,
                     key, poison):
                return self._spec_decode_fn(
                    store, dstore, unstack(params), unstack(dparams),
                    slot_ids, tokens, key, poison)

            return jax.shard_map(
                body, mesh=self.mesh,
                in_specs=(store_ps, dstore_ps, P(ax), P(ax), P(), P(),
                          P(), P()),
                out_specs=(store_ps, dstore_ps, P(), P(), P()),
                check_vma=False)

        def body(store, params, slot_ids, tokens, key, poison):
            return self._decode_fn(store, unstack(params), slot_ids,
                                   tokens, key, poison)

        return jax.shard_map(
            body, mesh=self.mesh,
            in_specs=(store_ps, P(ax), P(), P(), P(), P()),
            out_specs=(store_ps, P(), P()),
            check_vma=False)

    def _tp_prefill_body(self):
        """The prefill body under the same ``shard_map`` treatment.
        Seed rows cross the boundary in CANONICAL layout and the
        in_specs slice each rank's head shard out (so entries cached
        by an engine of a different tp size seed here unchanged); the
        raw-row outputs reassemble to canonical through the matching
        out_specs — together the consolidate/reshard pair the
        KV-state migration is built on."""
        from jax.sharding import PartitionSpec as P

        cfg = self.config
        ax = cfg.model_axis
        store_ps = self.spec.store_pspecs(cfg.data_axis, ax)
        row_ps = self.spec.row_pspecs(ax, lead=1)
        unstack = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)  # noqa: E731
        in_specs = [store_ps]
        out_specs = [store_ps]
        if self._spec_decode:
            dstore_ps = self.draft_spec.store_pspecs(cfg.data_axis, ax)
            drow_ps = self.draft_spec.row_pspecs(ax, lead=1)
            in_specs.append(dstore_ps)
            out_specs.append(dstore_ps)
        in_specs.append(P(ax))
        if self._spec_decode:
            in_specs.append(P(ax))
        in_specs += [P(), P(), P()]         # slot_ids, tokens, true_len
        if self._prefix:
            in_specs += [P(), row_ps]       # start, prefix_rows
            if self._spec_decode:
                in_specs.append(drow_ps)
        in_specs.append(P())                # key
        out_specs.append(P())               # first sampled token
        if self._prefix:
            out_specs.append(row_ps)
            if self._spec_decode:
                out_specs.append(drow_ps)

        def body(*args):
            it = iter(args)
            a2 = [next(it)]
            if self._spec_decode:
                a2.append(next(it))
            a2.append(unstack(next(it)))
            if self._spec_decode:
                a2.append(unstack(next(it)))
            a2.extend(it)
            return self._prefill_fn(*a2)

        return jax.shard_map(body, mesh=self.mesh,
                             in_specs=tuple(in_specs),
                             out_specs=tuple(out_specs),
                             check_vma=False)

    @property
    def compile_count(self):
        """AOT executables compiled at startup — the serving compile
        budget, by construction flat under any traffic shape (the
        speculative and seeded executables REPLACE ladder entries,
        they never add any)."""
        return len(self._decode_exec) + len(self._prefill_exec)

    @property
    def spec_enabled(self):
        """True when decode dispatches run the speculative round
        (multi-token results — the scheduler branches on this)."""
        return self._spec_decode

    @property
    def decode_headroom(self):
        """Cache positions a decode dispatch may write BEYOND the
        emitted tokens: the speculative window overshoots by up to
        ``num_draft_tokens``, so admission must keep ``prompt +
        max_new + headroom`` inside the position budget."""
        return self.config.num_draft_tokens if self._spec_decode else 0

    @property
    def prefix_hits(self):
        """THIS engine's hits — per-scope numbers, so a fleet-shared
        store still reports each replica's own column truthfully."""
        return self.prefix_store.scope_stats(self._scope)["hits"] \
            if self._prefix else 0

    @property
    def prefix_lookups(self):
        return self.prefix_store.scope_stats(self._scope)["lookups"] \
            if self._prefix else 0

    @property
    def prefix_hit_tokens(self):
        return self.prefix_store.scope_stats(
            self._scope)["hit_tokens"] if self._prefix else 0

    def adopt_prefix_store(self, store):
        """Swap in a shared (fleet-scoped) :class:`PrefixStore`. Host-
        only and compile-free, so the fleet calls it right after
        construction; per-scope accounting keeps this engine's hit
        columns separate on the shared store. Returns the store."""
        if not self._prefix:
            raise ValueError(
                "engine was built without prefix_cache=True — there "
                "is no seeded-prefill ladder to serve a shared store")
        self.prefix_store = store
        return store

    def kv_cache_bytes(self):
        return self.spec.total_bytes()

    def draft_kv_cache_bytes(self):
        return self.draft_spec.total_bytes() if self._spec_decode else 0

    def census_labels(self):
        """OOM post-mortem attribution (`live_buffer_census` matches
        leaves by identity): rebuilt per call because donation replaces
        the store arrays on every dispatch — a serve-time census must
        name the CURRENT KV-cache slots, not dead buffers. The draft
        ladder's buffers are first-class here: a speculative engine's
        OOM names the draft store and draft weights next to the
        target's."""
        labels = {"params": self._params, "kv_cache": self._store}
        if self._spec_decode:
            labels["draft_params"] = self._draft_params
            labels["kv_cache_draft"] = self._draft_store
        return labels

    def slot_lengths(self):
        """Host copy of the per-slot fill levels (one tiny fetch)."""
        return np.asarray(kvc.store_lengths(self._store))

    def seed_row_template(self, which="target"):
        """The CANONICAL (cross-rank) host row layout this engine
        seeds slots from — the shape/dtype contract a migration
        payload's rows must satisfy. tp-independent by construction:
        a tp=m engine's local groups axis times m is exactly the tp=1
        model layout, so engines of any TP size agree on it."""
        spec = self.spec if which == "target" else self.draft_spec
        return spec.host_zero_row(tp=self._tp) if spec is not None \
            else None

    def extract_kv_state(self, slot_ids):
        """Device-get each slot's KV state and consolidate it into a
        checksummed host payload — the donor half of constant-cost
        migration. Per slot: fetch the (possibly head-sharded) store
        rows, consolidate them to CANONICAL raw model-layout rows
        (per-rank int8 blocks dequantize and concatenate in head
        order — ``KVCacheSpec.consolidate_host_rows``), fetch the
        draft rows the same way on a speculative engine, and fold
        rows + fill length into a crc32 (:func:`kv_payload_crc`).

        Returns ``{slot: {"slot", "length", "tp", "cache_mode",
        "rows", "draft_rows", "crc"}}``. Call AFTER
        ``Scheduler.extract_unfinished`` (slot release only forgets
        the id — the rows stay resident) and BEFORE anything prefills
        into the freed slots."""
        lengths = self.slot_lengths()
        out = {}
        for slot in slot_ids:
            slot = int(slot)
            rows = jax.tree_util.tree_map(
                lambda l: np.asarray(jax.device_get(l[slot])),
                self._store)
            canon = self.spec.consolidate_host_rows(rows, tp=self._tp)
            dcanon = None
            if self._spec_decode:
                drows = jax.tree_util.tree_map(
                    lambda l: np.asarray(jax.device_get(l[slot])),
                    self._draft_store)
                dcanon = self.draft_spec.consolidate_host_rows(
                    drows, tp=self._tp)
            payload = {
                "slot": slot,
                "length": int(lengths[slot]),
                "tp": self._tp,
                "cache_mode": self.config.cache_mode,
                "rows": canon,
                "draft_rows": dcanon,
            }
            payload["crc"] = kv_payload_crc(payload)
            out[slot] = payload
        return out

    def _pick_bucket(self, ladder, n, what):
        for b in ladder:
            if n <= b:
                return b
        raise ValueError(f"{what} ({n}) exceeds the largest bucket "
                         f"({ladder[-1]})")

    # -- the compiled step bodies (pure; AOT-lowered at startup) -----------

    def _sample(self, logits, key):
        cfg = self.config
        return generation.sample_logits(
            logits, key, cfg.temperature, cfg.top_k, cfg.top_p
        ).astype(jnp.int32)

    def _unpack_prefill(self, args):
        it = iter(args)
        store = next(it)
        dstore = next(it) if self._spec_decode else None
        params = next(it)
        dparams = next(it) if self._spec_decode else None
        slot_ids, tokens, true_len = next(it), next(it), next(it)
        start = prefix_rows = dprefix_rows = None
        if self._prefix:
            start, prefix_rows = next(it), next(it)
            if self._spec_decode:
                dprefix_rows = next(it)
        return (store, dstore, params, dparams, slot_ids, tokens,
                true_len, start, prefix_rows, dprefix_rows, next(it))

    def _prefill_one_model(self, model, params, spec, tokens, true_len,
                           start, prefix_rows):
        """vmapped per-slot prefill for one model (target or draft):
        seeds from the passed FULL-PRECISION prefix rows (prefix mode
        — the row's ``cache_index`` rolls to the cut, so a shorter
        cached prefix is just a smaller index; positions past it stay
        resident but masked) or from a zero row, prefills the (suffix)
        tokens at offset positions, and rolls ``cache_index`` to the
        true end.

        Exactness hinges on the seeds being raw (model-layout, never
        dequantized): the suffix forward then attends over EXACTLY the
        prefix K/V a cold full prefill would have computed, and
        re-quantizing the raw prefix reproduces the cold store's int8
        blocks bit-for-bit (same values, same deterministic grid).
        Seeding from dequantized int8 instead would perturb every
        suffix K/V through the lossy prefix — enough to flip a
        near-tie argmax many tokens later (caught by the 8-device
        verify probe).

        Returns ``(store_rows, raw_rows, last_logits)`` — the
        quantized rows for the store scatter and the raw merged rows
        the host caches for future hits."""
        s = tokens.shape[1]

        def one(tok_row, n, st, prow):
            if self._prefix:
                base = generation._set_cache_index(prow, st)
                pos = (st + jnp.arange(s))[None, :]
                end = st + n
            else:
                base = kvc.zero_row(spec.template)
                pos = jnp.arange(s)[None, :]
                end = n
            cache, logits = generation.prefill(
                model, params, base, tok_row[None, :], pos,
                full_logits=True)
            last = logits[0, n - 1]                  # [vocab], true last
            return generation._set_cache_index(cache, end), last

        if self._prefix:
            raw, last_logits = jax.vmap(one)(tokens, true_len, start,
                                             prefix_rows)
        else:
            raw, last_logits = jax.vmap(
                lambda t, n: one(t, n, None, None))(tokens, true_len)
        return spec.quantize_rows(raw), raw, last_logits

    def _prefill_fn(self, *args):
        """Admit a bucket: per-slot prefill at padded length S,
        cache_index rolled back to each row's true end (pad positions
        stay resident but masked — the speculative-decode rollback
        trick), first token sampled from the true last position's
        TARGET logits. With a draft model the draft cache prefills the
        same tokens in the same executable (lockstep fill levels);
        with the prefix cache the merged store-layout rows ride out as
        extra outputs so the host can cache them for future hits."""
        (store, dstore, params, dparams, slot_ids, tokens, true_len,
         start, prefix_rows, dprefix_rows, key) = \
            self._unpack_prefill(args)
        rows, raw, last_logits = self._prefill_one_model(
            self.model, params, self.spec, tokens, true_len, start,
            prefix_rows)
        first = self._sample(last_logits, key)
        store = jax.tree_util.tree_map(
            lambda st, r: st.at[slot_ids].set(r), store, rows)
        out = [store]
        if self._spec_decode:
            drows, draw, _ = self._prefill_one_model(
                self.config.draft_model, dparams, self.draft_spec,
                tokens, true_len, start, dprefix_rows)
            dstore = jax.tree_util.tree_map(
                lambda st, r: st.at[slot_ids].set(r), dstore, drows)
            out.append(dstore)
        out.append(first)
        if self._prefix:
            out.append(raw)
            if self._spec_decode:
                out.append(draw)
        return tuple(out)

    def _decode_fn(self, store, params, slot_ids, tokens, key,
                   poison_slot):
        """One continuous-batching decode step over a slot bucket:
        gather rows, dequantize on read, run the model's own decode
        attention per slot at its own length, re-quantize ONLY the
        appended position, scatter back, sample.

        Per-slot quarantine rides in the same executable: a per-slot
        finite flag is derived from each row's logits (vmapped with
        the step — no executable beyond the ladder) and a non-finite
        row scatters ZEROED rows back (its KV and ``cache_index``
        reset in-graph) while sampling the pad token; healthy rows are
        untouched. ``poison_slot`` is the fault injector's traced i32
        handle (-1 = identity): ``faults.inject_slot_nan`` poisons one
        named slot's logits without changing the compiled program."""
        rows = jax.tree_util.tree_map(lambda l: l[slot_ids], store)
        model_rows = self.spec.materialize_rows(rows)
        lengths = kvc.store_lengths(model_rows)

        def one(cache_row, tok, n):
            cache_row = generation._set_cache_index(cache_row, n)
            cache_row, logits = generation.decode_step(
                self.model, params, cache_row, tok[None, None],
                jnp.full((1, 1), n, jnp.int32))
            return cache_row, logits[0]

        new_rows, logits = jax.vmap(one)(model_rows, tokens, lengths)
        logits = jnp.where(
            (slot_ids == poison_slot)[:, None],
            jnp.asarray(jnp.nan, logits.dtype), logits)
        finite = jnp.all(jnp.isfinite(
            logits.astype(jnp.float32)), axis=-1)
        nxt = self._sample(logits, key)
        nxt = jnp.where(finite, nxt,
                        jnp.asarray(self.config.pad_token_id, nxt.dtype))
        updated = self.spec.update_rows_at(rows, new_rows, lengths)
        b = finite.shape[0]

        def keep(u):
            f = finite.reshape((b,) + (1,) * (u.ndim - 1))
            return jnp.where(f, u, jnp.zeros_like(u))

        updated = jax.tree_util.tree_map(keep, updated)
        store = jax.tree_util.tree_map(
            lambda st, r: st.at[slot_ids].set(r), store, updated)
        return store, nxt, finite

    def _spec_decode_fn(self, store, dstore, params, dparams, slot_ids,
                        tokens, key, poison_slot):
        """One speculative continuous-batching round over a slot
        bucket — the fused draft -> verify -> accept -> rollback
        epilogue in ONE executable (no host round-trip between draft
        and verification):

        per slot (vmapped, each at its own fill level ``n``): the
        draft greedily proposes ``k`` tokens through its own cache
        (plus the completion feed, so a full accept leaves no hole);
        the target verifies the whole ``[last, d_1..d_k]`` window in
        one chunked forward (:func:`generation.verify_step` — the same
        body ``speculative_generate`` runs); the slot emits its
        longest matching prefix plus one target token (correction on
        mismatch, bonus on full accept) — per-slot MIXED acceptance,
        no batch minimum — and both caches roll their ``cache_index``
        back to ``n + accepted + 1``: rejected positions stay resident
        but masked (the trick this engine's prefill was built on)
        until the next round overwrites them. int8 stores re-quantize
        exactly the ``k + 1``-position window; untouched blocks pass
        through bit-identical.

        Per-slot quarantine rides along unchanged: non-finite
        verification logits (or the ``poison_slot`` injection handle)
        zero the slot's rows in BOTH stores and emit one pad token.

        Returns ``(store, dstore, emitted [b, k+1], counts [b],
        finite [b])`` — ``emitted[i, :counts[i]]`` are slot i's
        verified tokens, every one a target argmax over its own
        prefix (token-identical to the plain decode engine)."""
        k = int(self.config.num_draft_tokens)
        draft = self.config.draft_model
        rows = jax.tree_util.tree_map(lambda l: l[slot_ids], store)
        drows = jax.tree_util.tree_map(lambda l: l[slot_ids], dstore)
        model_rows = self.spec.materialize_rows(rows)
        draft_rows = self.draft_spec.materialize_rows(drows)
        lengths = kvc.store_lengths(model_rows)
        poisoned = slot_ids == poison_slot
        pad = jnp.asarray(self.config.pad_token_id, jnp.int32)

        def one(trow, drow, tok, n, bad):
            trow = generation._set_cache_index(trow, n)
            drow = generation._set_cache_index(drow, n)

            def dstep(carry, i):
                dc, t = carry
                dc, lg = generation.decode_step(
                    draft, dparams, dc, t[None, None],
                    jnp.full((1, 1), n + i, jnp.int32))
                nxt = jnp.argmax(
                    lg[0].astype(jnp.float32), -1).astype(jnp.int32)
                return (dc, nxt), nxt

            # k proposals + one completion feed of d_k (the draft
            # cache must hold every position before the next round's
            # feed, full accept included)
            (drow, _), ds = jax.lax.scan(dstep, (drow, tok),
                                         jnp.arange(k + 1))
            d = ds[:k]                                     # [k]
            chunk = jnp.concatenate([tok[None], d])[None, :]
            cpos = (n + jnp.arange(k + 1))[None, :]
            trow, v, logits = generation.verify_step(
                self.model, params, trow, chunk, cpos)
            v, logits = v[0], logits[0]          # [k+1], [k+1, vocab]
            logits = jnp.where(bad, jnp.asarray(jnp.nan, logits.dtype),
                               logits)
            finite = jnp.all(jnp.isfinite(logits.astype(jnp.float32)))
            match = (d == v[:k]).astype(jnp.int32)
            a = jnp.sum(jnp.cumprod(match))      # accepted draft count
            emit = jnp.where(jnp.arange(k + 1) == a, jnp.take(v, a),
                             jnp.concatenate([d, d[-1:]]))
            emit = jnp.where(finite, emit, pad).astype(jnp.int32)
            count = jnp.where(finite, a + 1, 1).astype(jnp.int32)
            trow = generation._set_cache_index(trow, n + count)
            drow = generation._set_cache_index(drow, n + count)
            return trow, drow, emit, count, finite

        new_rows, new_drows, emit, counts, finite = jax.vmap(one)(
            model_rows, draft_rows, tokens, lengths, poisoned)
        updated = self.spec.update_rows_span(rows, new_rows, lengths,
                                             k + 1)
        dupdated = self.draft_spec.update_rows_span(
            drows, new_drows, lengths, k + 1)
        b = finite.shape[0]

        def keep(u):
            f = finite.reshape((b,) + (1,) * (u.ndim - 1))
            return jnp.where(f, u, jnp.zeros_like(u))

        updated = jax.tree_util.tree_map(keep, updated)
        dupdated = jax.tree_util.tree_map(keep, dupdated)
        store = jax.tree_util.tree_map(
            lambda st, r: st.at[slot_ids].set(r), store, updated)
        dstore = jax.tree_util.tree_map(
            lambda st, r: st.at[slot_ids].set(r), dstore, dupdated)
        return store, dstore, emit, counts, finite

    # -- host API (the scheduler's surface) --------------------------------

    def _padded_ids(self, slot_ids, pad_slot_ids, bucket):
        ids = list(int(i) for i in slot_ids)
        need = bucket - len(ids)
        if need:
            pads = [int(i) for i in (pad_slot_ids or ())
                    if int(i) not in ids][:need]
            if len(pads) < need:
                raise ValueError(
                    f"bucket {bucket} needs {need} pad slot(s) but only "
                    f"{len(pads)} free id(s) were provided — pad ids "
                    f"must be distinct unused slots (a duplicate "
                    f"scatter would collide)")
            ids += pads
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate slot ids in {ids}")
        return ids

    def prefill(self, slot_ids, prompts, *, pad_slot_ids=None):
        """Prefill ``prompts[i]`` (unpadded 1-D int arrays) into
        ``slot_ids[i]`` and return the first generated token per
        prompt, ``np.ndarray [len(prompts)]``. Pads the call up to the
        smallest (batch, seq) bucket pair; TTFT is this call's wall
        clock (it blocks on the sampled tokens).

        With the prefix cache on, each prompt first consults the
        host-side :class:`PrefixStore`: a hit seeds the slot's KV rows
        from the cached copy and only the SUFFIX picks the seq bucket
        — so a long shared system prompt costs its bucket once,
        ever — and every prefilled prompt's merged rows are cached for
        future hits. ``last_prefill_hits`` records the per-prompt cut
        (0 = miss) for the scheduler's hit accounting."""
        if len(slot_ids) != len(prompts):
            raise ValueError("slot_ids and prompts disagree")
        n = len(prompts)
        plens = [len(p) for p in prompts]
        if min(plens) < 1:
            raise ValueError("empty prompt")
        bbucket = self._pick_bucket(self.config.batch_buckets, n,
                                    "prefill batch")
        ids = self._padded_ids(slot_ids, pad_slot_ids, bbucket)
        if not self._prefix:
            self.last_prefill_hits = [0] * n
            sbucket = self._pick_bucket(self.config.prefill_buckets,
                                        max(plens), "prompt length")
            toks = np.full((bbucket, sbucket),
                           self.config.pad_token_id, np.int32)
            lens = np.ones((bbucket,), np.int32)
            for i, p in enumerate(prompts):
                toks[i, :plens[i]] = np.asarray(p, np.int32)
                lens[i] = plens[i]
            args = self._prefill_args(
                self._put(np.asarray(ids, np.int32)), self._put(toks),
                self._put(lens), None, None, None, self._key())
            out = self._prefill_exec[(bbucket, sbucket)](*args)
            if self._spec_decode:
                self._store, self._draft_store, first = out
            else:
                self._store, first = out
            return np.asarray(first)[:n]
        return self._prefill_seeded(ids, prompts, plens, n, bbucket)

    def _prefill_seeded(self, ids, prompts, plens, n, bbucket):
        """The prefix-cache admission path: look up cuts, assemble the
        per-slot seed stack (cached entry rows on a hit, zeros on a
        miss), prefill only the suffix bucket, then cache the merged
        rows of every newly-seen prompt."""
        lookups = [self.prefix_store.lookup(p, scope=self._scope)
                   for p in prompts]
        cuts = [c for c, _ in lookups]
        suffix_lens = [plen - c for plen, c in zip(plens, cuts)]
        sbucket = self._pick_bucket(self.config.prefill_buckets,
                                    max(suffix_lens),
                                    "prompt suffix length")
        toks = np.full((bbucket, sbucket), self.config.pad_token_id,
                       np.int32)
        lens = np.ones((bbucket,), np.int32)
        starts = np.zeros((bbucket,), np.int32)
        for i, (p, (cut, _)) in enumerate(zip(prompts, lookups)):
            suffix = np.asarray(p, np.int32)[cut:]
            toks[i, :suffix.shape[0]] = suffix
            lens[i] = suffix.shape[0]
            starts[i] = cut
        hits = sum(1 for c in cuts if c)
        if hits:
            # assemble per-slot: entry rows on hit, zeros elsewhere
            prows = self._stack_seed_rows(lookups, bbucket, "rows")
            dprows = self._stack_seed_rows(lookups, bbucket,
                                           "draft_rows") \
                if self._spec_decode else None
        else:
            # miss-only groups reuse the pre-placed zero stack — no
            # host assembly, no fresh transfer
            prows = self._seed_rows_dev(bbucket, "target")
            dprows = self._seed_rows_dev(bbucket, "draft")
        args = self._prefill_args(
            self._put(np.asarray(ids, np.int32)), self._put(toks),
            self._put(lens), self._put(starts), prows, dprows,
            self._key())
        out = list(self._prefill_exec[(bbucket, sbucket)](*args))
        self._store = out.pop(0)
        if self._spec_decode:
            self._draft_store = out.pop(0)
        first = out.pop(0)
        rows = out.pop(0)
        drows = out.pop(0) if self._spec_decode else None
        self.last_prefill_hits = cuts
        self._record_prefix(prompts, plens, cuts, hits, sbucket, rows,
                            drows)
        return np.asarray(first)[:n]

    def _host_zero_row(self, attr):
        key = ("zero_row", attr)
        if key not in self._zero_rows_np:
            spec = self.spec if attr == "rows" else self.draft_spec
            self._zero_rows_np[key] = spec.host_zero_row(tp=self._tp)
        return self._zero_rows_np[key]

    def _stack_seed_rows(self, lookups, bbucket, attr):
        """[bbucket]-stacked host seed rows: cached entry rows where a
        lookup hit, zeros elsewhere (pads included). Entry rows and
        the zero row share the raw model-layout treedef, so one
        tree_map stacks them leaf-wise."""
        zero = self._host_zero_row(attr)
        picks = [getattr(e, attr) if (c and e is not None) else zero
                 for c, e in lookups]
        picks += [zero] * (bbucket - len(picks))
        stacked = jax.tree_util.tree_map(
            lambda *leaves: np.stack(leaves), *picks)
        return self._put_rows(
            stacked, "target" if attr == "rows" else "draft")

    def _record_prefix(self, prompts, plens, cuts, hits, sbucket, rows,
                       drows):
        """Hit accounting + insertion of newly-seen prompts (host
        copies of the RAW merged rows — full precision, so a future
        hit's suffix forward sees exactly what this cold prefill
        saw)."""
        n = len(prompts)
        reg = self._reg()
        if reg.enabled:
            reg.counter("serve/prefix_hits").inc(hits)
            reg.counter("serve/prefix_misses").inc(n - hits)
            hit_toks = sum(cuts)
            if hit_toks:
                reg.counter("serve/prefix_hit_tokens").inc(hit_toks)
            reg.event("serve", "prefix_lookup", prompts=n, hits=hits,
                      hit_tokens=hit_toks, suffix_bucket=sbucket,
                      entries=len(self.prefix_store),
                      store_bytes=self.prefix_store.total_bytes())
        inserts = [i for i in range(n)
                   if plens[i] > self.prefix_store.min_len
                   and not self.prefix_store.covers(prompts[i])]
        if not inserts:
            return
        host_rows = jax.tree_util.tree_map(np.asarray, rows)
        host_drows = jax.tree_util.tree_map(np.asarray, drows) \
            if drows is not None else None
        for i in inserts:
            # np.copy (not ascontiguousarray — that promotes 0-d
            # scalars like cache_index to 1-d) detaches the slice
            row_i = jax.tree_util.tree_map(
                lambda l: np.copy(l[i]), host_rows)
            drow_i = jax.tree_util.tree_map(
                lambda l: np.copy(l[i]), host_drows) \
                if host_drows is not None else None
            self.prefix_store.insert(prompts[i], row_i, drow_i,
                                     scope=self._scope)

    def decode(self, slot_ids, tokens, *, pad_slot_ids=None,
               guarded=True, retries=0, backoff_s=0.05,
               backoff_cap_s=1.0):
        """One decode step for the active ``slot_ids`` fed their last
        ``tokens``; returns ``(next_tokens, finite)`` — each
        ``np.ndarray [len(slot_ids)]``, ``finite[i]`` False iff slot
        ``i``'s logits went non-finite this step (its KV rows are
        already reset in-graph; the scheduler evicts it as
        ``poisoned``). A speculative engine (``spec_enabled``)
        dispatches one fused draft-verify round instead and returns
        ``(emitted [n, k+1], counts [n], finite [n])`` — slot i's
        verified tokens are ``emitted[i, :counts[i]]``; everything
        below (guarding, retries, injection) is identical.

        Dispatch runs under ``resilience.guarded_call``
        (``guarded=False`` opts out): an HBM exhaustion mid-traffic
        writes the memory post-mortem — census labeled with the KV
        cache and weights — and surfaces as ``HBMExhaustedError``.
        ``retries`` re-dispatches after transient failures
        (``robust.is_retryable_decode_error``) with capped exponential
        backoff; past the budget the call raises
        ``robust.DecodeFailedError`` so the caller fails only the
        implicated requests. The injection checkpoint
        (``faults.maybe_fail_decode`` / ``faults.poison_slot_for``)
        is keyed on the engine's lifetime decode-call counter."""
        from apex_tpu import resilience
        from apex_tpu.resilience import faults
        from apex_tpu.serving import robust

        n = len(slot_ids)
        bbucket = self._pick_bucket(self.config.batch_buckets, n,
                                    "decode batch")
        ids = self._padded_ids(slot_ids, pad_slot_ids, bbucket)
        toks = np.zeros((bbucket,), np.int32)
        toks[:n] = np.asarray(tokens, np.int32)
        step_idx = self._decode_calls
        self._decode_calls += 1
        poison = faults.poison_slot_for(step_idx)
        key = self._key()
        for attempt in range(int(retries) + 1):
            try:
                faults.maybe_fail_decode(step_idx)
                args = self._decode_args(
                    self._put(np.asarray(ids, np.int32)),
                    self._put(toks), key, self._put(np.int32(poison)))
                if guarded:
                    out = resilience.guarded_call(
                        self._decode_exec[bbucket], *args,
                        registry=self._registry,
                        labels=self.census_labels())
                else:
                    out = self._decode_exec[bbucket](*args)
                break
            except Exception as e:  # noqa: BLE001 — classified below
                if not robust.is_retryable_decode_error(e):
                    raise
                if attempt >= int(retries):
                    raise robust.DecodeFailedError(
                        f"decode call {step_idx} (bucket {bbucket}, "
                        f"slots {list(ids[:n])}) failed "
                        f"{attempt + 1} time(s); retry budget "
                        f"({retries}) exhausted: {e}",
                        attempts=attempt + 1, last_error=e) from e
                self.decode_retries_total += 1
                reg = self._reg()
                reg.counter("serve/decode_retries").inc()
                reg.event("serve", "decode_retry", step=step_idx,
                          attempt=attempt, error=type(e).__name__)
                time.sleep(robust.retry_backoff_s(
                    attempt, backoff_s, backoff_cap_s))
        if self._spec_decode:
            self._store, self._draft_store, emit, counts, finite = out
            return (np.asarray(emit)[:n], np.asarray(counts)[:n],
                    np.asarray(finite)[:n])
        self._store, nxt, finite = out
        return np.asarray(nxt)[:n], np.asarray(finite)[:n]

    def serve(self, requests, *, robust=None, guard=None, **kw):
        """Run a request list to completion through a fresh
        :class:`~apex_tpu.serving.scheduler.Scheduler`; returns
        ``(completed, stats)``. ``robust`` (a
        :class:`~apex_tpu.serving.robust.RobustConfig`) and ``guard``
        (a :class:`~apex_tpu.resilience.preemption.PreemptionGuard`)
        pass through to the scheduler. The convenience entry point
        bench.py's ``serve_decode``/``serve_chaos`` and the oneproc
        serve smokes drive."""
        from apex_tpu.serving.scheduler import Scheduler

        sched = Scheduler(self, registry=self._registry, robust=robust,
                          guard=guard)
        completed = sched.run(requests, **kw)
        return completed, sched.stats()
