"""ServeEngine — AOT-compiled, bucketed, continuous-batching decode.

The forward-only production path the ROADMAP's open item 3 asks for.
Shape discipline is the whole design: at startup the engine
ahead-of-time compiles (``jax.jit(...).lower(...).compile()``) exactly
ONE prefill executable per (batch-bucket, seq-bucket) pair and ONE
decode executable per batch-bucket, registers every compile with the
:class:`~apex_tpu.telemetry.compile_watch.CompileWatcher`, and from
then on steady-state traffic — whatever its arrival pattern — only
ever *calls* those executables. ``assert_no_recompiles`` around the
serving loop is therefore a hard invariant, not a hope: the compile
count equals the bucket-ladder size and stays flat as traffic varies
(the compile watcher was built for exactly this; see
docs/observability.md).

The decode step reuses the model's own incremental-decode semantics:
``generation.prefill`` / ``generation.decode_step`` vmapped over cache
slots, each slot carrying its own ``cache_index`` so mixed sequence
lengths coexist in one batch (greedy output is token-identical to
``generation.generate`` for the bf16 cache — pinned in
tests/L0/test_serving.py). The KV cache is the slotted store of
:mod:`apex_tpu.serving.kv_cache`: sharded over the data axis,
optionally int8-quantized with dequant-on-read inside the compiled
step.

Resource discipline mirrors the training substrate: cache preallocation
(the dominant HBM cost) runs under ``telemetry.memory.oom_guard``, the
decode step's budget is preflighted before any traffic, and every
decode dispatch goes through ``resilience.guarded_call`` so a real (or
injected) RESOURCE_EXHAUSTED writes a memory post-mortem instead of a
bare traceback. See docs/serving.md for the operational tour.
"""

import dataclasses
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models import generation
from apex_tpu.parallel import compression
from apex_tpu.serving import kv_cache as kvc
from apex_tpu.telemetry import compile_watch
from apex_tpu.telemetry import memory as tmemory
from apex_tpu.telemetry.registry import get_registry


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static serving knobs — everything that shapes an executable.

    ``batch_buckets`` is the decode ladder (active sequences pad up to
    the smallest bucket that fits); ``prefill_buckets`` the prompt-
    length ladder (prompts right-pad up to a bucket, the pad positions
    stay masked by the cache's absolute-position attention). The AOT
    compile count is ``len(batch_buckets) * len(prefill_buckets) +
    len(batch_buckets)`` — fixed at startup, flat under any traffic.
    """

    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    prefill_buckets: Tuple[int, ...] = (16, 32, 64)
    num_slots: int = 8
    cache_mode: str = "bf16"            # "bf16" | "int8"
    block_size: int = compression.BLOCK_SIZE
    temperature: float = 0.0            # 0 = greedy
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    data_axis: str = "data"             # mesh axis the slot dim shards over
    donate: bool = True                 # donate the store through the step
    preflight: bool = True
    preflight_strict: bool = False


class ServeEngine:
    """AOT-compiled prefill/decode over a slotted KV cache.

    The engine owns the device store and the compiled executables; it
    is deliberately ignorant of *requests* — admission, eviction, and
    latency accounting live in
    :class:`~apex_tpu.serving.scheduler.Scheduler` (which
    :meth:`serve` constructs for the common case). ``slot_ids`` in the
    host API are plain Python ints; padding a bucket uses caller-
    provided FREE slots (distinct ids — a duplicate scatter would
    collide), which the scheduler always has by construction.
    """

    def __init__(self, model, params, config: ServeConfig = None, *,
                 mesh=None, watcher=None, registry=None, name=None):
        from apex_tpu.transformer.parallel_state import (
            get_tensor_model_parallel_world_size,
        )

        if get_tensor_model_parallel_world_size() > 1:
            raise NotImplementedError(
                "ServeEngine drives a tp=1 model (shard the cache over "
                "the data axis; a TP serving loop composes later)")
        if not getattr(model, "decode", False):
            raise ValueError("ServeEngine needs a model built with "
                             "decode=True")
        config = config or ServeConfig()
        if not config.batch_buckets or not config.prefill_buckets:
            raise ValueError("empty bucket ladder")
        bb = tuple(sorted(set(int(b) for b in config.batch_buckets)))
        sb = tuple(sorted(set(int(s) for s in config.prefill_buckets)))
        if bb[-1] > config.num_slots:
            raise ValueError(
                f"largest batch bucket ({bb[-1]}) exceeds num_slots "
                f"({config.num_slots}) — a bucket gathers distinct slots")
        limit = model.config.max_position_embeddings
        if sb[-1] > limit:
            raise ValueError(
                f"largest prefill bucket ({sb[-1]}) exceeds "
                f"max_position_embeddings ({limit})")
        if mesh is not None and config.num_slots % mesh.devices.size:
            raise ValueError(
                f"num_slots ({config.num_slots}) must divide evenly "
                f"over the {mesh.devices.size}-device mesh")
        self.model = model
        self.config = dataclasses.replace(config, batch_buckets=bb,
                                          prefill_buckets=sb)
        # ``name`` prefixes every AOT registration with the compile
        # watcher: two fleet replicas compile the same ladder with
        # DIFFERENT NamedShardings (distinct device slices), so without
        # distinct names the second registration would be flagged as a
        # signature-diffed recompile — and a respawned replica must use
        # a fresh name for the same reason (serving.fleet appends the
        # generation).
        self.name = name
        self.mesh = mesh
        self.max_len = limit
        self._watcher = watcher if watcher is not None \
            else compile_watch.get_watcher()
        self._registry = registry
        self.spec = kvc.KVCacheSpec(model, config.num_slots,
                                    mode=config.cache_mode,
                                    block_size=config.block_size)

        # --- allocate the store (THE serving HBM cost) under the OOM
        # post-mortem handler, then commit shardings ---------------------
        labels = {"params": params}
        with tmemory.oom_guard(registry=registry, labels=labels):
            store = self.spec.allocate()
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                self._sharded = NamedSharding(
                    mesh, PartitionSpec(config.data_axis))
                self._replicated = NamedSharding(mesh, PartitionSpec())
                store = jax.device_put(store, self._sharded)
                params = jax.device_put(params, self._replicated)
            else:
                self._sharded = self._replicated = None
        self._store = store
        self._params = params
        self._key0 = jax.random.PRNGKey(0)
        self._step_counter = 0
        self._decode_calls = 0
        self.decode_retries_total = 0
        # census attribution for every OOM post-mortem from here on:
        # a serve-time death names KV-cache slots, not anonymous buffers
        labels["kv_cache"] = self._store

        # --- AOT compile the whole ladder, registered with the watcher --
        self._decode_exec = {}
        self._prefill_exec = {}
        self.aot_compile_seconds = 0.0
        decode_lowered = None
        aot = f"{name}/serve" if name else "serve"
        with tmemory.oom_guard(registry=registry, labels=labels):
            for b in self.config.batch_buckets:
                args = (self._store, self._params,
                        self._ids_aval(b), self._ids_aval(b),
                        self._key0, self._put(np.int32(-1)))
                lowered = jax.jit(
                    self._decode_fn,
                    donate_argnums=(0,) if config.donate else ()
                ).lower(*args)
                self._decode_exec[b] = self._compile(
                    lowered, f"{aot}/{config.cache_mode}/decode_b{b}", args)
                decode_lowered = lowered
                for s in self.config.prefill_buckets:
                    pargs = (self._store, self._params,
                             self._ids_aval(b),
                             self._tokens_aval(b, s),
                             self._ids_aval(b), self._key0)
                    plow = jax.jit(
                        self._prefill_fn,
                        donate_argnums=(0,) if config.donate else ()
                    ).lower(*pargs)
                    self._prefill_exec[(b, s)] = self._compile(
                        plow, f"{aot}/{config.cache_mode}/prefill_b{b}_s{s}", pargs)
        if config.temperature:
            # warm the host-side PRNG fold so the first sampled step
            # inside an assert_no_recompiles window compiles nothing
            jax.random.fold_in(self._key0, 0).block_until_ready()

        # --- HBM accounting: the decode step IS the steady state --------
        self.memory_report = None
        if config.preflight and decode_lowered is not None:
            self.memory_report = tmemory.report_from_lowered(
                decode_lowered, registry=registry, name="serve/decode")
            rep = self.memory_report
            if rep is not None and rep.get("headroom_frac") is not None \
                    and rep["headroom_frac"] < 0.0:
                msg = (f"serve decode step peak "
                       f"{rep['peak_bytes'] / 1e9:.2f} GB exceeds HBM "
                       f"capacity {rep['capacity_bytes'] / 1e9:.2f} GB "
                       f"— shrink num_slots, the bucket ladder, or "
                       f"switch cache_mode='int8'")
                if config.preflight_strict:
                    raise tmemory.MemoryBudgetError(msg)
                import warnings

                warnings.warn(msg, stacklevel=2)

        reg = self._reg()
        if reg.enabled:
            reg.gauge("serve/kv_cache_bytes").set(self.kv_cache_bytes())
            reg.counter("serve/aot_compiles").inc(self.compile_count)
            reg.event("serve", "engine_start",
                      engine=name,
                      batch_buckets=list(self.config.batch_buckets),
                      prefill_buckets=list(self.config.prefill_buckets),
                      num_slots=config.num_slots,
                      cache_dtype=self.spec.cache_dtype_name(),
                      kv_cache_bytes=self.kv_cache_bytes(),
                      compile_count=self.compile_count,
                      aot_compile_seconds=round(
                          self.aot_compile_seconds, 4))

    # -- small helpers -----------------------------------------------------

    def _reg(self):
        return self._registry or get_registry()

    def _compile(self, lowered, name, args):
        t0 = time.perf_counter()
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
        self.aot_compile_seconds += dt
        # lowered rides along so APEX_TPU_HLO_LINT=1 lints every ladder
        # executable (apex_tpu.analysis) without a second trace
        self._watcher.record_aot(name, args, seconds=dt, lowered=lowered)
        return compiled

    def _ids_aval(self, b):
        return self._put(np.zeros((b,), np.int32))

    def _tokens_aval(self, b, s):
        return self._put(np.zeros((b, s), np.int32))

    def _put(self, x):
        x = np.asarray(x)
        if self._replicated is not None:
            return jax.device_put(x, self._replicated)
        return jnp.asarray(x)

    def _key(self):
        if not self.config.temperature:
            return self._key0
        self._step_counter += 1
        return jax.random.fold_in(self._key0, self._step_counter)

    @property
    def compile_count(self):
        """AOT executables compiled at startup — the serving compile
        budget, by construction flat under any traffic shape."""
        return len(self._decode_exec) + len(self._prefill_exec)

    def kv_cache_bytes(self):
        return self.spec.total_bytes()

    def census_labels(self):
        """OOM post-mortem attribution (`live_buffer_census` matches
        leaves by identity): rebuilt per call because donation replaces
        the store arrays on every dispatch — a serve-time census must
        name the CURRENT KV-cache slots, not dead buffers."""
        return {"params": self._params, "kv_cache": self._store}

    def slot_lengths(self):
        """Host copy of the per-slot fill levels (one tiny fetch)."""
        return np.asarray(kvc.store_lengths(self._store))

    def _pick_bucket(self, ladder, n, what):
        for b in ladder:
            if n <= b:
                return b
        raise ValueError(f"{what} ({n}) exceeds the largest bucket "
                         f"({ladder[-1]})")

    # -- the compiled step bodies (pure; AOT-lowered at startup) -----------

    def _sample(self, logits, key):
        cfg = self.config
        return generation.sample_logits(
            logits, key, cfg.temperature, cfg.top_k, cfg.top_p
        ).astype(jnp.int32)

    def _prefill_fn(self, store, params, slot_ids, tokens, true_len,
                    key):
        """Admit a bucket: fresh per-slot prefill at padded length S,
        cache_index rolled back to each row's true length (pad
        positions stay resident but masked — the speculative-decode
        rollback trick), first token sampled from the true last
        position's logits."""
        s = tokens.shape[1]

        def one(tok_row, n):
            cache, logits = generation.prefill(
                self.model, params, kvc.zero_row(self.spec.template),
                tok_row[None, :], jnp.arange(s)[None, :],
                full_logits=True)
            last = logits[0, n - 1]                  # [vocab], true last
            return generation._set_cache_index(cache, n), last

        rows, last_logits = jax.vmap(one)(tokens, true_len)
        first = self._sample(last_logits, key)
        rows = self.spec.quantize_rows(rows)
        store = jax.tree_util.tree_map(
            lambda st, r: st.at[slot_ids].set(r), store, rows)
        return store, first

    def _decode_fn(self, store, params, slot_ids, tokens, key,
                   poison_slot):
        """One continuous-batching decode step over a slot bucket:
        gather rows, dequantize on read, run the model's own decode
        attention per slot at its own length, re-quantize ONLY the
        appended position, scatter back, sample.

        Per-slot quarantine rides in the same executable: a per-slot
        finite flag is derived from each row's logits (vmapped with
        the step — no executable beyond the ladder) and a non-finite
        row scatters ZEROED rows back (its KV and ``cache_index``
        reset in-graph) while sampling the pad token; healthy rows are
        untouched. ``poison_slot`` is the fault injector's traced i32
        handle (-1 = identity): ``faults.inject_slot_nan`` poisons one
        named slot's logits without changing the compiled program."""
        rows = jax.tree_util.tree_map(lambda l: l[slot_ids], store)
        model_rows = self.spec.materialize_rows(rows)
        lengths = kvc.store_lengths(model_rows)

        def one(cache_row, tok, n):
            cache_row = generation._set_cache_index(cache_row, n)
            cache_row, logits = generation.decode_step(
                self.model, params, cache_row, tok[None, None],
                jnp.full((1, 1), n, jnp.int32))
            return cache_row, logits[0]

        new_rows, logits = jax.vmap(one)(model_rows, tokens, lengths)
        logits = jnp.where(
            (slot_ids == poison_slot)[:, None],
            jnp.asarray(jnp.nan, logits.dtype), logits)
        finite = jnp.all(jnp.isfinite(
            logits.astype(jnp.float32)), axis=-1)
        nxt = self._sample(logits, key)
        nxt = jnp.where(finite, nxt,
                        jnp.asarray(self.config.pad_token_id, nxt.dtype))
        updated = self.spec.update_rows_at(rows, new_rows, lengths)
        b = finite.shape[0]

        def keep(u):
            f = finite.reshape((b,) + (1,) * (u.ndim - 1))
            return jnp.where(f, u, jnp.zeros_like(u))

        updated = jax.tree_util.tree_map(keep, updated)
        store = jax.tree_util.tree_map(
            lambda st, r: st.at[slot_ids].set(r), store, updated)
        return store, nxt, finite

    # -- host API (the scheduler's surface) --------------------------------

    def _padded_ids(self, slot_ids, pad_slot_ids, bucket):
        ids = list(int(i) for i in slot_ids)
        need = bucket - len(ids)
        if need:
            pads = [int(i) for i in (pad_slot_ids or ())
                    if int(i) not in ids][:need]
            if len(pads) < need:
                raise ValueError(
                    f"bucket {bucket} needs {need} pad slot(s) but only "
                    f"{len(pads)} free id(s) were provided — pad ids "
                    f"must be distinct unused slots (a duplicate "
                    f"scatter would collide)")
            ids += pads
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate slot ids in {ids}")
        return ids

    def prefill(self, slot_ids, prompts, *, pad_slot_ids=None):
        """Prefill ``prompts[i]`` (unpadded 1-D int arrays) into
        ``slot_ids[i]`` and return the first generated token per
        prompt, ``np.ndarray [len(prompts)]``. Pads the call up to the
        smallest (batch, seq) bucket pair; TTFT is this call's wall
        clock (it blocks on the sampled tokens)."""
        if len(slot_ids) != len(prompts):
            raise ValueError("slot_ids and prompts disagree")
        n = len(prompts)
        plens = [len(p) for p in prompts]
        if min(plens) < 1:
            raise ValueError("empty prompt")
        sbucket = self._pick_bucket(self.config.prefill_buckets,
                                    max(plens), "prompt length")
        bbucket = self._pick_bucket(self.config.batch_buckets, n,
                                    "prefill batch")
        ids = self._padded_ids(slot_ids, pad_slot_ids, bbucket)
        toks = np.full((bbucket, sbucket), self.config.pad_token_id,
                       np.int32)
        lens = np.ones((bbucket,), np.int32)
        for i, p in enumerate(prompts):
            toks[i, :plens[i]] = np.asarray(p, np.int32)
            lens[i] = plens[i]
        self._store, first = self._prefill_exec[(bbucket, sbucket)](
            self._store, self._params, self._put(np.asarray(ids,
                                                            np.int32)),
            self._put(toks), self._put(lens), self._key())
        return np.asarray(first)[:n]

    def decode(self, slot_ids, tokens, *, pad_slot_ids=None,
               guarded=True, retries=0, backoff_s=0.05,
               backoff_cap_s=1.0):
        """One decode step for the active ``slot_ids`` fed their last
        ``tokens``; returns ``(next_tokens, finite)`` — each
        ``np.ndarray [len(slot_ids)]``, ``finite[i]`` False iff slot
        ``i``'s logits went non-finite this step (its KV rows are
        already reset in-graph; the scheduler evicts it as
        ``poisoned``).

        Dispatch runs under ``resilience.guarded_call``
        (``guarded=False`` opts out): an HBM exhaustion mid-traffic
        writes the memory post-mortem — census labeled with the KV
        cache and weights — and surfaces as ``HBMExhaustedError``.
        ``retries`` re-dispatches after transient failures
        (``robust.is_retryable_decode_error``) with capped exponential
        backoff; past the budget the call raises
        ``robust.DecodeFailedError`` so the caller fails only the
        implicated requests. The injection checkpoint
        (``faults.maybe_fail_decode`` / ``faults.poison_slot_for``)
        is keyed on the engine's lifetime decode-call counter."""
        from apex_tpu import resilience
        from apex_tpu.resilience import faults
        from apex_tpu.serving import robust

        n = len(slot_ids)
        bbucket = self._pick_bucket(self.config.batch_buckets, n,
                                    "decode batch")
        ids = self._padded_ids(slot_ids, pad_slot_ids, bbucket)
        toks = np.zeros((bbucket,), np.int32)
        toks[:n] = np.asarray(tokens, np.int32)
        step_idx = self._decode_calls
        self._decode_calls += 1
        poison = faults.poison_slot_for(step_idx)
        key = self._key()
        for attempt in range(int(retries) + 1):
            try:
                faults.maybe_fail_decode(step_idx)
                args = (self._store, self._params,
                        self._put(np.asarray(ids, np.int32)),
                        self._put(toks), key,
                        self._put(np.int32(poison)))
                if guarded:
                    store, nxt, finite = resilience.guarded_call(
                        self._decode_exec[bbucket], *args,
                        registry=self._registry,
                        labels=self.census_labels())
                else:
                    store, nxt, finite = self._decode_exec[bbucket](*args)
                break
            except Exception as e:  # noqa: BLE001 — classified below
                if not robust.is_retryable_decode_error(e):
                    raise
                if attempt >= int(retries):
                    raise robust.DecodeFailedError(
                        f"decode call {step_idx} (bucket {bbucket}, "
                        f"slots {list(ids[:n])}) failed "
                        f"{attempt + 1} time(s); retry budget "
                        f"({retries}) exhausted: {e}",
                        attempts=attempt + 1, last_error=e) from e
                self.decode_retries_total += 1
                reg = self._reg()
                reg.counter("serve/decode_retries").inc()
                reg.event("serve", "decode_retry", step=step_idx,
                          attempt=attempt, error=type(e).__name__)
                time.sleep(robust.retry_backoff_s(
                    attempt, backoff_s, backoff_cap_s))
        self._store = store
        return np.asarray(nxt)[:n], np.asarray(finite)[:n]

    def serve(self, requests, *, robust=None, guard=None, **kw):
        """Run a request list to completion through a fresh
        :class:`~apex_tpu.serving.scheduler.Scheduler`; returns
        ``(completed, stats)``. ``robust`` (a
        :class:`~apex_tpu.serving.robust.RobustConfig`) and ``guard``
        (a :class:`~apex_tpu.resilience.preemption.PreemptionGuard`)
        pass through to the scheduler. The convenience entry point
        bench.py's ``serve_decode``/``serve_chaos`` and the oneproc
        serve smokes drive."""
        from apex_tpu.serving.scheduler import Scheduler

        sched = Scheduler(self, registry=self._registry, robust=robust,
                          guard=guard)
        completed = sched.run(requests, **kw)
        return completed, sched.stats()
