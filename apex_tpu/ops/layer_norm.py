"""Fused LayerNorm / RMSNorm — Pallas TPU kernels with custom VJP.

Parity: reference csrc/layer_norm_cuda.cpp (442) + layer_norm_cuda_kernel.cu
(1,170) exporting ``forward[_affine]``, ``backward[_affine]``,
``rms_forward*``, ``rms_backward*`` — consumed by
apex/normalization/fused_layer_norm.py:32-165.

TPU design: the kernel bodies live in :mod:`apex_tpu.kernels.norm`
(one Pallas kernel per (fwd, bwd-dx) pass, row-blocked, fp32 row stats
on the VPU; backward recomputes stats from the stashed input instead of
round-tripping them through HBM) behind the ``layernorm`` / ``rmsnorm``
gates of the kernel registry (:mod:`apex_tpu.kernels.registry` —
``APEX_TPU_KERNELS`` master switch, per-kernel overrides, legacy
``APEX_TPU_PALLAS_LN=1`` still honored). This module keeps the public
entry points, the custom VJP wiring, and the pure-jnp oracle — the
math XLA fuses itself, which is both the non-TPU fallback (CPU tests;
the reference's own CPU path exists "mainly for unittest sake",
fused_layer_norm.py:411-413) and the kernels' parity reference. The
kernels default OFF even on TPU: measured on a real chip (BERT-large,
hidden 1024) the jnp path is ~14% faster end-to-end because XLA's own
LN fusion matches the kernel's bandwidth and the custom-call is a
fusion barrier.
"""

import functools

import jax
import jax.numpy as jnp

from apex_tpu.kernels import norm as _kernels
from apex_tpu.kernels.registry import PallasGate, get_kernel_registry

_INTERPRET = False  # flipped by tests to debug kernels


def _record(name, use, gate):
    """kernels/dispatch telemetry (trace-time; no-op when the metrics
    registry is disabled)."""
    path = ("interpret" if (use and _interp(gate))
            else "pallas" if use else "oracle")
    get_kernel_registry().dispatch(name, path)


def _use_pallas(*arrays_and_gate) -> bool:
    """Whether to run the hand-written Pallas kernel instead of the jnp
    lowering XLA fuses itself — the registry gate's decision (tests
    monkeypatch this to force the kernel on CPU). An optional
    :class:`PallasGate` positional selects the rmsnorm gate; default is
    the layernorm gate."""
    gate = next((a for a in arrays_and_gate if isinstance(a, PallasGate)),
                _kernels.GATE_LN)
    return gate.enabled()


def _interp(gate):
    return _INTERPRET or gate.interpret


def _ln_stats(x):
    return _kernels._ln_stats(x)


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------

def _ln_fwd(x2d, weight, bias, eps):
    use = _use_pallas(x2d)
    _record("layernorm", use, _kernels.GATE_LN)
    if use:
        return _kernels.ln_fwd(x2d, weight, bias, eps,
                               interpret=_interp(_kernels.GATE_LN))
    x = x2d.astype(jnp.float32)
    mean, var = _ln_stats(x)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x2d.dtype)


def _ln_bwd_dx(dy2d, x2d, weight, eps):
    if _use_pallas(x2d):
        return _kernels.ln_bwd_dx(dy2d, x2d, weight, eps,
                                  interpret=_interp(_kernels.GATE_LN))
    dy = dy2d.astype(jnp.float32)
    x = x2d.astype(jnp.float32)
    mean, var = _ln_stats(x)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    wdy = dy * weight.astype(jnp.float32) if weight is not None else dy
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * rstd
    return dx.astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layer_norm_affine(x2d, weight, bias, eps, out_dtype):
    return _ln_fwd(x2d, weight, bias, eps).astype(out_dtype)


def _layer_norm_affine_fwd(x2d, weight, bias, eps, out_dtype):
    y = _ln_fwd(x2d, weight, bias, eps)
    return y.astype(out_dtype), (x2d, weight)


def _layer_norm_affine_bwd(eps, out_dtype, res, dy):
    x2d, weight = res
    dy2d = dy.astype(x2d.dtype)
    dx = _ln_bwd_dx(dy2d, x2d, weight, eps)
    if weight is not None:
        x = x2d.astype(jnp.float32)
        mean, var = _ln_stats(x)
        xhat = (x - mean) * jax.lax.rsqrt(var + eps)
        dyf = dy.astype(jnp.float32)
        dw = jnp.sum(dyf * xhat, axis=0).astype(weight.dtype)
        db = jnp.sum(dyf, axis=0).astype(weight.dtype)
    else:
        dw = None
        db = None
    return dx, dw, db


_layer_norm_affine.defvjp(_layer_norm_affine_fwd, _layer_norm_affine_bwd)


def layer_norm(x, normalized_shape, weight=None, bias=None, eps=1e-5,
               out_dtype=None):
    """Fused layer norm over the trailing ``normalized_shape`` dims.

    Entry-point parity: fused_layer_norm_cuda.forward[_affine]
    (reference apex/normalization/fused_layer_norm.py:43-77).
    """
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    normalized_shape = tuple(normalized_shape)
    h = 1
    for d in normalized_shape:
        h *= d
    orig_shape = x.shape
    x2d = x.reshape(-1, h)
    w = weight.reshape(h) if weight is not None else None
    b = bias.reshape(h) if bias is not None else None
    out_dtype = out_dtype or x.dtype
    y = _layer_norm_affine(x2d, w, b, float(eps), out_dtype)
    return y.reshape(orig_shape)


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------

def _rms_fwd(x2d, weight, eps):
    use = _use_pallas(x2d, _kernels.GATE_RMS)
    _record("rmsnorm", use, _kernels.GATE_RMS)
    if use:
        return _kernels.rms_fwd(x2d, weight, eps,
                                interpret=_interp(_kernels.GATE_RMS))
    x = x2d.astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(x2d.dtype)


def _rms_bwd_dx(dy2d, x2d, weight, eps):
    if _use_pallas(x2d, _kernels.GATE_RMS):
        return _kernels.rms_bwd_dx(dy2d, x2d, weight, eps,
                                   interpret=_interp(_kernels.GATE_RMS))
    dy = dy2d.astype(jnp.float32)
    x = x2d.astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = x * rstd
    wdy = dy * weight.astype(jnp.float32) if weight is not None else dy
    c = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - xhat * c) * rstd
    return dx.astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_norm_affine(x2d, weight, eps, out_dtype):
    return _rms_fwd(x2d, weight, eps).astype(out_dtype)


def _rms_norm_affine_fwd(x2d, weight, eps, out_dtype):
    y = _rms_fwd(x2d, weight, eps)
    return y.astype(out_dtype), (x2d, weight)


def _rms_norm_affine_bwd(eps, out_dtype, res, dy):
    x2d, weight = res
    dy2d = dy.astype(x2d.dtype)
    dx = _rms_bwd_dx(dy2d, x2d, weight, eps)
    if weight is not None:
        x = x2d.astype(jnp.float32)
        ms = jnp.mean(x * x, axis=-1, keepdims=True)
        xhat = x * jax.lax.rsqrt(ms + eps)
        dw = jnp.sum(dy.astype(jnp.float32) * xhat, axis=0).astype(weight.dtype)
    else:
        dw = None
    return dx, dw


_rms_norm_affine.defvjp(_rms_norm_affine_fwd, _rms_norm_affine_bwd)


def rms_norm(x, normalized_shape, weight=None, eps=1e-5, out_dtype=None):
    """Fused RMSNorm (entry-point parity: fused_layer_norm_cuda.rms_forward*,
    reference apex/normalization/fused_layer_norm.py:80-164)."""
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    normalized_shape = tuple(normalized_shape)
    h = 1
    for d in normalized_shape:
        h *= d
    orig_shape = x.shape
    x2d = x.reshape(-1, h)
    w = weight.reshape(h) if weight is not None else None
    out_dtype = out_dtype or x.dtype
    y = _rms_norm_affine(x2d, w, float(eps), out_dtype)
    return y.reshape(orig_shape)
