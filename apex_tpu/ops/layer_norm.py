"""Fused LayerNorm / RMSNorm — Pallas TPU kernels with custom VJP.

Parity: reference csrc/layer_norm_cuda.cpp (442) + layer_norm_cuda_kernel.cu
(1,170) exporting ``forward[_affine]``, ``backward[_affine]``,
``rms_forward*``, ``rms_backward*`` — consumed by
apex/normalization/fused_layer_norm.py:32-165.

TPU design: one Pallas kernel per (fwd, bwd-dx) pass, gridded over row
blocks with the full hidden dim resident in VMEM; per-row statistics are
computed in fp32 on the VPU. The backward *recomputes* the row stats from
the stashed input instead of round-tripping them through HBM (stats are
VPU-cheap; HBM bandwidth is the bottleneck). Weight/bias grads are
column-sum reductions that XLA already does optimally, so they stay as jnp
reductions in the VJP. On non-TPU backends (CPU tests) a pure-jnp path
with identical math is used — the same strategy as the reference's CPU
fallback (fused_layer_norm.py:411-413 "CPU path is here mainly for
unittest sake").
"""

import functools

import jax
import jax.numpy as jnp

_INTERPRET = False  # flipped by tests to debug kernels


def _use_pallas(*arrays) -> bool:
    """Whether to run the hand-written Pallas kernel instead of the jnp
    lowering XLA fuses itself.

    Default: OFF. Measured on a real chip (BERT-large, hidden 1024), the
    jnp path is ~14% faster end-to-end: XLA's own LN fusion matches the
    kernel's bandwidth, and the custom-call is a fusion barrier that adds
    layout copies around every layer. The kernel remains available for
    shapes XLA handles poorly (APEX_TPU_PALLAS_LN=1 forces it) and is kept
    correct by the test suite.
    """
    import os

    if os.environ.get("APEX_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    if os.environ.get("APEX_TPU_PALLAS_LN", "0") != "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _row_block(n_rows: int, hidden: int) -> int:
    # Keep x, y and temps for a block within a few MB of VMEM.
    budget = 4 * 1024 * 1024
    rows = max(8, budget // max(1, 4 * hidden * 4))
    rows = min(rows, 512)
    rows = max(8, (rows // 8) * 8)
    return rows


def _ln_stats(x):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    return mean, var


# ---------------------------------------------------------------------------
# LayerNorm kernels
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, *, eps, affine):
    x = x_ref[...].astype(jnp.float32)
    mean, var = _ln_stats(x)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if affine:
        y = y * w_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _ln_bwd_kernel(dy_ref, x_ref, w_ref, dx_ref, *, eps, affine):
    dy = dy_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    mean, var = _ln_stats(x)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    wdy = dy * w_ref[...].astype(jnp.float32) if affine else dy
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _pallas_rowwise(kernel, outs_dtype, x2d, *vectors):
    """Launch a row-blocked kernel: x2d [n, h] gridded over rows, each
    vector arg [h] broadcast to every block."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, h = x2d.shape
    rb = _row_block(n, h)
    grid = (pl.cdiv(n, rb),)
    in_specs = [pl.BlockSpec((rb, h), lambda i: (i, 0), memory_space=pltpu.VMEM)]
    args = [x2d]
    for v in vectors:
        if v.ndim == 2 and v.shape[0] == n:
            in_specs.append(pl.BlockSpec((rb, h), lambda i: (i, 0),
                                         memory_space=pltpu.VMEM))
        else:
            in_specs.append(pl.BlockSpec((h,), lambda i: (0,),
                                         memory_space=pltpu.VMEM))
        args.append(v)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((rb, h), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, h), outs_dtype),
        interpret=_INTERPRET,
    )(*args)


def _ones(h):
    return jnp.ones((h,), jnp.float32)


def _ln_fwd(x2d, weight, bias, eps):
    if _use_pallas(x2d):
        h = x2d.shape[1]
        affine = weight is not None
        kernel = functools.partial(_ln_fwd_kernel, eps=eps, affine=affine)
        w = weight if affine else _ones(h)
        b = bias if bias is not None else jnp.zeros((h,), jnp.float32)
        # kernel signature: (x, w, b, y)
        def k(x_ref, w_ref, b_ref, y_ref):
            kernel(x_ref, w_ref, b_ref, y_ref)
        return _pallas_rowwise(k, x2d.dtype, x2d, w, b)
    x = x2d.astype(jnp.float32)
    mean, var = _ln_stats(x)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x2d.dtype)


def _ln_bwd_dx(dy2d, x2d, weight, eps):
    if _use_pallas(x2d):
        h = x2d.shape[1]
        affine = weight is not None
        w = weight if affine else _ones(h)
        kernel = functools.partial(_ln_bwd_kernel, eps=eps, affine=affine)

        def k(x_ref, dy_ref, w_ref, dx_ref):
            kernel(dy_ref, x_ref, w_ref, dx_ref)
        return _pallas_rowwise(k, x2d.dtype, x2d, dy2d, w)
    dy = dy2d.astype(jnp.float32)
    x = x2d.astype(jnp.float32)
    mean, var = _ln_stats(x)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    wdy = dy * weight.astype(jnp.float32) if weight is not None else dy
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * rstd
    return dx.astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layer_norm_affine(x2d, weight, bias, eps, out_dtype):
    return _ln_fwd(x2d, weight, bias, eps).astype(out_dtype)


def _layer_norm_affine_fwd(x2d, weight, bias, eps, out_dtype):
    y = _ln_fwd(x2d, weight, bias, eps)
    return y.astype(out_dtype), (x2d, weight)


def _layer_norm_affine_bwd(eps, out_dtype, res, dy):
    x2d, weight = res
    dy2d = dy.astype(x2d.dtype)
    dx = _ln_bwd_dx(dy2d, x2d, weight, eps)
    if weight is not None:
        x = x2d.astype(jnp.float32)
        mean, var = _ln_stats(x)
        xhat = (x - mean) * jax.lax.rsqrt(var + eps)
        dyf = dy.astype(jnp.float32)
        dw = jnp.sum(dyf * xhat, axis=0).astype(weight.dtype)
        db = jnp.sum(dyf, axis=0).astype(weight.dtype)
    else:
        dw = None
        db = None
    return dx, dw, db


_layer_norm_affine.defvjp(_layer_norm_affine_fwd, _layer_norm_affine_bwd)


def layer_norm(x, normalized_shape, weight=None, bias=None, eps=1e-5,
               out_dtype=None):
    """Fused layer norm over the trailing ``normalized_shape`` dims.

    Entry-point parity: fused_layer_norm_cuda.forward[_affine]
    (reference apex/normalization/fused_layer_norm.py:43-77).
    """
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    normalized_shape = tuple(normalized_shape)
    h = 1
    for d in normalized_shape:
        h *= d
    orig_shape = x.shape
    x2d = x.reshape(-1, h)
    w = weight.reshape(h) if weight is not None else None
    b = bias.reshape(h) if bias is not None else None
    out_dtype = out_dtype or x.dtype
    y = _layer_norm_affine(x2d, w, b, float(eps), out_dtype)
    return y.reshape(orig_shape)


# ---------------------------------------------------------------------------
# RMSNorm kernels
# ---------------------------------------------------------------------------

def _rms_fwd_kernel(x_ref, w_ref, y_ref, *, eps, affine):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    if affine:
        y = y * w_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)


def _rms_bwd_kernel(dy_ref, x_ref, w_ref, dx_ref, *, eps, affine):
    dy = dy_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = x * rstd
    wdy = dy * w_ref[...].astype(jnp.float32) if affine else dy
    c = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - xhat * c) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _rms_fwd(x2d, weight, eps):
    if _use_pallas(x2d):
        h = x2d.shape[1]
        affine = weight is not None
        w = weight if affine else _ones(h)
        kernel = functools.partial(_rms_fwd_kernel, eps=eps, affine=affine)

        def k(x_ref, w_ref, y_ref):
            kernel(x_ref, w_ref, y_ref)
        return _pallas_rowwise(k, x2d.dtype, x2d, w)
    x = x2d.astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(ms + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(x2d.dtype)


def _rms_bwd_dx(dy2d, x2d, weight, eps):
    if _use_pallas(x2d):
        h = x2d.shape[1]
        affine = weight is not None
        w = weight if affine else _ones(h)
        kernel = functools.partial(_rms_bwd_kernel, eps=eps, affine=affine)

        def k(x_ref, dy_ref, w_ref, dx_ref):
            kernel(dy_ref, x_ref, w_ref, dx_ref)
        return _pallas_rowwise(k, x2d.dtype, x2d, dy2d, w)
    dy = dy2d.astype(jnp.float32)
    x = x2d.astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    xhat = x * rstd
    wdy = dy * weight.astype(jnp.float32) if weight is not None else dy
    c = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx = (wdy - xhat * c) * rstd
    return dx.astype(x2d.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms_norm_affine(x2d, weight, eps, out_dtype):
    return _rms_fwd(x2d, weight, eps).astype(out_dtype)


def _rms_norm_affine_fwd(x2d, weight, eps, out_dtype):
    y = _rms_fwd(x2d, weight, eps)
    return y.astype(out_dtype), (x2d, weight)


def _rms_norm_affine_bwd(eps, out_dtype, res, dy):
    x2d, weight = res
    dy2d = dy.astype(x2d.dtype)
    dx = _rms_bwd_dx(dy2d, x2d, weight, eps)
    if weight is not None:
        x = x2d.astype(jnp.float32)
        ms = jnp.mean(x * x, axis=-1, keepdims=True)
        xhat = x * jax.lax.rsqrt(ms + eps)
        dw = jnp.sum(dy.astype(jnp.float32) * xhat, axis=0).astype(weight.dtype)
    else:
        dw = None
    return dx, dw


_rms_norm_affine.defvjp(_rms_norm_affine_fwd, _rms_norm_affine_bwd)


def rms_norm(x, normalized_shape, weight=None, eps=1e-5, out_dtype=None):
    """Fused RMSNorm (entry-point parity: fused_layer_norm_cuda.rms_forward*,
    reference apex/normalization/fused_layer_norm.py:80-164)."""
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    normalized_shape = tuple(normalized_shape)
    h = 1
    for d in normalized_shape:
        h *= d
    orig_shape = x.shape
    x2d = x.reshape(-1, h)
    w = weight.reshape(h) if weight is not None else None
    out_dtype = out_dtype or x.dtype
    y = _rms_norm_affine(x2d, w, float(eps), out_dtype)
    return y.reshape(orig_shape)
