"""Multi-tensor fused update ops (TPU-native ``amp_C``).

Parity surface: reference csrc/amp_C_frontend.cpp:160-188 exports
``multi_tensor_scale/sgd/axpby/l2norm[_mp|_scale]/adam[_capturable]/adagrad/
novograd/lamb[_mp]`` — chunked CUDA kernels over lists of tensors
(csrc/multi_tensor_apply.cuh:15-26 takes <=110 tensors per launch with a
device-side ``noop_flag`` for overflow-abort).

TPU design: the GPU problem these kernels solve — thousands of tiny kernel
launches — does not exist under XLA. Every op here is a pure function over
*lists of arrays* that is called inside one ``jit``; XLA fuses the whole
parameter sweep into a handful of loops over HBM. The ``noop_flag`` becomes a
functional overflow scalar threaded through the update (the same scheme the
reference's ``capturable`` CUDA-graph path uses, apex/optimizers/
fused_adam.py:171-229): updates are computed unconditionally and selected
with ``jnp.where(noop, old, new)`` so the step stays branch-free under jit.

All ops are functional: they *return* new lists instead of mutating in place.
"""

from typing import List, Sequence

import jax.numpy as jnp


Arrays = List[jnp.ndarray]


def _finite_flag(tensors: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Return 1.0 if any tensor contains inf/nan else 0.0 (the noop flag)."""
    bad = jnp.zeros((), jnp.bool_)
    for t in tensors:
        bad = bad | ~jnp.all(jnp.isfinite(t.astype(jnp.float32)))
    return bad.astype(jnp.float32)


def _keep(noop, old, new):
    """Select ``old`` where the overflow flag is set (branch-free skip)."""
    return jnp.where(noop > 0, old, new).astype(old.dtype)


# ---------------------------------------------------------------------------
# scale / axpby / l2norm — the amp + DDP helpers
# ---------------------------------------------------------------------------

def multi_tensor_scale(noop_flag, tensor_lists, scale):
    """out[i] = in[i] * scale, with inf/nan detection.

    Parity: csrc/multi_tensor_scale_kernel.cu via apex/amp/scaler.py:57-71.
    ``tensor_lists`` = [ins, outs]; the outs only matter for dtype. Returns
    (new_outs, noop_flag_out).
    """
    ins, outs = tensor_lists
    new_outs = []
    bad = noop_flag
    for x, o in zip(ins, outs):
        y = x.astype(jnp.float32) * scale
        bad = jnp.maximum(bad, _finite_flag([y]))
        new_outs.append(y.astype(o.dtype))
    return new_outs, bad


def multi_tensor_axpby(noop_flag, tensor_lists, a, b, arg_to_check=-1):
    """out[i] = a*x[i] + b*y[i] with inf/nan detection.

    Parity: csrc/multi_tensor_axpby_kernel.cu via apex/amp/scaler.py:152-189
    (grad accumulation with stashed fp32 grads).
    """
    xs, ys, outs = tensor_lists
    new_outs = []
    bad = noop_flag
    for x, y, o in zip(xs, ys, outs):
        r = a * x.astype(jnp.float32) + b * y.astype(jnp.float32)
        bad = jnp.maximum(bad, _finite_flag([r]))
        new_outs.append(r.astype(o.dtype))
    return new_outs, bad


def multi_tensor_l2norm(noop_flag, tensor_lists, per_tensor=False):
    """Global (and optionally per-tensor) L2 norm over a list of tensors.

    Parity: csrc/multi_tensor_l2norm_kernel.cu via
    apex/optimizers/fused_lamb.py:124-133.
    Returns (global_norm, per_tensor_norms or None).
    """
    (xs,) = tensor_lists
    sq = jnp.zeros((), jnp.float32)
    per = []
    for x in xs:
        s = jnp.sum(jnp.square(x.astype(jnp.float32)))
        sq = sq + s
        if per_tensor:
            per.append(jnp.sqrt(s))
    total = jnp.sqrt(sq)
    return total, (jnp.stack(per) if per_tensor else None)


def multi_tensor_l2norm_mp(noop_flag, tensor_lists, per_tensor=False):
    """Mixed-precision variant: upcasts before reduction (same math here)."""
    return multi_tensor_l2norm(noop_flag, tensor_lists, per_tensor)


def multi_tensor_l2norm_scale(noop_flag, tensor_lists, scale, per_tensor=False):
    """L2 norm of scale*x (used for pre-unscaled grad norms)."""
    (xs,) = tensor_lists
    return multi_tensor_l2norm(noop_flag, [[x.astype(jnp.float32) * scale for x in xs]], per_tensor)


# ---------------------------------------------------------------------------
# optimizer update ops
# ---------------------------------------------------------------------------

def multi_tensor_sgd(
    noop_flag,
    tensor_lists,
    wd,
    momentum,
    dampening,
    lr,
    nesterov,
    first_run,
    wd_after_momentum,
    scale=1.0,
):
    """Fused SGD with momentum.

    Parity: csrc/multi_tensor_sgd_kernel.cu via
    apex/optimizers/fused_sgd.py:211-213. tensor_lists = [grads, params,
    momentum_buffers]. Returns (new_params, new_momentum, noop).
    """
    grads, params, moms = tensor_lists
    new_params, new_moms = [], []
    for g, p, m in zip(grads, params, moms):
        g32 = g.astype(jnp.float32) * scale
        p32 = p.astype(jnp.float32)
        if wd != 0 and not wd_after_momentum:
            g32 = g32 + wd * p32
        if momentum != 0:
            m32 = jnp.where(first_run, g32, momentum * m.astype(jnp.float32) + (1 - dampening) * g32)
            d = g32 + momentum * m32 if nesterov else m32
        else:
            m32 = m.astype(jnp.float32)
            d = g32
        if wd != 0 and wd_after_momentum:
            d = d + wd * p32
        p_new = p32 - lr * d
        new_params.append(_keep(noop_flag, p, p_new))
        new_moms.append(_keep(noop_flag, m, m32))
    return new_params, new_moms, noop_flag


def multi_tensor_adam(
    noop_flag,
    tensor_lists,
    lr,
    beta1,
    beta2,
    eps,
    step,
    mode,
    bias_correction,
    weight_decay,
):
    """Fused Adam/AdamW.

    Parity: csrc/multi_tensor_adam.cu via apex/optimizers/fused_adam.py:231-269.
    tensor_lists = [grads, params, exp_avgs, exp_avg_sqs].
    ``mode``: 0 = L2 regularization (classic Adam), 1 = decoupled wd (AdamW).
    Returns (new_params, new_m, new_v, noop).
    """
    grads, params, ms, vs = tensor_lists
    if bias_correction:
        bc1 = 1.0 - beta1 ** step
        bc2 = 1.0 - beta2 ** step
    else:
        bc1 = bc2 = 1.0
    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(grads, params, ms, vs):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if mode == 0 and weight_decay != 0:
            g32 = g32 + weight_decay * p32
        m32 = beta1 * m.astype(jnp.float32) + (1 - beta1) * g32
        v32 = beta2 * v.astype(jnp.float32) + (1 - beta2) * jnp.square(g32)
        m_hat = m32 / bc1
        v_hat = v32 / bc2
        update = m_hat / (jnp.sqrt(v_hat) + eps)
        if mode == 1 and weight_decay != 0:
            update = update + weight_decay * p32
        p_new = p32 - lr * update
        new_p.append(_keep(noop_flag, p, p_new))
        new_m.append(_keep(noop_flag, m, m32))
        new_v.append(_keep(noop_flag, v, v32))
    return new_p, new_m, new_v, noop_flag


def multi_tensor_adam_capturable(noop_flag, tensor_lists, lr, beta1, beta2, eps, step, mode, bias_correction, weight_decay, inv_scale=1.0):
    """Capturable Adam: grads arrive still scaled; unscale inside the update.

    Parity: multi_tensor_adam_capturable (csrc/multi_tensor_adam.cu) used by
    apex/optimizers/fused_adam.py:188-229 for CUDA-graph capture. On TPU the
    whole step is always "captured" (jitted) so this simply folds the
    unscale into the update.
    """
    grads, params, ms, vs = tensor_lists
    grads = [g.astype(jnp.float32) * inv_scale for g in grads]
    return multi_tensor_adam(
        noop_flag, [grads, params, ms, vs], lr, beta1, beta2, eps, step, mode, bias_correction, weight_decay
    )


def multi_tensor_adam_capturable_master(noop_flag, tensor_lists, lr, beta1, beta2, eps, step, mode, bias_correction, weight_decay, inv_scale=1.0):
    """Capturable Adam with fp32 master weights.

    tensor_lists = [grads, params(low-prec), exp_avgs, exp_avg_sqs, masters].
    The update is computed on the fp32 masters; low-precision params are a
    cast of the masters (reference multi_tensor_adam.cu master variant).
    """
    grads, params, ms, vs, masters = tensor_lists
    grads = [g.astype(jnp.float32) * inv_scale for g in grads]
    new_masters, new_m, new_v, noop = multi_tensor_adam(
        noop_flag, [grads, masters, ms, vs], lr, beta1, beta2, eps, step, mode, bias_correction, weight_decay
    )
    new_params = [nm.astype(p.dtype) for nm, p in zip(new_masters, params)]
    return new_params, new_m, new_v, new_masters, noop


def multi_tensor_adagrad(noop_flag, tensor_lists, lr, eps, mode, weight_decay):
    """Fused Adagrad. Parity: csrc/multi_tensor_adagrad.cu via
    apex/optimizers/fused_adagrad.py:5-121. tensor_lists = [grads, params, h]."""
    grads, params, hs = tensor_lists
    new_p, new_h = [], []
    for g, p, h in zip(grads, params, hs):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if mode == 0 and weight_decay != 0:
            g32 = g32 + weight_decay * p32
        h32 = h.astype(jnp.float32) + jnp.square(g32)
        update = g32 / (jnp.sqrt(h32) + eps)
        if mode == 1 and weight_decay != 0:
            update = update + weight_decay * p32
        p_new = p32 - lr * update
        new_p.append(_keep(noop_flag, p, p_new))
        new_h.append(_keep(noop_flag, h, h32))
    return new_p, new_h, noop_flag


def multi_tensor_novograd(
    noop_flag,
    tensor_lists,
    lr,
    beta1,
    beta2,
    eps,
    step,
    bias_correction,
    weight_decay,
    grad_averaging,
    moment_mode,
    norm_type,
    init_zero=False,
):
    """Fused NovoGrad: per-*tensor* second moment (layer-wise ||g||).

    Parity: csrc/multi_tensor_novograd.cu via
    apex/optimizers/fused_novograd.py:183-198. tensor_lists = [grads, params,
    exp_avgs]; the per-tensor second moments ride in a stacked vector.
    ``moment_mode``: 0 = L2-into-grad before moments, 1 = decoupled wd.
    ``init_zero``: seed v at 0 (first step uses (1-beta2)*||g||^2) instead
    of ||g||^2 (reference fused_novograd.py init_zero).
    Returns (new_params, new_m, new_v_vector, noop).
    """
    grads, params, ms, v_vec = tensor_lists[0], tensor_lists[1], tensor_lists[2], tensor_lists[3]
    if bias_correction:
        bc1 = 1.0 - beta1 ** step
    else:
        bc1 = 1.0
    beta3 = (1 - beta1) if grad_averaging else 1.0
    new_p, new_m, new_v = [], [], []
    for i, (g, p, m) in enumerate(zip(grads, params, ms)):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if norm_type == 2:
            gnorm_sq = jnp.sum(jnp.square(g32))
        else:  # max-norm
            gnorm_sq = jnp.square(jnp.max(jnp.abs(g32)))
        v_prev = v_vec[i].astype(jnp.float32)
        first_v = (1 - beta2) * gnorm_sq if init_zero else gnorm_sq
        v32 = jnp.where(step == 1, first_v, beta2 * v_prev + (1 - beta2) * gnorm_sq)
        denom = jnp.sqrt(v32) + eps
        gn = g32 / denom
        if weight_decay != 0 and moment_mode == 0:
            gn = gn + weight_decay * p32
        m32 = beta1 * m.astype(jnp.float32) + beta3 * gn
        update = m32 / bc1
        if weight_decay != 0 and moment_mode == 1:
            update = update + weight_decay * p32
        p_new = p32 - lr * update
        new_p.append(_keep(noop_flag, p, p_new))
        new_m.append(_keep(noop_flag, m, m32))
        new_v.append(jnp.where(noop_flag > 0, v_prev, v32))
    return new_p, new_m, jnp.stack(new_v), noop_flag


def _lamb_grad_clip(global_grad_norm, max_grad_norm):
    """Global grad clipping scale (csrc/multi_tensor_lamb.cu scales by
    clipped_global_grad_norm = max(gnorm/max_norm, 1))."""
    if max_grad_norm is not None and max_grad_norm > 0:
        return jnp.maximum(global_grad_norm / max_grad_norm, 1.0)
    return jnp.asarray(1.0, jnp.float32)


def _lamb_tensor_direction(g, p, m, v, wd, *, beta1, beta2, beta3, bc1, bc2,
                           eps, mode, clip):
    """One tensor's LAMB moment update + update direction (stage-1 math,
    shared by the fused op and the legacy two-stage ops)."""
    g32 = g.astype(jnp.float32) / clip
    p32 = p.astype(jnp.float32)
    if mode == 0 and wd != 0:  # L2 into grad
        g32 = g32 + wd * p32
    m32 = beta1 * m.astype(jnp.float32) + beta3 * g32
    v32 = beta2 * v.astype(jnp.float32) + (1 - beta2) * jnp.square(g32)
    update = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + eps)
    if mode == 1 and wd != 0:  # decoupled (LAMB default)
        update = update + wd * p32
    return m32, v32, update


def _lamb_apply_trust(p32, update, lr, apply_trust):
    """Trust-ratio-scaled parameter step (stage-2 math); NVLAMB applies
    the ratio even when wd == 0."""
    if apply_trust:
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
        ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
    else:
        ratio = jnp.asarray(1.0, jnp.float32)
    return p32 - lr * ratio * update


def _lamb_bias_correction(bias_correction, beta1, beta2, step):
    if bias_correction:
        return 1.0 - beta1 ** step, 1.0 - beta2 ** step
    return 1.0, 1.0


def _lamb_update_lists(
    noop_flag, grads, params, ms, vs, lr, beta1, beta2, eps, step, bias_correction,
    weight_decay, grad_averaging, mode, global_grad_norm, max_grad_norm, use_nvlamb,
):
    """Shared LAMB math for the fused and mixed-precision variants."""
    bc1, bc2 = _lamb_bias_correction(bias_correction, beta1, beta2, step)
    beta3 = (1 - beta1) if grad_averaging else 1.0
    clip = _lamb_grad_clip(global_grad_norm, max_grad_norm)
    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(grads, params, ms, vs):
        m32, v32, update = _lamb_tensor_direction(
            g, p, m, v, weight_decay, beta1=beta1, beta2=beta2, beta3=beta3,
            bc1=bc1, bc2=bc2, eps=eps, mode=mode, clip=clip)
        p_new = _lamb_apply_trust(p.astype(jnp.float32), update, lr,
                                  (weight_decay != 0) or use_nvlamb)
        new_p.append(_keep(noop_flag, p, p_new))
        new_m.append(_keep(noop_flag, m, m32))
        new_v.append(_keep(noop_flag, v, v32))
    return new_p, new_m, new_v


def multi_tensor_lamb(
    noop_flag,
    tensor_lists,
    lr,
    beta1,
    beta2,
    eps,
    step,
    bias_correction,
    weight_decay,
    grad_averaging,
    mode,
    global_grad_norm,
    max_grad_norm,
    use_nvlamb=False,
):
    """Fused LAMB. Parity: csrc/multi_tensor_lamb.cu via
    apex/optimizers/fused_lamb.py:183-199. tensor_lists = [grads, params, m, v]."""
    grads, params, ms, vs = tensor_lists
    new_p, new_m, new_v = _lamb_update_lists(
        noop_flag, grads, params, ms, vs, lr, beta1, beta2, eps, step,
        bias_correction, weight_decay, grad_averaging, mode, global_grad_norm,
        max_grad_norm, use_nvlamb,
    )
    return new_p, new_m, new_v, noop_flag


def multi_tensor_lamb_mp(
    noop_flag,
    tensor_lists,
    lr,
    beta1,
    beta2,
    eps,
    step,
    bias_correction,
    weight_decay,
    grad_averaging,
    mode,
    global_grad_norm,
    max_grad_norm,
    use_nvlamb,
    found_inf,
    inv_scale,
):
    """Mixed-precision LAMB with fp32 master params.

    Parity: csrc/multi_tensor_lamb_mp.cu via
    apex/optimizers/fused_mixed_precision_lamb.py:8-256.
    tensor_lists = [grads, params(low-prec), m, v, masters].
    """
    grads, params, ms, vs, masters = tensor_lists
    noop = jnp.maximum(noop_flag, found_inf)
    grads32 = [g.astype(jnp.float32) * inv_scale for g in grads]
    new_masters, new_m, new_v = _lamb_update_lists(
        noop, grads32, masters, ms, vs, lr, beta1, beta2, eps, step,
        bias_correction, weight_decay, grad_averaging, mode, global_grad_norm,
        max_grad_norm, use_nvlamb,
    )
    new_params = [nm.astype(p.dtype) for nm, p in zip(new_masters, params)]
    return new_params, new_m, new_v, new_masters, noop


def multi_tensor_lamb_stage1(noop_flag, tensor_lists, per_tensor_decay,
                             step, beta1, beta2, beta3, bias_correction,
                             eps, grad_averaging, mode, global_grad_norm,
                             max_global_grad_norm):
    """Legacy two-stage LAMB, stage 1 (parity: csrc/
    multi_tensor_lamb_stage_1.cu via contrib fused_lamb): computes the
    per-parameter update direction u = m_hat/(sqrt(v_hat)+eps) + wd*p.
    tensor_lists = [grads, params, m, v, update_out]; returns
    (m, v, updates, noop_flag). ``beta3`` overrides the momentum mix when
    given; otherwise it derives from ``grad_averaging`` like the fused op.
    """
    grads, params, ms, vs, _ = tensor_lists
    if beta3 is None:
        beta3 = (1.0 - beta1) if grad_averaging else 1.0
    clip = _lamb_grad_clip(global_grad_norm, max_global_grad_norm)
    bc1, bc2 = _lamb_bias_correction(bias_correction, beta1, beta2, step)
    new_m, new_v, updates = [], [], []
    for g, p, m, v, wd in zip(grads, params, ms, vs, per_tensor_decay):
        m32, v32, u = _lamb_tensor_direction(
            g, p, m, v, wd, beta1=beta1, beta2=beta2, beta3=beta3,
            bc1=bc1, bc2=bc2, eps=eps, mode=mode, clip=clip)
        new_m.append(_keep(noop_flag, m, m32))
        new_v.append(_keep(noop_flag, v, v32))
        updates.append(u)
    return new_m, new_v, updates, noop_flag


def multi_tensor_lamb_stage2(noop_flag, tensor_lists, per_tensor_decay, lr,
                             use_nvlamb=False):
    """Legacy two-stage LAMB, stage 2 (parity: csrc/
    multi_tensor_lamb_stage_2.cu:45): applies the update, scaled by the
    trust ratio only when ``use_nvlamb`` or that tensor's decay != 0 —
    matching the fused op's ``apply_trust`` gate.
    tensor_lists = [params, updates]; returns (params, noop_flag).
    """
    params, updates = tensor_lists
    new_p = []
    for p, u, wd in zip(params, updates, per_tensor_decay):
        p_new = _lamb_apply_trust(p.astype(jnp.float32), u, lr,
                                  use_nvlamb or wd != 0)
        new_p.append(_keep(noop_flag, p, p_new))
    return new_p, noop_flag
