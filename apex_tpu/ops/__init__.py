"""apex_tpu.ops — fused op implementations (jnp + Pallas TPU kernels).

This layer is the TPU-native equivalent of the reference's ``csrc/`` CUDA
extension layer (reference setup.py:109-359). Each CUDA kernel family gets
either (a) a Pallas TPU kernel, or (b) a jitted jnp composition that XLA
fuses into one loop — whichever profiles better on the MXU/VPU. Python entry
points mirror the pybind exports (reference csrc/amp_C_frontend.cpp:160-188).
"""

from apex_tpu.ops.multi_tensor import (  # noqa: F401
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_l2norm_mp,
    multi_tensor_l2norm_scale,
    multi_tensor_sgd,
    multi_tensor_adam,
    multi_tensor_adam_capturable,
    multi_tensor_adam_capturable_master,
    multi_tensor_adagrad,
    multi_tensor_novograd,
    multi_tensor_lamb,
    multi_tensor_lamb_mp,
    multi_tensor_lamb_stage1,
    multi_tensor_lamb_stage2,
)
