"""FP16_Optimizer — legacy master-weight optimizer wrapper.

Parity: reference apex/fp16_utils/fp16_optimizer.py:13-557
(``backward``/``update_master_grads``/``clip_master_grads``/``step``,
static or dynamic loss scaling, fp32 master params).

TPU design: a functional wrapper over any apex_tpu fused optimizer that
keeps fp32 masters and runs the scale -> grads -> unscale -> clip -> step
cycle in one jittable call.
"""

import jax
import jax.numpy as jnp

from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler


class FP16_Optimizer(object):
    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=False):
        self.inner = init_optimizer
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)
        self.overflow = False
        self.first_closure_call_this_step = True

    @property
    def loss_scale(self):
        return self.loss_scaler.loss_scale

    def init(self, params):
        from apex_tpu.optimizers._base import master_copy_tree

        inner_state = self.inner.init(params)
        # alias-free copies: astype is a no-op on fp32 leaves and would
        # alias masters to live params (donation double-donate; see
        # master_copy_tree — the double-donation lint rule in
        # apex_tpu.analysis enforces this at trace time)
        inner_state["fp32_master"] = master_copy_tree(params)
        return inner_state

    def backward(self, loss):
        """Return the scaled loss (the caller differentiates it);
        reference fp16_optimizer.py backward() scales then calls
        loss.backward()."""
        return loss * self.loss_scaler.loss_scale

    def update_master_grads(self, grads):
        """Unscale fp16 grads into fp32 master grads; detect overflow."""
        inv = 1.0 / self.loss_scaler.loss_scale
        master_grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)
        bad = jnp.zeros((), jnp.bool_)
        for g in jax.tree_util.tree_leaves(master_grads):
            bad = bad | ~jnp.all(jnp.isfinite(g))
        self.overflow = bool(bad)
        self.loss_scaler.update_scale(self.overflow)
        return master_grads

    def clip_master_grads(self, grads, max_norm, norm_type=2):
        """Clip master grads by global norm (reference clip_master_grads)."""
        leaves = jax.tree_util.tree_leaves(grads)
        total = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
        scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
        return jax.tree_util.tree_map(lambda g: g * scale, grads), total

    def step(self, grads, state, params, *, lr=None):
        """Full cycle: unscale -> overflow check -> inner step on masters ->
        cast back to model dtype. Jit-safe (branch-free skip)."""
        inv = 1.0 / self.loss_scaler.loss_scale
        master_grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv, grads)
        bad = jnp.zeros((), jnp.float32)
        for g in jax.tree_util.tree_leaves(master_grads):
            bad = jnp.maximum(bad, (~jnp.all(jnp.isfinite(g))).astype(jnp.float32))
        masters = state["fp32_master"]
        inner_state = {k: v for k, v in state.items() if k != "fp32_master"}
        new_masters, new_inner = self.inner.step(
            master_grads, inner_state, masters, lr=lr, found_inf=bad)
        new_params = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype), new_masters, params)
        new_inner["fp32_master"] = new_masters
        return new_params, new_inner

    def state_dict(self):
        sd = {"loss_scale": self.loss_scaler.loss_scale,
              "overflow": self.overflow}
        if isinstance(self.loss_scaler, DynamicLossScaler):
            sd["cur_iter"] = self.loss_scaler.cur_iter
            sd["last_overflow_iter"] = self.loss_scaler.last_overflow_iter
        return sd

    def load_state_dict(self, sd):
        self.overflow = sd.get("overflow", False)
        if isinstance(self.loss_scaler, DynamicLossScaler):
            self.loss_scaler.cur_scale = sd["loss_scale"]
            self.loss_scaler.cur_iter = sd.get("cur_iter", 0)
            self.loss_scaler.last_overflow_iter = sd.get("last_overflow_iter", -1)
        else:
            self.loss_scaler.cur_scale = sd["loss_scale"]
