"""Legacy loss scalers.

Parity: reference apex/fp16_utils/loss_scaler.py (188 LoC): ``LossScaler``
(static) and ``DynamicLossScaler`` (overflow backoff / growth-interval).
"""

import jax
import jax.numpy as jnp


def _has_overflow(grads):
    leaves = jax.tree_util.tree_leaves(grads)
    bad = jnp.zeros((), jnp.bool_)
    for g in leaves:
        bad = bad | ~jnp.all(jnp.isfinite(g.astype(jnp.float32)))
    return bad


class LossScaler(object):
    """Static loss scaler (reference loss_scaler.py LossScaler)."""

    def __init__(self, scale=1.0):
        self.cur_scale = scale

    def has_overflow(self, params):
        return False

    def update_scale(self, overflow):
        pass

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, grads):
        return jax.tree_util.tree_map(lambda g: g * self.loss_scale, grads)

    def backward(self, loss):
        return loss * self.loss_scale


class DynamicLossScaler(object):
    """Dynamic loss scaler (reference loss_scaler.py DynamicLossScaler:
    backoff 0.5 on overflow, x2 every ``scale_window`` clean steps)."""

    def __init__(self, init_scale=2 ** 32, scale_factor=2.0, scale_window=1000):
        self.cur_scale = init_scale
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window

    def has_overflow(self, grads):
        return bool(_has_overflow(grads))

    def update_scale(self, overflow):
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1)
            self.last_overflow_iter = self.cur_iter
        else:
            if (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
                self.cur_scale *= self.scale_factor
        self.cur_iter += 1

    @property
    def loss_scale(self):
        return self.cur_scale

    def scale_gradient(self, grads):
        return jax.tree_util.tree_map(lambda g: g * self.loss_scale, grads)

    def backward(self, loss):
        return loss * self.loss_scale
