"""Legacy manual mixed-precision helpers.

Parity: reference apex/fp16_utils/fp16util.py (189 LoC): ``network_to_half``,
``convert_network``, ``prep_param_lists``, ``master_params_to_model_params``,
``model_grads_to_master_grads``, ``to_python_float``.

In JAX a "network" is its parameter pytree; conversion helpers are tree
casts. bf16 is the TPU-native half type; fp16 is accepted for parity.
"""

import jax
import jax.numpy as jnp

from apex_tpu.amp.frontend import _is_norm_path


def _cast_leaf(leaf, dtype):
    if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
        return leaf.astype(dtype)
    return leaf


def network_to_half(params, dtype=jnp.bfloat16):
    """Cast all floating params to half precision, keeping norm layers fp32
    (reference fp16util.py network_to_half keeps BN fp32 via BN_convert_float)."""
    return convert_network(params, dtype)


def BN_convert_float(params):
    """Restore norm-layer params to fp32 (reference BN_convert_float)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _cast_leaf(leaf, jnp.float32) if _is_norm_path(path) else leaf,
        params)


def convert_network(params, dtype):
    """Cast params to ``dtype`` except normalization layers
    (reference convert_network, used by amp O2 at _initialize.py:178-184)."""
    def cast(path, leaf):
        if _is_norm_path(path):
            return _cast_leaf(leaf, jnp.float32)
        return _cast_leaf(leaf, dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def prep_param_lists(params, flat_master=False):
    """Return (model_params, fp32 master copies).

    Parity: reference prep_param_lists; with ``flat_master=True`` masters are
    one flat fp32 vector (the reference's _flatten_dense_tensors path).
    """
    model_leaves = jax.tree_util.tree_leaves(params)
    if flat_master:
        flat = jnp.concatenate([p.reshape(-1).astype(jnp.float32) for p in model_leaves])
        return model_leaves, [flat]
    return model_leaves, [jnp.array(p, dtype=jnp.float32, copy=True)
                          for p in model_leaves]  # alias-free masters


def model_grads_to_master_grads(model_grads, master_params, flat_master=False):
    if flat_master:
        return [jnp.concatenate([g.reshape(-1).astype(jnp.float32) for g in model_grads])]
    return [g.astype(jnp.float32) for g in model_grads]


def master_params_to_model_params(model_params, master_params, flat_master=False):
    if flat_master:
        flat = master_params[0]
        outs, off = [], 0
        for p in model_params:
            n = p.size
            outs.append(flat[off:off + n].reshape(p.shape).astype(p.dtype))
            off += n
        return outs
    return [m.astype(p.dtype) for m, p in zip(master_params, model_params)]


def to_python_float(t):
    if hasattr(t, "item"):
        return t.item()
    return float(t)
