"""Shared gating for the Pallas decode kernels (mla_decode, gqa_decode).

One place for the backend/interpret decision and the block-size ladder,
so a fix to backend detection or the divisibility fallback applies to
every kernel at once (the two modules previously carried verbatim
copies differing only in the env-var name)."""

import jax


class PallasGate:
    """Per-kernel enable switch: ``env_var=0`` opts out; interpreter
    mode (tests) wins over backend detection; otherwise TPU-only."""

    def __init__(self, env_var: str):
        self.env_var = env_var
        self.interpret = False

    def force_interpret(self, on: bool):
        self.interpret = bool(on)

    def enabled(self) -> bool:
        import os

        if os.environ.get(self.env_var, "1") == "0":
            return False
        if self.interpret:
            return True
        try:
            return jax.default_backend() == "tpu"
        except Exception:
            return False


def choose_block(cache_len: int, preferred: int):
    """Largest tile size that divides the cache buffer: the preferred
    size, then the 256/128 rungs (a 1280-long buffer should stream in
    256-tiles, not silently lose the kernel), then the whole buffer for
    short caches. None -> no dividing block; caller falls back."""
    if cache_len <= preferred:
        return cache_len
    for b in (preferred, 256, 128):
        if b <= cache_len and cache_len % b == 0:
            return b
    return None
