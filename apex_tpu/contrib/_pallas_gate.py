"""Compat shim: the shared Pallas gating moved to
:mod:`apex_tpu.kernels.registry` (the kernel registry — one code path
deciding pallas-vs-oracle-vs-interpret for every kernel, master switch
``APEX_TPU_KERNELS`` with per-kernel overrides). Import ``PallasGate``
and ``choose_block`` from there; this module re-exports them for the
existing decode-kernel call sites."""

from apex_tpu.kernels.registry import PallasGate, choose_block  # noqa: F401
