"""GroupBatchNorm2d — cudnn_gbn-parity entry point.

Parity: reference apex/contrib/cudnn_gbn/batch_norm.py:44-130
(``GroupBatchNorm2d(num_features, group_size, ...)``: NHWC batch norm
synchronized within ``group_size``-rank groups via peer-memory IPC +
cuDNN-frontend kernels).

TPU design: the peer-memory/IPC plumbing disappears — group sync is a
collective over a mesh sub-axis. Callers lay out the dp axis as
('dp_outer', 'dp_bn') with ``dp_bn`` of size ``group_size`` and this
module reduces Welford stats over ``axis_name`` exactly like
apex_tpu.parallel.SyncBatchNorm (one shared implementation; this class is
the cudnn_gbn-flavored constructor, like contrib groupbn's
BatchNorm2d_NHWC is the groupbn-flavored one).
"""

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


class GroupBatchNorm2d(nn.Module):
    """NHWC group batch norm (reference cudnn_gbn/batch_norm.py:44).

    ``group_size`` is carried for API parity; the actual group is the mesh
    axis named ``axis_name`` (size must equal group_size when both given).
    """

    num_features: int
    group_size: int = 1
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = "dp_bn"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, use_running_average: Optional[bool] = None):
        if x.ndim != 4:
            raise ValueError(f"expected 4D NHWC input (got {x.ndim}D input)")
        if x.shape[-1] != self.num_features:
            raise ValueError(
                f"expected {self.num_features} channels, got {x.shape[-1]}")
        axis = self.axis_name if self.group_size != 1 else None
        if axis is not None:
            from jax import lax

            try:
                axis_size = lax.axis_size(axis)
            except Exception:
                axis_size = None  # axis unbound (eager/single-device use)
            if axis_size is not None and axis_size != self.group_size:
                raise ValueError(
                    f"GroupBatchNorm2d: mesh axis '{axis}' has size "
                    f"{axis_size} but group_size={self.group_size}")
        # torch-style momentum (weight of the NEW stat) -> flax-style
        # momentum (weight of the OLD running stat)
        return SyncBatchNorm(
            axis_name=axis, momentum=1.0 - self.momentum, epsilon=self.eps,
            dtype=self.dtype, use_bias=self.affine, use_scale=self.affine,
            name="bn")(x, use_running_average=use_running_average)
