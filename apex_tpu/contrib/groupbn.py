"""Group BatchNorm (NHWC) with fused add+relu.

Parity: reference apex/contrib/groupbn (batch_norm.py:225 ``BatchNorm2d_NHWC``
+ ~7k LoC CUDA incl. inter-GPU IPC) and apex/contrib/cudnn_gbn: NHWC batch
norm synchronized within groups of ranks ("bn_group"), fused elementwise
add + relu epilogues.

TPU design: NHWC is the native layout; group sync = psum over a sub-axis
of the dp mesh axis (callers split 'dp' into ('dp_outer', 'dp_bn') and
pass ``axis_name='dp_bn'``). The IPC machinery disappears — ICI collectives
do the exchange.
"""

from typing import Optional

import flax.linen as nn

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm


class BatchNorm2d_NHWC(SyncBatchNorm):
    """NHWC group batch norm (reference groupbn/batch_norm.py:225).

    ``fuse_relu`` and the additive ``z`` input mirror the reference's
    bn_add_relu path. ``bn_group`` > 1 maps to syncing over ``axis_name``.
    """

    fuse_relu: bool = False
    bn_group: int = 1

    @nn.compact
    def __call__(self, x, z=None, use_running_average: Optional[bool] = None):
        axis = self.axis_name if self.bn_group != 1 else None
        # Re-dispatch through SyncBatchNorm with group-limited axis.
        return SyncBatchNorm(
            use_running_average=self.use_running_average,
            axis_name=axis, momentum=self.momentum, epsilon=self.epsilon,
            dtype=self.dtype, param_dtype=self.param_dtype,
            use_bias=self.use_bias, use_scale=self.use_scale,
            fuse_relu=self.fuse_relu, name="bn")(
                x, use_running_average=use_running_average, z=z)
