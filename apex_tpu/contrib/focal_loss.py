"""Fused focal loss.

Parity: reference apex/contrib/focal_loss (focal_loss.py:60 +
csrc/focal_loss) — ``focal_loss_forward`` over class logits for detection
workloads: FL(p_t) = -alpha_t (1-p_t)^gamma log(p_t), with label smoothing.
"""

import jax
import jax.numpy as jnp


def focal_loss(cls_output, cls_targets_at_level, num_positives_sum,
               num_real_classes, alpha=0.25, gamma=2.0, label_smoothing=0.0):
    """Sigmoid focal loss (reference focal_loss.py semantics).

    cls_output: [..., num_classes] logits; targets: [...] int class ids with
    -1/-2 conventions: <0 means ignore (-2) or background (-1).
    Returns scalar loss normalized by num_positives_sum.
    """
    num_classes = cls_output.shape[-1]
    valid = cls_targets_at_level >= -1
    t = jnp.clip(cls_targets_at_level, 0, num_real_classes - 1)
    onehot = jax.nn.one_hot(t, num_classes, dtype=jnp.float32)
    onehot = jnp.where((cls_targets_at_level >= 0)[..., None], onehot, 0.0)
    if label_smoothing > 0:
        onehot = onehot * (1 - label_smoothing) + label_smoothing / num_classes
    x = cls_output.astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * onehot + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * onehot + (1 - p) * (1 - onehot)
    alpha_t = alpha * onehot + (1 - alpha) * (1 - onehot)
    fl = alpha_t * jnp.power(1 - p_t, gamma) * ce
    fl = jnp.where(valid[..., None], fl, 0.0)
    return jnp.sum(fl) / num_positives_sum


class FocalLoss:
    @staticmethod
    def apply(*args, **kwargs):
        return focal_loss(*args, **kwargs)
