"""Fused Conv+Bias[+ReLU/+Mask] ops.

Parity: reference apex/contrib/conv_bias_relu/conv_bias_relu.py (81 LoC +
csrc/conv_bias_relu.cpp 1,639 LoC of cuDNN-frontend fusion): four NHWC
ops — ConvBiasReLU, ConvBias, ConvBiasMaskReLU, ConvFrozenScaleBiasReLU —
each a conv2d with epilogue fused into one kernel.

TPU design: ``lax.conv_general_dilated`` in NHWC with the epilogue
expressed inline; XLA fuses bias/scale/relu/mask into the convolution the
same way the cuDNN runtime-fusion engine does, and the MXU executes the
conv. Weights are OHWI ([out, kh, kw, in]) to match NHWC activations.
"""

import jax.numpy as jnp
from jax import lax

_DN = ("NHWC", "OHWI", "NHWC")


def _conv(x, weight, padding, stride):
    pad = ((padding, padding), (padding, padding))
    return lax.conv_general_dilated(
        x, weight, window_strides=(stride, stride), padding=pad,
        dimension_numbers=_DN, preferred_element_type=jnp.float32)


def conv_bias_relu(x, weight, bias, padding, stride):
    """ReLU(conv(x, w) + b) (reference ConvBiasReLU)."""
    out = _conv(x, weight, padding, stride) + bias.astype(jnp.float32)
    return jnp.maximum(out, 0.0).astype(x.dtype)


def conv_bias(x, weight, bias, padding, stride):
    """conv(x, w) + b (reference ConvBias)."""
    out = _conv(x, weight, padding, stride) + bias.astype(jnp.float32)
    return out.astype(x.dtype)


def conv_bias_mask_relu(x, weight, bias, mask, padding, stride):
    """ReLU((conv(x, w) + b) * mask) (reference ConvBiasMaskReLU)."""
    out = _conv(x, weight, padding, stride) + bias.astype(jnp.float32)
    out = out * mask.astype(jnp.float32)
    return jnp.maximum(out, 0.0).astype(x.dtype)


def conv_frozen_scale_bias_relu(x, weight, scale, bias, padding, stride):
    """ReLU(conv(x, w) * scale + b) — frozen-BN folding
    (reference ConvFrozenScaleBiasReLU)."""
    out = _conv(x, weight, padding, stride)
    out = out * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return jnp.maximum(out, 0.0).astype(x.dtype)


# reference exports capitalized autograd-function aliases
ConvBiasReLU = conv_bias_relu
ConvBias = conv_bias
ConvBiasMaskReLU = conv_bias_mask_relu
ConvFrozenScaleBiasReLU = conv_frozen_scale_bias_relu
