"""Fused gradient clipping.

Parity: reference apex/contrib/clip_grad/clip_grad.py:128 —
``clip_grad_norm_`` drop-in built on multi_tensor_l2norm + multi_tensor_scale.
Functional on TPU: returns (clipped_grads, total_norm).
"""

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_l2norm, multi_tensor_scale


def clip_grad_norm_(grads, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """Clip a grad pytree by global norm; returns (new_grads, total_norm).

    ``error_if_nonfinite`` (torch parity, and — unlike the previous
    revision — actually honored):

    - ``True``: raise :class:`~apex_tpu.resilience.NonFiniteError` when
      ``total_norm`` is non-finite. Raising needs a concrete value, so
      this mode is eager-only; called under ``jit`` it raises a
      ``ValueError`` at trace time pointing at the in-graph
      alternatives.
    - ``False`` (default): a non-finite ``total_norm`` leaves the
      gradients **unclipped** instead of scaling every leaf by
      NaN/``max_norm/inf`` — the poison then stays visible to
      ``resilience.guarded_update``, which is the jit-native place to
      skip the step.
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if norm_type == 2.0:
        total_norm, _ = multi_tensor_applier(
            multi_tensor_l2norm, jnp.zeros(()), [leaves])
    elif norm_type == float("inf"):
        total_norm = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in leaves]))
    else:
        total_norm = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(l.astype(jnp.float32)), norm_type))
                for l in leaves), 1.0 / norm_type)
    norm_is_finite = jnp.isfinite(total_norm)
    if error_if_nonfinite:
        try:
            concrete_finite = bool(norm_is_finite)
        except jax.errors.TracerBoolConversionError as e:
            raise ValueError(
                "clip_grad_norm_(error_if_nonfinite=True) must run "
                "eagerly — raising needs a concrete norm. Inside jit, "
                "use error_if_nonfinite=False (non-finite norms fall "
                "back to unclipped grads) and skip the step with "
                "apex_tpu.resilience.guarded_update") from e
        if not concrete_finite:
            from apex_tpu.resilience.guard import NonFiniteError

            raise NonFiniteError(
                f"clip_grad_norm_: total norm of order {norm_type} is "
                f"non-finite ({float(jnp.asarray(total_norm))}); set "
                "error_if_nonfinite=False to fall back to unclipped "
                "gradients")
    clip_coef = max_norm / (total_norm + 1e-6)
    # non-finite norm => coefficient 1.0 (leave grads untouched), never
    # a NaN broadcast into every parameter's gradient
    clip_coef = jnp.where(norm_is_finite,
                          jnp.minimum(clip_coef, 1.0), 1.0)
    outs, _ = multi_tensor_applier(
        multi_tensor_scale, jnp.zeros(()), [leaves, leaves], clip_coef)
    return jax.tree_util.tree_unflatten(treedef, outs), total_norm
