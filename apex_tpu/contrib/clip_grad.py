"""Fused gradient clipping.

Parity: reference apex/contrib/clip_grad/clip_grad.py:128 —
``clip_grad_norm_`` drop-in built on multi_tensor_l2norm + multi_tensor_scale.
Functional on TPU: returns (clipped_grads, total_norm).
"""

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_l2norm, multi_tensor_scale


def clip_grad_norm_(grads, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """Clip a grad pytree by global norm; returns (new_grads, total_norm)."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if norm_type == 2.0:
        total_norm, _ = multi_tensor_applier(
            multi_tensor_l2norm, jnp.zeros(()), [leaves])
    elif norm_type == float("inf"):
        total_norm = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in leaves]))
    else:
        total_norm = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(l.astype(jnp.float32)), norm_type))
                for l in leaves), 1.0 / norm_type)
    clip_coef = max_norm / (total_norm + 1e-6)
    clip_coef = jnp.minimum(clip_coef, 1.0)
    outs, _ = multi_tensor_applier(
        multi_tensor_scale, jnp.zeros(()), [leaves, leaves], clip_coef)
    return jax.tree_util.tree_unflatten(treedef, outs), total_norm
