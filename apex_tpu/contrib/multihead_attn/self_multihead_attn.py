"""Fused self multi-head attention.

Parity: reference apex/contrib/multihead_attn/self_multihead_attn.py (254
LoC + ~8k LoC CUDA/CUTLASS): fused QKV projection, strided-batched GEMM
attention with fused softmax(+dropout), output projection; ``impl`` in
{'fast', 'default'}, optional ``include_norm_add`` (pre-LN + residual add
fused into the block).

TPU design: one flax module; the attention core is the Pallas flash
attention (contrib.fmha) on TPU with the einsum reference elsewhere. Fused
norm-add = FusedLayerNorm + residual in the same jit.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.fmha import flash_attention
from apex_tpu.normalization import FusedLayerNorm


class SelfMultiheadAttn(nn.Module):
    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    separate_qkv_params: bool = False
    mask_additive: bool = False
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key=None, value=None, key_padding_mask=None,
                 need_weights=False, attn_mask=None, is_training=True):
        # query: [s, b, h] (reference layout)
        cfg_h = self.embed_dim
        nh = self.num_heads
        hd = cfg_h // nh
        s, b, _ = query.shape

        residual = query
        if self.include_norm_add:
            query = FusedLayerNorm(normalized_shape=cfg_h,
                                   param_dtype=jnp.float32,
                                   name="lyr_norm")(query.astype(jnp.float32)
                                                    ).astype(query.dtype)

        if self.separate_qkv_params:
            q_w = self.param("q_weight", nn.initializers.xavier_uniform(),
                             (cfg_h, cfg_h), self.param_dtype)
            k_w = self.param("k_weight", nn.initializers.xavier_uniform(),
                             (cfg_h, cfg_h), self.param_dtype)
            v_w = self.param("v_weight", nn.initializers.xavier_uniform(),
                             (cfg_h, cfg_h), self.param_dtype)
            q, k, v = query @ q_w, query @ k_w, query @ v_w
            if self.bias:
                q = q + self.param("q_bias", nn.initializers.zeros,
                                   (cfg_h,), self.param_dtype)
                k = k + self.param("k_bias", nn.initializers.zeros,
                                   (cfg_h,), self.param_dtype)
                v = v + self.param("v_bias", nn.initializers.zeros,
                                   (cfg_h,), self.param_dtype)
        else:
            qkv_w = self.param("qkv_weight", nn.initializers.xavier_uniform(),
                               (cfg_h, 3 * cfg_h), self.param_dtype)
            qkv = query @ qkv_w
            if self.bias:
                qkv = qkv + self.param("qkv_bias", nn.initializers.zeros,
                                       (3 * cfg_h,), self.param_dtype)
            q, k, v = jnp.split(qkv, 3, axis=-1)

        # [s, b, h] -> [b, nh, s, hd]
        def to_heads(x):
            return x.reshape(s, b, nh, hd).transpose(1, 2, 0, 3)

        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        scale = 1.0 / (hd ** 0.5)

        # flash path has no dropout hook; route through the einsum path
        # whenever attention dropout must actually be applied
        use_flash = (attn_mask is None and key_padding_mask is None
                     and self.impl == "fast"
                     and not (self.dropout > 0 and is_training))
        if use_flash:
            ctx = flash_attention(qh, kh, vh, False, scale)
        else:
            scores = jnp.einsum("bnqd,bnkd->bnqk",
                                qh.astype(jnp.float32),
                                kh.astype(jnp.float32)) * scale
            if attn_mask is not None:
                if self.mask_additive:
                    scores = scores + attn_mask.astype(jnp.float32)
                else:
                    scores = jnp.where(attn_mask.astype(bool), -10000.0, scores)
            if key_padding_mask is not None:
                scores = jnp.where(
                    key_padding_mask[:, None, None, :].astype(bool),
                    -10000.0, scores)
            probs = jax.nn.softmax(scores, axis=-1)
            if self.dropout > 0 and is_training:
                probs = nn.Dropout(self.dropout, deterministic=not is_training)(probs)
            ctx = jnp.einsum("bnqk,bnkd->bnqd", probs,
                             vh.astype(jnp.float32)).astype(query.dtype)

        out = ctx.transpose(2, 0, 1, 3).reshape(s, b, cfg_h)
        out_w = self.param("out_proj_weight", nn.initializers.xavier_uniform(),
                           (cfg_h, cfg_h), self.param_dtype)
        out = out @ out_w
        if self.bias:
            out = out + self.param("out_proj_bias", nn.initializers.zeros,
                                   (cfg_h,), self.param_dtype)
        if self.include_norm_add:
            out = out + residual
        return (out, None) if need_weights else out
