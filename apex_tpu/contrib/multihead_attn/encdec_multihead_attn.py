"""Encoder-decoder multi-head attention.

Parity: reference apex/contrib/multihead_attn/encdec_multihead_attn.py —
Q from the decoder stream, fused KV projection from the encoder stream.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.fmha import flash_attention
from apex_tpu.normalization import FusedLayerNorm


class EncdecMultiheadAttn(nn.Module):
    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, query, key, value=None, key_padding_mask=None,
                 need_weights=False, attn_mask=None, is_training=True):
        h = self.embed_dim
        nh = self.num_heads
        hd = h // nh
        sq, b, _ = query.shape
        sk = key.shape[0]

        residual = query
        if self.include_norm_add:
            query = FusedLayerNorm(normalized_shape=h, param_dtype=jnp.float32,
                                   name="lyr_norm")(query.astype(jnp.float32)
                                                    ).astype(query.dtype)

        q_w = self.param("q_weight", nn.initializers.xavier_uniform(),
                         (h, h), self.param_dtype)
        kv_w = self.param("kv_weight", nn.initializers.xavier_uniform(),
                          (h, 2 * h), self.param_dtype)
        q = query @ q_w
        kv = key @ kv_w
        if self.bias:
            q = q + self.param("q_bias", nn.initializers.zeros,
                               (h,), self.param_dtype)
            kv = kv + self.param("kv_bias", nn.initializers.zeros,
                                 (2 * h,), self.param_dtype)
        k, v = jnp.split(kv, 2, axis=-1)

        def to_heads(x, s):
            return x.reshape(s, b, nh, hd).transpose(1, 2, 0, 3)

        qh = to_heads(q, sq)
        kh = to_heads(k, sk)
        vh = to_heads(v, sk)
        scale = 1.0 / (hd ** 0.5)

        # flash path has no dropout hook; use the einsum path when
        # attention dropout is live (reference applies dropout in the
        # fused attn kernel, encdec_multihead_attn_func.py)
        use_flash = (attn_mask is None and key_padding_mask is None
                     and sq == sk
                     and not (self.dropout > 0 and is_training))
        if use_flash:
            ctx = flash_attention(qh, kh, vh, False, scale)
        else:
            scores = jnp.einsum("bnqd,bnkd->bnqk", qh.astype(jnp.float32),
                                kh.astype(jnp.float32)) * scale
            if attn_mask is not None:
                scores = jnp.where(attn_mask.astype(bool), -10000.0, scores)
            if key_padding_mask is not None:
                scores = jnp.where(
                    key_padding_mask[:, None, None, :].astype(bool),
                    -10000.0, scores)
            probs = jax.nn.softmax(scores, axis=-1)
            if self.dropout > 0 and is_training:
                probs = nn.Dropout(self.dropout,
                                   deterministic=not is_training)(probs)
            ctx = jnp.einsum("bnqk,bnkd->bnqd", probs,
                             vh.astype(jnp.float32)).astype(query.dtype)

        out = ctx.transpose(2, 0, 1, 3).reshape(sq, b, h)
        out_w = self.param("out_proj_weight", nn.initializers.xavier_uniform(),
                           (h, h), self.param_dtype)
        out = out @ out_w
        if self.bias:
            out = out + self.param("out_proj_bias", nn.initializers.zeros,
                                   (h,), self.param_dtype)
        if self.include_norm_add:
            out = out + residual
        return (out, None) if need_weights else out
