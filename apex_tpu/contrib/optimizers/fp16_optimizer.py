"""Deprecated contrib FP16_Optimizer (reference apex/contrib/optimizers/
fp16_optimizer.py, 243 LoC — the variant amp's check recognizes at
_initialize.py:16). Defers to apex_tpu.fp16_utils.FP16_Optimizer."""

import warnings

from apex_tpu.fp16_utils.fp16_optimizer import (
    FP16_Optimizer as _FP16_Optimizer,
)


class FP16_Optimizer(_FP16_Optimizer):
    def __init__(self, *args, **kwargs):
        warnings.warn(
            "apex_tpu.contrib.optimizers.FP16_Optimizer is deprecated; use "
            "apex_tpu.fp16_utils.FP16_Optimizer", DeprecationWarning,
            stacklevel=2)
        super().__init__(*args, **kwargs)
