"""DistributedFusedLAMB — ZeRO-sharded LAMB over the data-parallel axis.

Parity: reference apex/contrib/optimizers/distributed_fused_lamb.py
(1,061 LoC): allreduce-hook-driven flat buffers, fused L2 norms,
clip-after-allreduce, per-layer trust ratios on sharded state.

TPU design: like :class:`DistributedFusedAdam` (reduce-scatter ->
shard update -> all-gather) plus LAMB's per-*tensor* norms, computed on
the flat shards with a static segment-id map and completed with one psum:
``segment_sum(local shard) -> psum over dp -> full per-tensor norms``.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    DistributedFusedAdam as _Adam,
    _as_segments,
    _flatten_f32,
    _padded_size,
    _unflatten_like,
    zero_state_bytes,
)
from apex_tpu.parallel import compression
from apex_tpu.telemetry import comm as _telemetry_comm
from apex_tpu.telemetry import numerics as _numerics
from apex_tpu.telemetry import trace as _telemetry_trace
from apex_tpu.transformer.tensor_parallel.mappings import _axis_size


class DistributedFusedLAMB:
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-6, weight_decay=0.01, max_grad_norm=1.0,
                 adam_w_mode=True, grad_averaging=True, use_nvlamb=False,
                 clip_after_ar=True, axis_name: str = "dp",
                 compress: bool = False,
                 grad_compress=None, param_compress=None,
                 compress_block_size: int = compression.BLOCK_SIZE,
                 numerics=None, overlap: bool = False,
                 message_size: int = 10000000):
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        self.clip_after_ar = clip_after_ar
        self.axis_name = axis_name
        # Compressed collectives, same policy as DistributedFusedAdam:
        # compress=True -> int8 grads (error feedback in state) + bf16
        # param gather; override per-path via grad_/param_compress.
        # LAMB's grad-norm clip runs on the DEQUANTIZED shard, i.e.
        # after quantization error enters — clip_after_ar semantics.
        if compress and grad_compress is None:
            grad_compress = "int8"
        if compress and param_compress is None:
            param_compress = "bf16"
        self.grad_compress = grad_compress
        self.param_compress = param_compress
        self.compress_block_size = compress_block_size
        # same contract as DistributedFusedAdam: truthy -> ``step``
        # returns (params, state, stats) with stats of the incoming
        # (pre-flatten, pre-compression) grads
        self.numerics = numerics
        # Overlapped mode (parallel/overlap.py): bucket-partitioned
        # state, per-bucket reduce-scatter chains. LAMB's global
        # grad-norm clip is the one cross-bucket coupling — the
        # scatters still interleave with the backward, but with
        # ``max_grad_norm > 0`` every (cheap, scalar-joined) shard
        # update waits for the clip factor; set ``max_grad_norm=0``
        # for strict bucket-i-only data dependence.
        self.overlap = overlap
        self.message_size = message_size

    # -- overlapped mode: the bucket plan + init are layout-only and
    # shared verbatim with DistributedFusedAdam (same master/moment
    # shard cut, same padding math); only the update math is LAMB's
    overlap_plan = _Adam.overlap_plan
    _init_bucket = _Adam._init_bucket
    _init_overlapped = _Adam._init_overlapped

    @property
    def overlap_needs_global_norm(self):
        """True when clipping couples every bucket's update to the
        global grad norm (one scalar join; the scatters stay
        independent)."""
        return bool(self.max_grad_norm and self.max_grad_norm > 0)

    def bucket_reduce(self, flat_g, bstate):
        """Reduce-scatter ONE bucket's padded flat gradient; returns
        ``(local shard — averaged iff grad_averaging, new residual or
        None)``."""
        world = _axis_size(self.axis_name)
        if world == 1:
            return flat_g, bstate.get("grad_residual")
        with _telemetry_trace.span("zero/grad_reduce_scatter",
                                   compress=self.grad_compress or "none",
                                   overlap=True):
            if self.grad_compress is None:
                _telemetry_comm.record_collective(
                    "psum_scatter", elements=flat_g.size,
                    dtype=flat_g.dtype, axis_name=self.axis_name,
                    world=world)
                g_shard = lax.psum_scatter(flat_g, self.axis_name,
                                           tiled=True)
                residual = None
            else:
                g_shard, residual = compression.psum_scatter_compressed(
                    flat_g, self.axis_name, mode=self.grad_compress,
                    residual=bstate.get("grad_residual"),
                    block_size=self.compress_block_size)
        if self.grad_averaging:
            g_shard = g_shard / world
        return g_shard, residual

    def overlap_global_clip(self, g_shards):
        """The clip factor from the GLOBAL grad norm: per-bucket local
        sums of squares joined into one scalar psum — sum-of-squares
        partitions exactly over buckets, so the value matches the
        monolithic step's up to fp32 summation order."""
        world = _axis_size(self.axis_name)
        gsq = jnp.zeros((), jnp.float32)
        for g in g_shards:
            gsq = gsq + jnp.sum(jnp.square(g))
        if world > 1:
            gsq = lax.psum(gsq, self.axis_name)
        gnorm = jnp.sqrt(gsq)
        if self.max_grad_norm and self.max_grad_norm > 0:
            return jnp.maximum(gnorm / self.max_grad_norm, 1.0)
        return jnp.asarray(1.0, jnp.float32)

    def _lamb_mvu(self, g_shard, p, lstate, *, step):
        """The fused LAMB moment + raw-update pass — ONE multi-tensor
        kernel call per shard/bucket
        (:func:`apex_tpu.kernels.optim.fused_lamb_mvu`; the jnp oracle
        is byte-for-byte the math this class used to inline). The
        per-tensor trust ratio stays with the caller: it couples the
        whole shard through the segment-norm scalar join."""
        from apex_tpu.kernels import optim as _koptim

        b1, b2 = self.betas
        beta3 = (1 - b1) if self.grad_averaging else 1.0
        bc1 = 1.0 - b1 ** step if self.bias_correction else 1.0
        bc2 = 1.0 - b2 ** step if self.bias_correction else 1.0
        return _koptim.fused_lamb_mvu(
            g_shard, p, lstate["exp_avg_shard"],
            lstate["exp_avg_sq_shard"], bc1=bc1, bc2=bc2, b1=b1, b2=b2,
            beta3=beta3, eps=self.eps, weight_decay=self.weight_decay,
            adam_w=bool(self.adam_w_mode))

    def _bucket_segments(self, bucket, p_leaves):
        """Static per-tensor segment ids for one bucket's padded flat
        vector, shard-major — the bucket-local analog of
        :meth:`_layout`'s map (pad -> segment T)."""
        world = _axis_size(self.axis_name)
        sizes = [int(np.prod(l.shape)) for l in p_leaves]
        seg = np.repeat(np.arange(len(sizes)), sizes)
        seg = np.concatenate([seg, np.full(bucket.padded - bucket.n,
                                           len(sizes))])
        return seg.reshape(world, bucket.padded // world), len(sizes)

    def bucket_update_gather(self, g_shard, bstate, bucket, p_leaves, *,
                             lr=None, step, noop, clip=None,
                             new_residual=None):
        """Sharded LAMB update (per-tensor trust ratios computed from
        this bucket's own segment map) + param all-gather for ONE
        bucket. ``clip`` is the global factor from
        :meth:`overlap_global_clip` (None -> no clipping)."""
        lr = self.lr if lr is None else lr
        world = _axis_size(self.axis_name)
        seg_shards, T = self._bucket_segments(bucket, p_leaves)
        if clip is not None:
            g_shard = g_shard / clip
        p = bstate["master_shard"]
        m, v, update = self._lamb_mvu(g_shard, p, bstate, step=step)

        w_sq = self._per_tensor_sq(p, seg_shards, world, T)
        u_sq = self._per_tensor_sq(update, seg_shards, world, T)
        w_norm = jnp.sqrt(w_sq)
        u_norm = jnp.sqrt(u_sq)
        if (self.weight_decay != 0) or self.use_nvlamb:
            ratio_t = jnp.where((w_norm > 0) & (u_norm > 0),
                                w_norm / u_norm, 1.0)
        else:
            ratio_t = jnp.ones((T,), jnp.float32)
        if world > 1:
            rank = lax.axis_index(self.axis_name)
            seg_local = jnp.asarray(seg_shards)[rank]
        else:
            seg_local = jnp.asarray(seg_shards).reshape(-1)
        ratio = jnp.concatenate(
            [ratio_t, jnp.ones((1,), jnp.float32)])[seg_local]

        p_new = p - lr * ratio * update
        keep = noop > 0
        p_new = jnp.where(keep, p, p_new)
        m = jnp.where(keep, bstate["exp_avg_shard"], m)
        v = jnp.where(keep, bstate["exp_avg_sq_shard"], v)

        if world > 1:
            with _telemetry_trace.span("zero/param_all_gather",
                                       compress=self.param_compress
                                       or "none", overlap=True):
                if self.param_compress is None:
                    _telemetry_comm.record_collective(
                        "all_gather", elements=p_new.size,
                        dtype=p_new.dtype, axis_name=self.axis_name,
                        world=world)
                    flat_p = lax.all_gather(p_new, self.axis_name,
                                            tiled=True)
                else:
                    flat_p = compression.all_gather_compressed(
                        p_new, self.axis_name, mode=self.param_compress,
                        block_size=self.compress_block_size)
        else:
            flat_p = p_new
        new_bstate = {"master_shard": p_new, "exp_avg_shard": m,
                      "exp_avg_sq_shard": v}
        if compression.needs_residual(self.grad_compress):
            new_bstate["grad_residual"] = jnp.where(
                keep, bstate["grad_residual"], new_residual)
        from apex_tpu.parallel.distributed import unflatten

        new_leaves = unflatten(flat_p[:bucket.n], p_leaves)
        return new_leaves, new_bstate

    def _step_overlapped(self, grads, state, params, *, lr, found_inf,
                         scale):
        lr = self.lr if lr is None else lr
        g_segs, was_list = _as_segments(grads)
        p_segs, _ = _as_segments(params)
        plan = self.overlap_plan(p_segs)
        noop = (jnp.zeros((), jnp.float32) if found_inf is None
                else jnp.asarray(found_inf, jnp.float32))
        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        # phase 1: every bucket's reduce-scatter (independent chains)
        reduced = []
        for k, (grads_k, seg_plan) in enumerate(zip(g_segs, plan)):
            g_leaves = jax.tree_util.tree_leaves(grads_k)
            for bi, bucket in enumerate(seg_plan):
                bstate = state["buckets"][k][bi]
                flat_g = jnp.concatenate(
                    [g_leaves[i].reshape(-1).astype(jnp.float32)
                     for i in bucket.leaf_idx]) / scale
                flat_g = jnp.pad(flat_g, (0, bucket.padded - bucket.n))
                g_shard, new_residual = self.bucket_reduce(flat_g, bstate)
                reduced.append((k, bi, bucket, g_shard, new_residual))
        # phase 2: the one scalar join (global clip), then per-bucket
        # updates + gathers
        clip = (self.overlap_global_clip([g for *_, g, _ in reduced])
                if self.overlap_needs_global_norm else None)
        new_leaves_by_seg = [list(jax.tree_util.tree_leaves(p))
                             for p in p_segs]
        new_buckets = [[None] * len(seg_plan)
                       for seg_plan in plan]
        for k, bi, bucket, g_shard, new_residual in reduced:
            p_leaves = new_leaves_by_seg[k]
            bstate = state["buckets"][k][bi]
            new_leaves, nb = self.bucket_update_gather(
                g_shard, bstate, bucket,
                [p_leaves[i] for i in bucket.leaf_idx],
                lr=lr, step=step, noop=noop, clip=clip,
                new_residual=new_residual)
            for i, leaf in zip(bucket.leaf_idx, new_leaves):
                p_leaves[i] = leaf
            new_buckets[k][bi] = nb
        new_params = [
            jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(p_segs[k]),
                new_leaves_by_seg[k])
            for k in range(len(p_segs))]
        new_state = {"step": step,
                     "buckets": tuple(tuple(seg) for seg in new_buckets)}
        out_params = new_params if was_list else new_params[0]
        if self.numerics:
            stats = {}
            depth = (_numerics.default_prefix_depth()
                     if self.numerics is True else int(self.numerics))
            for grads_k in g_segs:
                stats.update(_numerics.tree_stats(
                    grads_k, prefix_depth=depth, prefix="grads"))
            return out_params, new_state, stats
        return out_params, new_state

    def _grad_stats(self, grads):
        depth = (_numerics.default_prefix_depth() if self.numerics is True
                 else int(self.numerics))
        return _numerics.tree_stats(grads, prefix_depth=depth,
                                    prefix="grads")

    def state_bytes(self, params, *, world=None, registry=None,
                    record=True):
        """Per-device sharded vs unsharded optimizer-state bytes for
        ``params`` at ``world``-way ZeRO sharding (default: the bound
        axis size, or 1 outside shard_map — pass ``world=`` host-side).
        See :func:`~apex_tpu.contrib.optimizers.distributed_fused_adam.
        zero_state_bytes`."""
        if world is None:
            world = _axis_size(self.axis_name)
        return zero_state_bytes(
            params, world=world, grad_compress=self.grad_compress,
            param_compress=self.param_compress,
            block_size=self.compress_block_size,
            axis_name=self.axis_name, optimizer="DistributedFusedLAMB",
            registry=registry, record=record)

    # -- elastic re-sharding: same flat layout as DistributedFusedAdam
    # (master/moment shards + optional full-length EF residual), so the
    # same consolidate/reshard math applies verbatim

    # topology / consolidation / re-sharding dispatch shared verbatim
    # with DistributedFusedAdam (same flat + bucket + 2-D layouts;
    # ``type(self).__name__`` stamps the right optimizer name)
    topology = _Adam.topology
    state_dict_full = _Adam.state_dict_full
    load_state_dict_resharded = _Adam.load_state_dict_resharded

    def _layout(self, params):
        leaves = jax.tree_util.tree_leaves(params)
        sizes = [int(np.prod(l.shape)) for l in leaves]
        n = sum(sizes)
        world = _axis_size(self.axis_name)
        # shard boundaries must land on quantization-block boundaries
        padded = _padded_size(n, world, self.grad_compress,
                              self.param_compress,
                              self.compress_block_size)
        # static segment ids over the padded flat vector (pad -> segment T)
        seg = np.repeat(np.arange(len(sizes)), sizes)
        seg = np.concatenate([seg, np.full(padded - n, len(sizes))])
        return n, padded, world, len(sizes), seg

    def _shard_segments(self, seg, padded, world):
        return seg.reshape(world, padded // world)

    def init(self, params):
        if self.overlap:
            return self._init_overlapped(params)
        n, padded, world, T, seg = self._layout(params)
        flat = jnp.pad(_flatten_f32(params), (0, padded - n))
        if world > 1:
            rank = lax.axis_index(self.axis_name)
            shard = lax.dynamic_slice_in_dim(flat, rank * (padded // world),
                                             padded // world)
        else:
            shard = flat
        state = {
            "step": jnp.zeros((), jnp.int32),
            "master_shard": shard,
            "exp_avg_shard": jnp.zeros_like(shard),
            "exp_avg_sq_shard": jnp.zeros_like(shard),
        }
        if compression.needs_residual(self.grad_compress):
            state["grad_residual"] = jnp.zeros((padded,), jnp.float32)
        return state

    def _per_tensor_sq(self, x_shard, seg_shards, world, T):
        """Per-tensor sum-of-squares from a local flat shard + psum."""
        if world > 1:
            rank = lax.axis_index(self.axis_name)
            seg_local = jnp.asarray(seg_shards)[rank]
        else:
            seg_local = jnp.asarray(seg_shards).reshape(-1)
        partial = jax.ops.segment_sum(jnp.square(x_shard), seg_local,
                                      num_segments=T + 1)
        if world > 1:
            partial = lax.psum(partial, self.axis_name)
        return partial[:T]

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        if self.overlap:
            return self._step_overlapped(grads, state, params, lr=lr,
                                         found_inf=found_inf,
                                         scale=scale)
        lr = self.lr if lr is None else lr
        stats = self._grad_stats(grads) if self.numerics else None
        n, padded, world, T, seg = self._layout(params)
        seg_shards = self._shard_segments(seg, padded, world)
        noop = (jnp.zeros((), jnp.float32) if found_inf is None
                else jnp.asarray(found_inf, jnp.float32))

        flat_g = _flatten_f32(grads) / scale
        flat_g = jnp.pad(flat_g, (0, padded - n))
        grad_residual = state.get("grad_residual")
        if world > 1:
            with _telemetry_trace.span("zero/grad_reduce_scatter",
                                       compress=self.grad_compress
                                       or "none"):
                if self.grad_compress is None:
                    _telemetry_comm.record_collective(
                        "psum_scatter", elements=flat_g.size,
                        dtype=flat_g.dtype, axis_name=self.axis_name,
                        world=world)
                    g_shard = lax.psum_scatter(flat_g, self.axis_name,
                                               tiled=True)
                else:
                    g_shard, grad_residual = \
                        compression.psum_scatter_compressed(
                            flat_g, self.axis_name,
                            mode=self.grad_compress,
                            residual=grad_residual,
                            block_size=self.compress_block_size)
            if self.grad_averaging:
                g_shard = g_shard / world
        else:
            g_shard = flat_g

        # global grad norm + clipping (reference: fused L2 norm then
        # clip-after-allreduce)
        gsq = jnp.sum(jnp.square(g_shard))
        if world > 1:
            gsq = lax.psum(gsq, self.axis_name)
        gnorm = jnp.sqrt(gsq)
        if self.max_grad_norm and self.max_grad_norm > 0:
            clip = jnp.maximum(gnorm / self.max_grad_norm, 1.0)
        else:
            clip = jnp.asarray(1.0, jnp.float32)
        g_shard = g_shard / clip

        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        p = state["master_shard"]
        m, v, update = self._lamb_mvu(g_shard, p, state, step=step)

        # per-tensor trust ratios from sharded norms
        w_sq = self._per_tensor_sq(p, seg_shards, world, T)
        u_sq = self._per_tensor_sq(update, seg_shards, world, T)
        w_norm = jnp.sqrt(w_sq)
        u_norm = jnp.sqrt(u_sq)
        apply_trust = (self.weight_decay != 0) or self.use_nvlamb
        if apply_trust:
            ratio_t = jnp.where((w_norm > 0) & (u_norm > 0),
                                w_norm / u_norm, 1.0)
        else:
            ratio_t = jnp.ones((T,), jnp.float32)
        if world > 1:
            rank = lax.axis_index(self.axis_name)
            seg_local = jnp.asarray(seg_shards)[rank]
        else:
            seg_local = jnp.asarray(seg_shards).reshape(-1)
        ratio = jnp.concatenate([ratio_t, jnp.ones((1,), jnp.float32)])[seg_local]

        p_new = p - lr * ratio * update
        keep = noop > 0
        p_new = jnp.where(keep, p, p_new)
        m = jnp.where(keep, state["exp_avg_shard"], m)
        v = jnp.where(keep, state["exp_avg_sq_shard"], v)

        if world > 1:
            with _telemetry_trace.span("zero/param_all_gather",
                                       compress=self.param_compress
                                       or "none"):
                if self.param_compress is None:
                    _telemetry_comm.record_collective(
                        "all_gather", elements=p_new.size,
                        dtype=p_new.dtype, axis_name=self.axis_name,
                        world=world)
                    flat_p = lax.all_gather(p_new, self.axis_name,
                                            tiled=True)
                else:
                    flat_p = compression.all_gather_compressed(
                        p_new, self.axis_name, mode=self.param_compress,
                        block_size=self.compress_block_size)
        else:
            flat_p = p_new
        new_params = _unflatten_like(flat_p[:n], params)
        new_state = {
            "step": step,
            "master_shard": p_new,
            "exp_avg_shard": m,
            "exp_avg_sq_shard": v,
        }
        if compression.needs_residual(self.grad_compress):
            # overflow-skipped steps drop the bogus quantization error
            new_state["grad_residual"] = jnp.where(
                keep, state["grad_residual"], grad_residual)
        if self.numerics:
            return new_params, new_state, stats
        return new_params, new_state

    # reference-API hooks kept for drop-in use
    def set_global_scale(self, global_scale):
        self._global_scale = global_scale

    def complete_reductions(self):
        """No-op: reductions are part of the jitted step on TPU."""
