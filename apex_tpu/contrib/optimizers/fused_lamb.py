"""Deprecated contrib FusedLAMB (reference apex/contrib/optimizers/
fused_lamb.py, 208 LoC). Defers to apex_tpu.optimizers.FusedLAMB."""

import warnings

from apex_tpu.optimizers.fused_lamb import FusedLAMB as _FusedLAMB


class FusedLAMB(_FusedLAMB):
    def __init__(self, *args, **kwargs):
        warnings.warn(
            "apex_tpu.contrib.optimizers.FusedLAMB is deprecated; use "
            "apex_tpu.optimizers.FusedLAMB", DeprecationWarning, stacklevel=2)
        super().__init__(*args, **kwargs)
