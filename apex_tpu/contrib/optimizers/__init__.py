from apex_tpu.contrib.optimizers.distributed_fused_adam import (  # noqa: F401
    DistributedFusedAdam,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import (  # noqa: F401
    DistributedFusedLAMB,
)
# deprecated set (reference apex/contrib/optimizers/: older duplicates kept
# for backward compatibility; these warn and defer to apex_tpu.optimizers)
from apex_tpu.contrib.optimizers.fused_adam import FusedAdam  # noqa: F401
from apex_tpu.contrib.optimizers.fused_lamb import FusedLAMB  # noqa: F401
from apex_tpu.contrib.optimizers.fused_sgd import FusedSGD  # noqa: F401
from apex_tpu.contrib.optimizers.fp16_optimizer import (  # noqa: F401
    FP16_Optimizer,
)
