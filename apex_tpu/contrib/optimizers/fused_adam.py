"""Deprecated contrib FusedAdam.

Parity: reference apex/contrib/optimizers/fused_adam.py (206 LoC) — an
older FusedAdam kept for backward compatibility; the reference's version
warns and defers behavior to apex.optimizers.FusedAdam. Same here.
"""

import warnings

from apex_tpu.optimizers.fused_adam import FusedAdam as _FusedAdam


class FusedAdam(_FusedAdam):
    def __init__(self, *args, **kwargs):
        warnings.warn(
            "apex_tpu.contrib.optimizers.FusedAdam is deprecated; use "
            "apex_tpu.optimizers.FusedAdam", DeprecationWarning, stacklevel=2)
        # old contrib kwarg names accepted and dropped
        kwargs.pop("use_mt", None)
        kwargs.pop("amp_scale_adjustment", None)
        super().__init__(*args, **kwargs)
