"""Deprecated contrib FusedSGD (reference apex/contrib/optimizers/
fused_sgd.py, 211 LoC). Defers to apex_tpu.optimizers.FusedSGD."""

import warnings

from apex_tpu.optimizers.fused_sgd import FusedSGD as _FusedSGD


class FusedSGD(_FusedSGD):
    def __init__(self, *args, **kwargs):
        warnings.warn(
            "apex_tpu.contrib.optimizers.FusedSGD is deprecated; use "
            "apex_tpu.optimizers.FusedSGD", DeprecationWarning, stacklevel=2)
        super().__init__(*args, **kwargs)
