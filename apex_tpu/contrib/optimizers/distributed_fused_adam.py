"""DistributedFusedAdam — ZeRO-sharded Adam over the data-parallel axis.

Parity: reference apex/contrib/optimizers/distributed_fused_adam.py
(2,075 LoC): parameters/grads flattened into fragments+buckets sharded
across the process group, overlapped reduce-scatter grad sync, param
all-gather, fp32 master shards.

TPU design: the bucket machinery collapses to three collectives inside one
jitted step:
  1. flatten grads -> ``lax.psum_scatter`` over 'dp' (the overlapped
     reduce-scatter),
  2. fused Adam update on the local fp32 master/moment shard (1/dp of the
     state per device — the ZeRO memory saving),
  3. ``lax.all_gather`` of the updated shard back to full params.
XLA's latency-hiding scheduler overlaps (1) with the tail of the backward
when the whole train step is one jit.

Must run inside shard_map with the 'dp' axis bound; falls back to
single-device (no collectives) when the axis is absent.

Expert parallelism: the flat-vector sharding treats each (ep, tp) cell's
local param view independently, and ``step``'s psum_scatter averages over
'dp' alone — correct for expert shards, but *dense* params also replicate
over 'ep'. Pre-average dense grads over 'ep' first::

    grads = all_reduce_gradients(grads, axis_name="ep",
                                 expert_param_predicate=is_expert_param,
                                 expert_axis_name=())   # experts untouched
    params, opt_state = opt.step(grads, opt_state, params)

(total dense averaging = ep here x dp inside = the full replica set).
"""

import warnings
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.parallel import compression
from apex_tpu.telemetry import comm as _telemetry_comm
from apex_tpu.telemetry import numerics as _numerics
from apex_tpu.telemetry import trace as _telemetry_trace
from apex_tpu.transformer.tensor_parallel.mappings import _axis_size


def _flat_size(params):
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)))


def _flatten_f32(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])


def _unflatten_like(flat, params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    outs, off = [], 0
    for l in leaves:
        n = int(np.prod(l.shape))
        outs.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, outs)


def _padded_size(n, world, grad_compress, param_compress, block_size):
    """Padded flat length for ``world``-way sharding: shards must be
    equal-length, and int8 modes additionally need every rank's shard
    to cover whole quantization blocks."""
    align = world
    if any(m in ("int8", "int4") for m in (grad_compress, param_compress)):
        align *= block_size
    return ((n + align - 1) // align) * align


def _global_flat(arr, padded, world, name):
    """Normalize one ZeRO state buffer to the host-global ``(padded,)``
    flat vector: accepts the ``out_specs=P(axis)`` concatenation
    (already ``(padded,)``), the ``(world, padded // world)`` per-rank
    stack, or a single-device ``(padded,)`` shard. Rank order is the
    concatenation order either way (``init`` slices rank*shard_len)."""
    a = np.asarray(arr)
    if a.ndim == 2:
        if a.shape != (world, padded // world):
            raise ValueError(
                f"{name}: stacked shards have shape {a.shape}, wanted "
                f"({world}, {padded // world})")
        a = a.reshape(-1)
    if a.shape != (padded,):
        raise ValueError(
            f"{name}: flat length {a.shape} does not match the padded "
            f"length {padded} for world={world} — wrong world, or a "
            f"state written with different compression alignment?")
    return a


def consolidate_zero_state(state, params, *, world, grad_compress=None,
                           param_compress=None,
                           block_size=compression.BLOCK_SIZE,
                           optimizer="zero"):
    """Host-side: the per-rank ZeRO shards -> one full, UNPADDED
    state_dict (the re-shardable canonical form).

    ``state`` is the host-global view of a run's optimizer state: each
    ``*_shard`` leaf either the ``(padded,)`` concatenation of the
    per-rank shards (what the ``out_specs=P(axis)`` carry idiom hands
    the host) or a ``(world, padded // world)`` stack; the per-rank
    full-length EF residual is a ``(world, padded)`` stack. The
    returned dict holds fp32 ``master`` / ``exp_avg`` / ``exp_avg_sq``
    of the *logical* length ``n`` (shard padding stripped — padding is
    a function of the world size and must be recomputed per topology),
    the int8 error-feedback ``grad_residual`` as the SUM over ranks
    (the total pending correction — the only topology-invariant view;
    unpadded, its pad tail being identically zero), and the layout
    metadata an elastic restore needs (``world``, ``block_size``,
    compression modes). Bit-exact: values are copied, never
    re-quantized or re-rounded."""
    n = _flat_size(params)
    padded = _padded_size(n, world, grad_compress, param_compress,
                          block_size)
    full = {
        "format": 1,
        "optimizer": optimizer,
        "world": int(world),
        "n_elements": n,
        "block_size": int(block_size),
        "grad_compress": grad_compress,
        "param_compress": param_compress,
        "step": np.asarray(state["step"], np.int32).reshape(()),
    }
    for src, dst in (("master_shard", "master"),
                     ("exp_avg_shard", "exp_avg"),
                     ("exp_avg_sq_shard", "exp_avg_sq")):
        full[dst] = _global_flat(state[src], padded, world, src)[:n]
    if state.get("grad_residual") is not None:
        full["grad_residual"] = _consolidated_residual(
            state["grad_residual"], padded, world)[:n]
    return full


def _consolidated_residual(res, padded, world):
    """The EF residual is full-length and PER-RANK (each rank's own
    local quantization error), so the host-global carry stacks it on a
    leading world axis. The canonical consolidated form is the SUM over
    ranks — the total pending correction the replica set owes the
    gradients: each rank adds its residual before the psum, so only the
    sum is topology-invariant. Returns the summed ``(padded,)``
    vector."""
    res = np.asarray(res)
    if res.ndim == 2:
        if res.shape != (world, padded):
            raise ValueError(
                f"grad_residual: stacked shape {res.shape}, wanted "
                f"({world}, {padded})")
        return res.sum(axis=0)
    if res.shape == (padded,):
        if world != 1:
            raise ValueError(
                f"grad_residual: got one ({padded},) vector for "
                f"world={world} — the per-rank residuals must be "
                f"stacked ({world}, {padded}); a single vector is "
                "only unambiguous at world=1")
        return res
    raise ValueError(
        f"grad_residual: shape {res.shape}, wanted "
        f"({world}, {padded}) or ({padded},) at world=1")


def reshard_zero_state(full, params, *, world, grad_compress=None,
                       param_compress=None,
                       block_size=compression.BLOCK_SIZE):
    """Host-side: one full unpadded state_dict
    (:func:`consolidate_zero_state`) -> the host-global ZeRO state for
    a ``world``-way mesh, with the shard padding recomputed for the NEW
    topology (int8 block alignment included).

    Returns ``{"step", "master_shard", "exp_avg_shard",
    "exp_avg_sq_shard"[, "grad_residual"]}`` where each ``*_shard``
    leaf is the ``(new_padded,)`` concatenation — feed it through
    ``in_specs=P(axis)`` and every rank receives exactly its
    ``new_padded // world`` slice (``world=1`` consumes it whole) —
    and ``grad_residual`` is the per-rank ``(world, new_padded)``
    stack (rank 0 carrying the whole summed correction, so the
    topology-invariant total is preserved to the bit).
    Master/moment values are bit-identical to the writer's on the
    logical prefix; only the zero pad tail changes length, so an
    8 -> 4 -> 1 -> 8 round-trip reproduces the consolidated state_dict
    exactly."""
    n = _flat_size(params)
    if full.get("n_elements") not in (None, n):
        raise ValueError(
            f"state_dict is for {full['n_elements']} elements, params "
            f"flatten to {n} — wrong model for this checkpoint")
    padded = _padded_size(n, world, grad_compress, param_compress,
                          block_size)

    def pad(v):
        a = np.asarray(v, np.float32)
        if a.shape != (n,):
            raise ValueError(f"full state buffer has shape {a.shape}, "
                             f"wanted ({n},)")
        return np.pad(a, (0, padded - n))

    state = {
        "step": jnp.asarray(np.asarray(full["step"], np.int32)
                            .reshape(())),
        "master_shard": jnp.asarray(pad(full["master"])),
        "exp_avg_shard": jnp.asarray(pad(full["exp_avg"])),
        "exp_avg_sq_shard": jnp.asarray(pad(full["exp_avg_sq"])),
    }
    written_residual = full.get("grad_residual")
    if compression.needs_residual(grad_compress):
        if written_residual is None:
            # written without EF (fp32/bf16 grads): start a fresh,
            # zeroed residual — correct, just loses nothing real
            state["grad_residual"] = jnp.zeros((world, padded),
                                               jnp.float32)
        else:
            # rank 0 carries the whole pending correction, the rest
            # start at zero: the sum over ranks — the only
            # topology-invariant quantity — is preserved TO THE BIT
            # (an even total/world split would round on
            # re-consolidation: sequentially summing w identical fp32
            # values is inexact for non-power-of-two partial sums)
            rows = np.zeros((world, padded), np.float32)
            rows[0] = pad(written_residual)
            state["grad_residual"] = jnp.asarray(rows)
    elif written_residual is not None:
        warnings.warn(
            "reshard_zero_state: the checkpoint carries an int8 "
            "error-feedback residual but the target optimizer is not "
            "grad_compress='int8' — dropping the residual (its error "
            "will re-enter the gradients once, bounded by one "
            "quantization step)")
    return state


# ---------------------------------------------------------------------------
# overlap=True bucket-partitioned state: consolidation + re-sharding
# ---------------------------------------------------------------------------

def _leaf_arrays_from_flat(flat, leaves):
    """Split a flat vector into arrays shaped like ``leaves`` (host
    numpy; exact byte copies)."""
    outs, off = [], 0
    for l in leaves:
        n = int(np.prod(l.shape))
        outs.append(np.asarray(flat[off:off + n]).reshape(l.shape))
        off += n
    return outs


def _check_bucket_layout(state, plan):
    if not (isinstance(state, dict) and "buckets" in state):
        raise ValueError(
            "expected an overlap=True bucket-partitioned state "
            "({'step', 'buckets': ...})")
    buckets = state["buckets"]
    if len(buckets) != len(plan) or any(
            len(seg_state) != len(seg_plan)
            for seg_state, seg_plan in zip(buckets, plan)):
        raise ValueError(
            f"bucket state layout {[len(s) for s in buckets]} does not "
            f"match the plan {[len(s) for s in plan]} derived from the "
            f"params — wrong params/segments, message_size, or world "
            f"for this state")


def consolidate_zero_overlap_state(state, params, *, world,
                                   grad_compress=None,
                                   param_compress=None,
                                   block_size=compression.BLOCK_SIZE,
                                   message_size=10000000,
                                   optimizer="zero"):
    """Host-side: an ``overlap=True`` bucket-partitioned ZeRO state ->
    the SAME full, unpadded format-1 state_dict
    :func:`consolidate_zero_state` produces — so a checkpoint written
    by an overlapped run re-partitions onto any topology (and into
    either step mode) through the one canonical form.

    ``state["buckets"][k][bi]`` leaves follow the host-global carry
    idiom per bucket: each ``*_shard`` the ``(bucket.padded,)``
    concatenation over ranks (or a ``(world, padded // world)`` stack),
    the EF residual the per-rank ``(world, bucket.padded)`` stack
    consolidated by SUM. The bucket plan is recomputed from ``params``
    (may be a list of per-segment pytrees) + ``world`` +
    ``message_size`` — deterministic host math, validated against the
    state's layout. Bit-exact: values are copied, never re-rounded."""
    segs, _ = _as_segments(params)
    plan = plan_zero_overlap(segs, world=world,
                             grad_compress=grad_compress,
                             param_compress=param_compress,
                             block_size=block_size,
                             message_size=message_size)
    _check_bucket_layout(state, plan)
    n = _flat_size(params)
    slots = {key: [] for key in ("master", "exp_avg", "exp_avg_sq",
                                 "grad_residual")}
    has_residual = False
    for k, (params_k, seg_plan) in enumerate(zip(segs, plan)):
        leaves = jax.tree_util.tree_leaves(params_k)
        seg_slots = {key: [None] * len(leaves) for key in slots}
        for bi, bucket in enumerate(seg_plan):
            bst = state["buckets"][k][bi]
            b_leaves = [leaves[i] for i in bucket.leaf_idx]
            for src, dst in (("master_shard", "master"),
                             ("exp_avg_shard", "exp_avg"),
                             ("exp_avg_sq_shard", "exp_avg_sq")):
                flat = _global_flat(bst[src], bucket.padded, world,
                                    f"buckets[{k}][{bi}].{src}")
                for i, piece in zip(bucket.leaf_idx,
                                    _leaf_arrays_from_flat(
                                        flat[:bucket.n], b_leaves)):
                    seg_slots[dst][i] = piece
            if bst.get("grad_residual") is not None:
                has_residual = True
                res = _consolidated_residual(
                    bst["grad_residual"], bucket.padded, world)
                for i, piece in zip(bucket.leaf_idx,
                                    _leaf_arrays_from_flat(
                                        res[:bucket.n], b_leaves)):
                    seg_slots["grad_residual"][i] = piece
        for key in slots:
            slots[key].extend(seg_slots[key])
    full = {
        "format": 1,
        "optimizer": optimizer,
        "world": int(world),
        "n_elements": n,
        "block_size": int(block_size),
        "grad_compress": grad_compress,
        "param_compress": param_compress,
        "step": np.asarray(state["step"], np.int32).reshape(()),
    }
    for key in ("master", "exp_avg", "exp_avg_sq"):
        full[key] = np.concatenate(
            [p.reshape(-1) for p in slots[key]])
    if has_residual:
        full["grad_residual"] = np.concatenate(
            [p.reshape(-1) for p in slots["grad_residual"]])
    return full


def reshard_zero_overlap_state(full, params, *, world,
                               grad_compress=None, param_compress=None,
                               block_size=compression.BLOCK_SIZE,
                               message_size=10000000):
    """Host-side inverse: one full format-1 state_dict (written by
    EITHER step mode, at any world) -> the ``overlap=True``
    bucket-partitioned state for a ``world``-way mesh, every bucket
    independently re-padded (int8 block alignment included). Each
    bucket's ``*_shard`` leaves come back as the ``(padded,)``
    concatenation — the ``in_specs=P(axis)`` feed layout — and its EF
    residual as the ``(world, padded)`` stack with rank 0 carrying the
    whole summed correction (same invariant as
    :func:`reshard_zero_state`)."""
    segs, _ = _as_segments(params)
    plan = plan_zero_overlap(segs, world=world,
                             grad_compress=grad_compress,
                             param_compress=param_compress,
                             block_size=block_size,
                             message_size=message_size)
    n = _flat_size(params)
    if full.get("n_elements") not in (None, n):
        raise ValueError(
            f"state_dict is for {full['n_elements']} elements, params "
            f"flatten to {n} — wrong model for this checkpoint")
    stateful = compression.needs_residual(grad_compress)
    written_residual = full.get("grad_residual")
    if written_residual is not None and not stateful:
        warnings.warn(
            "reshard_zero_overlap_state: the checkpoint carries an "
            "int8 error-feedback residual but the target optimizer is "
            "not compressed — dropping the residual (its error will "
            "re-enter the gradients once, bounded by one quantization "
            "step)")
    off = 0
    flats = {}
    for key in ("master", "exp_avg", "exp_avg_sq"):
        v = np.asarray(full[key], np.float32)
        if v.shape != (n,):
            raise ValueError(f"full state buffer {key} has shape "
                             f"{v.shape}, wanted ({n},)")
        flats[key] = v
    if stateful:
        flats["grad_residual"] = (
            np.asarray(written_residual, np.float32)
            if written_residual is not None
            else np.zeros((n,), np.float32))
    buckets = []
    for params_k, seg_plan in zip(segs, plan):
        leaves = jax.tree_util.tree_leaves(params_k)
        sizes = [int(np.prod(l.shape)) for l in leaves]
        starts = np.concatenate([[0], np.cumsum(sizes)]).astype(int)
        seg_states = []
        for bucket in seg_plan:
            bst = {}
            for src, key in (("master_shard", "master"),
                             ("exp_avg_shard", "exp_avg"),
                             ("exp_avg_sq_shard", "exp_avg_sq")):
                flat = np.concatenate(
                    [flats[key][off + starts[i]:off + starts[i + 1]]
                     for i in bucket.leaf_idx])
                bst[src] = jnp.asarray(
                    np.pad(flat, (0, bucket.padded - bucket.n)))
            if stateful:
                flat = np.concatenate(
                    [flats["grad_residual"]
                     [off + starts[i]:off + starts[i + 1]]
                     for i in bucket.leaf_idx])
                rows = np.zeros((world, bucket.padded), np.float32)
                rows[0, :bucket.n] = flat
                bst["grad_residual"] = jnp.asarray(rows)
            seg_states.append(bst)
        buckets.append(tuple(seg_states))
        off += int(sum(sizes))
    return {"step": jnp.asarray(np.asarray(full["step"], np.int32)
                                .reshape(())),
            "buckets": tuple(buckets)}


# ---------------------------------------------------------------------------
# 2-D (data, model) topologies: the shard table gains the TP dimension
# ---------------------------------------------------------------------------

def _partition_dim_leaves(params, partition_dims):
    """Per-leaf partition dims aligned with ``params``' flattened
    leaves (``None`` = replicated over the model axis). The dims tree
    may use ``None`` values, so it is flattened AGAINST the params
    treedef rather than on its own."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    dims = treedef.flatten_up_to(partition_dims)
    for leaf, dim in zip(leaves, dims):
        if dim is not None and not (
                isinstance(dim, int) and 0 <= dim < len(leaf.shape)):
            raise ValueError(
                f"partition dim {dim!r} invalid for a leaf of shape "
                f"{leaf.shape}")
    return leaves, treedef, dims


def split_params_for_model_axis(params, partition_dims, tp_world):
    """FULL param tree -> list (len ``tp_world``) of per-model-rank
    LOCAL trees, each leaf sliced along its partition dim (replicated
    leaves shared). The host-side view of what ``shard_map`` hands
    each model rank."""
    leaves, treedef, dims = _partition_dim_leaves(params, partition_dims)
    per_rank = []
    for t in range(tp_world):
        local = []
        for leaf, dim in zip(leaves, dims):
            if dim is None:
                local.append(np.asarray(leaf))
                continue
            a = np.asarray(leaf)
            if a.shape[dim] % tp_world:
                raise ValueError(
                    f"leaf dim {dim} of shape {a.shape} does not split "
                    f"{tp_world} ways over the model axis")
            local.append(np.split(a, tp_world, axis=dim)[t])
        per_rank.append(jax.tree_util.tree_unflatten(treedef, local))
    return per_rank


def consolidate_zero_state_2d(states, params, partition_dims, *,
                              dp_world, tp_world, grad_compress=None,
                              param_compress=None,
                              block_size=compression.BLOCK_SIZE,
                              message_size=10000000, optimizer="zero"):
    """Host-side: per-``(data, model)``-coordinate ZeRO shards -> one
    full 2-D state_dict in the FULL (TP-unsharded) parameter domain —
    the topology-invariant canonical form an elastic 2x4 -> 2x2 -> 2x4
    reshard round-trips through bit-identically.

    ``states`` is a list (len ``tp_world``, model-rank order) of the
    per-model-rank host-global 1-D states — each either the monolithic
    ``*_shard`` layout or the ``overlap=True`` bucket layout, each
    consolidated over its OWN dp replica set first. ``params`` is the
    FULL param tree (or list of segments) and ``partition_dims`` the
    matching tree of model-axis partition dims (``None`` = replicated
    — e.g. :func:`apex_tpu.parallel.mesh2d.gpt2_partition_dims`).

    Merging over the model axis: split leaves concatenate their local
    slices along the partition dim; replicated leaves must be
    BIT-IDENTICAL across model ranks (their grads — and hence masters,
    moments, and EF residuals — are model-invariant by construction on
    a correct 2-D program; a mismatch means the program diverged and
    raises rather than silently averaging)."""
    if len(states) != tp_world:
        raise ValueError(f"got {len(states)} per-model-rank states for "
                         f"tp_world={tp_world}")
    local_params = split_params_for_model_axis(params, partition_dims,
                                               tp_world)
    fulls = []
    for t, st in enumerate(states):
        kw = dict(world=dp_world, grad_compress=grad_compress,
                  param_compress=param_compress, block_size=block_size,
                  optimizer=optimizer)
        if isinstance(st, dict) and "buckets" in st:
            fulls.append(consolidate_zero_overlap_state(
                st, local_params[t], message_size=message_size, **kw))
        else:
            fulls.append(consolidate_zero_state(st, local_params[t],
                                                **kw))
    steps = {int(np.asarray(f["step"])) for f in fulls}
    if len(steps) != 1:
        raise ValueError(f"model ranks disagree on the step: {steps} — "
                         "states from different checkpoints?")
    leaves, treedef, dims = _partition_dim_leaves(params, partition_dims)
    full = {
        "format": 2,
        "optimizer": optimizer,
        "dp_world": int(dp_world),
        "tp_world": int(tp_world),
        "n_elements": _flat_size(params),
        "block_size": int(block_size),
        "grad_compress": grad_compress,
        "param_compress": param_compress,
        "step": fulls[0]["step"],
    }
    keys = ["master", "exp_avg", "exp_avg_sq"]
    if all("grad_residual" in f for f in fulls):
        keys.append("grad_residual")
    for key in keys:
        per_rank_leaves = []
        for t in range(tp_world):
            local_leaves = jax.tree_util.tree_leaves(local_params[t])
            per_rank_leaves.append(_leaf_arrays_from_flat(
                np.asarray(fulls[t][key], np.float32), local_leaves))
        merged = []
        for li, (leaf, dim) in enumerate(zip(leaves, dims)):
            pieces = [per_rank_leaves[t][li] for t in range(tp_world)]
            if dim is None:
                for t in range(1, tp_world):
                    if not np.array_equal(pieces[0], pieces[t]):
                        raise ValueError(
                            f"{key}: replicated leaf {li} differs "
                            f"between model ranks 0 and {t} — the 2-D "
                            f"program's model-invariance broke; "
                            f"refusing to consolidate")
                merged.append(pieces[0])
            else:
                merged.append(np.concatenate(pieces, axis=dim))
        full[key] = np.concatenate([p.reshape(-1) for p in merged])
    return full


def reshard_zero_state_2d(full, params, partition_dims, *, dp_world,
                          tp_world, grad_compress=None,
                          param_compress=None,
                          block_size=compression.BLOCK_SIZE,
                          message_size=10000000, overlap=False):
    """Host-side inverse of :func:`consolidate_zero_state_2d`: one full
    2-D state_dict -> the list (len ``tp_world``) of per-model-rank
    1-D states for a NEW ``(dp_world, tp_world)`` topology — monolithic
    ``*_shard`` layout, or bucket-partitioned when ``overlap=True``.
    Both the TP slicing and the dp-shard padding are recomputed for the
    new topology; master/moment values restore bit-identically, the EF
    residual re-enters as each model column's dp-rank-0 carry (the
    dp-summed, model-merged total — topology-invariant to the bit)."""
    if full.get("format") not in (1, 2):
        raise ValueError(f"unknown state_dict format "
                         f"{full.get('format')!r}")
    n = _flat_size(params)
    if full.get("n_elements") not in (None, n):
        raise ValueError(
            f"state_dict is for {full['n_elements']} elements, params "
            f"flatten to {n} — wrong model for this checkpoint")
    leaves, treedef, dims = _partition_dim_leaves(params, partition_dims)
    local_params = split_params_for_model_axis(params, partition_dims,
                                               tp_world)
    keys = ["master", "exp_avg", "exp_avg_sq"]
    if full.get("grad_residual") is not None:
        keys.append("grad_residual")
    # full flat (whole-model leaf order) -> per-leaf arrays, sliced per
    # new model rank, re-flattened in local leaf order
    states = []
    for t in range(tp_world):
        sub = {"format": 1, "optimizer": full.get("optimizer"),
               "n_elements": _flat_size(local_params[t]),
               "step": full["step"]}
        for key in keys:
            full_leaves = _leaf_arrays_from_flat(
                np.asarray(full[key], np.float32), leaves)
            local = []
            for leaf_arr, dim in zip(full_leaves, dims):
                local.append(
                    leaf_arr if dim is None
                    else np.split(leaf_arr, tp_world, axis=dim)[t])
            sub[key] = np.concatenate([p.reshape(-1) for p in local])
        kw = dict(world=dp_world, grad_compress=grad_compress,
                  param_compress=param_compress, block_size=block_size)
        if overlap:
            states.append(reshard_zero_overlap_state(
                sub, local_params[t], message_size=message_size, **kw))
        else:
            states.append(reshard_zero_state(sub, local_params[t],
                                             **kw))
    return states


def split_params_for_pipe_axis(params, pp_world, *, shared_tail=1):
    """List of segments (model order; the trailing ``shared_tail``
    segments are the pipe-REPLICATED tied edge — embeddings / final
    norm / head) -> list (len ``pp_world``) of per-stage segment
    lists, each stage's contiguous layer slice plus the shared tail.
    The host-side view of :func:`apex_tpu.parallel.pipeline.split_stages`
    composed with the tied-edge replication."""
    segs = list(params)
    if shared_tail < 0 or shared_tail > len(segs):
        raise ValueError(f"shared_tail={shared_tail} out of range for "
                         f"{len(segs)} segments")
    owned = segs[:len(segs) - shared_tail]
    tail = segs[len(segs) - shared_tail:]
    if pp_world <= 0 or len(owned) % pp_world:
        raise ValueError(
            f"{len(owned)} owned segments do not split into "
            f"pp_world={pp_world} equal stages")
    per = len(owned) // pp_world
    return [owned[p * per:(p + 1) * per] + tail
            for p in range(pp_world)]


def consolidate_zero_state_3d(states, params, partition_dims, *,
                              dp_world, tp_world, pp_world,
                              shared_tail=1, grad_compress=None,
                              param_compress=None,
                              block_size=compression.BLOCK_SIZE,
                              message_size=10000000, optimizer="zero"):
    """Host-side: per-``(data, model, pipe)``-coordinate ZeRO shards ->
    one full 3-D state_dict in the whole-model parameter domain.

    ``states`` is a list (len ``pp_world``, stage order) of the 2-D
    per-stage inputs :func:`consolidate_zero_state_2d` takes (a list of
    per-model-rank states). ``params`` is the whole model as a list of
    segments in model order whose trailing ``shared_tail`` segments are
    the pipe-replicated tied edge; ``partition_dims`` is the matching
    segment list of model-axis split tables.

    The canonical flat layout is ``[stage-owned segments in model
    order] + [shared tail once]`` — independent of ``pp_world``, which
    is what makes a 2x2x2 run restore bit-identically to 2x2x1 and
    1x2x2. The shared tail must be BIT-IDENTICAL across stages (its
    grads are pipe-psummed before the DP sync, so masters, moments and
    EF residuals stay stage-invariant on a correct program; a mismatch
    raises rather than silently averaging)."""
    if len(states) != pp_world:
        raise ValueError(f"got {len(states)} per-stage states for "
                         f"pp_world={pp_world}")
    stage_params = split_params_for_pipe_axis(
        params, pp_world, shared_tail=shared_tail)
    stage_dims = split_params_for_pipe_axis(
        partition_dims, pp_world, shared_tail=shared_tail)
    kw = dict(dp_world=dp_world, tp_world=tp_world,
              grad_compress=grad_compress, param_compress=param_compress,
              block_size=block_size, message_size=message_size,
              optimizer=optimizer)
    fulls = [consolidate_zero_state_2d(states[p], stage_params[p],
                                       stage_dims[p], **kw)
             for p in range(pp_world)]
    steps = {int(np.asarray(f["step"])) for f in fulls}
    if len(steps) != 1:
        raise ValueError(f"pipeline stages disagree on the step: "
                         f"{steps} — states from different checkpoints?")
    tail_n = _flat_size(params[len(params) - shared_tail:]) \
        if shared_tail else 0
    full = {
        "format": 3,
        "optimizer": optimizer,
        "dp_world": int(dp_world),
        "tp_world": int(tp_world),
        "pp_world": int(pp_world),
        "shared_tail_elements": int(tail_n),
        "n_elements": _flat_size(params),
        "block_size": int(block_size),
        "grad_compress": grad_compress,
        "param_compress": param_compress,
        "step": fulls[0]["step"],
    }
    keys = ["master", "exp_avg", "exp_avg_sq"]
    if all("grad_residual" in f for f in fulls):
        keys.append("grad_residual")
    for key in keys:
        owned_parts, tails = [], []
        for p in range(pp_world):
            arr = np.asarray(fulls[p][key], np.float32)
            if tail_n:
                owned_parts.append(arr[:arr.size - tail_n])
                tails.append(arr[arr.size - tail_n:])
            else:
                owned_parts.append(arr)
        for p in range(1, pp_world):
            if tails and not np.array_equal(tails[0], tails[p]):
                raise ValueError(
                    f"{key}: pipe-replicated tail differs between "
                    f"stages 0 and {p} — the tied-edge pipe-invariance "
                    f"broke; refusing to consolidate")
        full[key] = np.concatenate(
            owned_parts + (tails[:1] if tail_n else []))
    return full


def reshard_zero_state_3d(full, params, partition_dims, *, dp_world,
                          tp_world, pp_world, shared_tail=1,
                          grad_compress=None, param_compress=None,
                          block_size=compression.BLOCK_SIZE,
                          message_size=10000000, overlap=False):
    """Host-side inverse of :func:`consolidate_zero_state_3d`: one full
    state_dict (format 3, or a format-1/2 dict written at ``pp == 1`` —
    the canonical flat layout is identical) -> the list (len
    ``pp_world``, stage order) of 2-D per-stage restore inputs, each a
    list (len ``tp_world``) of per-model-rank 1-D states for the NEW
    ``(dp, tp, pp)`` topology. Stage slicing, TP slicing and dp-shard
    padding are all recomputed; masters, moments and the EF residual
    restore bit-identically."""
    if full.get("format") not in (1, 2, 3):
        raise ValueError(f"unknown state_dict format "
                         f"{full.get('format')!r}")
    n = _flat_size(params)
    if full.get("n_elements") not in (None, n):
        raise ValueError(
            f"state_dict is for {full['n_elements']} elements, params "
            f"flatten to {n} — wrong model for this checkpoint")
    tail_n = _flat_size(params[len(params) - shared_tail:]) \
        if shared_tail else 0
    want = full.get("shared_tail_elements")
    if want is not None and int(want) != tail_n:
        raise ValueError(
            f"state_dict's shared tail is {want} elements, params' is "
            f"{tail_n} — differing tied-edge convention")
    stage_params = split_params_for_pipe_axis(
        params, pp_world, shared_tail=shared_tail)
    stage_dims = split_params_for_pipe_axis(
        partition_dims, pp_world, shared_tail=shared_tail)
    owned_sizes = [_flat_size(sp[:len(sp) - shared_tail]
                              if shared_tail else sp)
                   for sp in stage_params]
    keys = ["master", "exp_avg", "exp_avg_sq"]
    if full.get("grad_residual") is not None:
        keys.append("grad_residual")
    out = []
    off = 0
    for p in range(pp_world):
        sub = {"format": 2, "optimizer": full.get("optimizer"),
               "dp_world": int(dp_world), "tp_world": int(tp_world),
               "n_elements": _flat_size(stage_params[p]),
               "step": full["step"]}
        for key in keys:
            arr = np.asarray(full[key], np.float32)
            owned = arr[off:off + owned_sizes[p]]
            tail = arr[arr.size - tail_n:] if tail_n else arr[:0]
            sub[key] = np.concatenate([owned, tail])
        off += owned_sizes[p]
        out.append(reshard_zero_state_2d(
            sub, stage_params[p], stage_dims[p], dp_world=dp_world,
            tp_world=tp_world, grad_compress=grad_compress,
            param_compress=param_compress, block_size=block_size,
            message_size=message_size, overlap=overlap))
    return out


def zero_state_bytes(params, *, world, grad_compress=None,
                     param_compress=None,
                     block_size=compression.BLOCK_SIZE, axis_name="dp",
                     optimizer="zero", registry=None, record=True):
    """Sharded vs unsharded optimizer-state bytes — the measurable ZeRO
    win (Xu et al., arXiv:2004.13336: sharding the weight-update state
    over the replica set is what frees the HBM that batch size wants).

    Host-side accounting from the same layout math ``init`` uses, with
    an EXPLICIT ``world`` (outside shard_map the axis is unbound, so
    the caller names the replica count it is sizing for). Per-device
    bytes: ``unsharded_state_bytes`` is what a replicated fp32
    Adam/LAMB would hold (3 fp32 buffers — master + two moments — of
    the padded flat length), ``sharded_state_bytes`` is what this
    optimizer actually holds (the same 3 buffers at 1/world, plus the
    full-length error-feedback residual when the grad sync is int8 —
    the residual lives in the pre-scatter gradient domain and is NOT
    sharded, an honest cost of ``compress=True``). Records a ``memory``
    event + ``memory/zero_state_sharded_bytes`` gauge when telemetry is
    enabled and ``record=True``."""
    from apex_tpu.telemetry.registry import get_registry

    n = _flat_size(params)
    padded = _padded_size(n, world, grad_compress, param_compress,
                          block_size)
    f32 = 4
    unsharded = 3 * padded * f32
    sharded = 3 * (padded // world) * f32
    residual = padded * f32 if compression.needs_residual(grad_compress) else 0
    params_bytes = int(sum(
        int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(params)))
    report = {
        "optimizer": optimizer,
        "axis_name": str(axis_name),
        "world": int(world),
        "n_elements": n,
        "padded_elements": padded,
        "params_bytes": params_bytes,
        "unsharded_state_bytes": unsharded,
        "sharded_state_bytes": sharded + residual,
        "residual_bytes": residual,
        "savings_bytes": unsharded - (sharded + residual),
        "savings_ratio": unsharded / max(sharded + residual, 1),
        "grad_compress": grad_compress,
        "param_compress": param_compress,
    }
    if record:
        reg = registry or get_registry()
        if reg.enabled:
            reg.gauge("memory/zero_state_sharded_bytes").set(
                report["sharded_state_bytes"])
            reg.gauge("memory/zero_state_unsharded_bytes").set(unsharded)
            reg.event("memory", "zero_state_bytes", **report)
    return report


class ZeroBucket(NamedTuple):
    """One planned ZeRO overlap bucket: segment-local leaf indices, the
    flat element count, and the world/block-aligned padded length its
    shards are cut from."""

    leaf_idx: tuple
    n: int
    padded: int


def plan_zero_overlap(segment_params, *, world, grad_compress=None,
                      param_compress=None,
                      block_size=compression.BLOCK_SIZE,
                      message_size=10000000):
    """Host-side overlap bucket plan for a ZeRO optimizer: per segment
    (a list of param pytrees — pass ``[params]`` for an unsegmented
    model), the dtype-segregated ``message_size``-capped grouping of
    ``parallel.distributed.plan_buckets``, each bucket independently
    padded for ``world``-way sharding (int8 block alignment included).
    Buckets never span a segment boundary, so each becomes ready the
    moment its segment's backward finishes."""
    from apex_tpu.parallel.distributed import plan_buckets

    plan = []
    for params in segment_params:
        leaves = jax.tree_util.tree_leaves(params)
        buckets = []
        if leaves:
            for idxs in plan_buckets(leaves, message_size):
                n = int(sum(int(leaves[i].size) for i in idxs))
                buckets.append(ZeroBucket(
                    tuple(idxs), n,
                    _padded_size(n, world, grad_compress, param_compress,
                                 block_size)))
        plan.append(tuple(buckets))
    return tuple(plan)


def _as_segments(tree_or_list):
    """Normalize ``params``/``grads`` to the segmented form: a
    list/tuple of CONTAINER pytrees (dicts etc.) passes through as
    segments, anything else — including a plain list of arrays —
    becomes one segment."""
    if isinstance(tree_or_list, (list, tuple)) and tree_or_list and all(
            not hasattr(t, "shape") for t in tree_or_list):
        return list(tree_or_list), True
    return [tree_or_list], False


class DistributedFusedAdam:
    """Args mirror the reference's core knobs (distributed_fused_adam.py:147):
    lr, bias_correction, betas, eps, weight_decay, adam_w_mode,
    grad_sync_dtype (bucket dtype), process-group options map to
    ``axis_name``.

    ``overlap=True`` restructures the step for backward/collective
    overlap (parallel/overlap.py, arXiv 2004.13336): the flat state is
    partitioned into ``message_size``-capped buckets, and each bucket
    runs its own reduce-scatter -> sharded Adam update -> all-gather
    chain, data-dependent ONLY on that bucket's gradients — so XLA can
    interleave bucket *i*'s collectives and update with the backward
    compute that produces bucket *i-1*. ``init``/``step`` then also
    accept a LIST of param/grad pytrees (one per model segment; buckets
    never span segments), which is how
    ``overlap.overlapped_zero_step`` drives the per-bucket machinery
    from inside its segmented backward. Elastic re-sharding
    (``state_dict_full``/``load_state_dict_resharded``) is not
    supported for the bucket-partitioned state."""

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999),
                 eps=1e-8, adam_w_mode=True, weight_decay=0.0,
                 axis_name: str = "dp", grad_sync_dtype=None,
                 store_params=False, store_param_remainders=False,
                 compress: bool = False,
                 grad_compress: Optional[str] = None,
                 param_compress: Optional[str] = None,
                 compress_block_size: int = compression.BLOCK_SIZE,
                 numerics=None, overlap: bool = False,
                 message_size: int = 10000000):
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay
        self.axis_name = axis_name
        self.grad_sync_dtype = grad_sync_dtype
        # Compressed collectives (parallel/compression.py): ``compress=
        # True`` turns on the recommended pair — int8 block-quantized
        # grad reduce-scatter WITH error feedback (the residual rides in
        # the optimizer state), bf16 param all-gather (params tolerate a
        # cast; the fp32 master shard stays exact). Override either mode
        # individually via grad_compress / param_compress.
        if compress and grad_compress is None:
            grad_compress = "int8"
        if compress and param_compress is None:
            param_compress = "bf16"
        self.grad_compress = grad_compress
        self.param_compress = param_compress
        self.compress_block_size = compress_block_size
        # In-graph numerics (telemetry/numerics.py): True / an int
        # grouping depth makes ``step`` return a third element — the
        # per-module stats of the INCOMING grads (pre-flatten, pre-
        # compression: the flat ZeRO buffers lose module attribution,
        # so stats are taken where the module structure still exists).
        self.numerics = numerics
        # Overlapped mode (parallel/overlap.py): bucket-partitioned
        # state, per-bucket reduce-scatter -> shard update -> all-gather
        # chains with no cross-bucket data dependence.
        self.overlap = overlap
        self.message_size = message_size

    # -- overlapped mode: bucket plan + per-bucket primitives -----------

    @property
    def overlap_needs_global_norm(self):
        """Adam has no cross-bucket coupling: every bucket's update is
        data-dependent only on its own scattered grads."""
        return False

    def overlap_plan(self, params_or_segments):
        segs, _ = _as_segments(params_or_segments)
        return plan_zero_overlap(
            segs, world=_axis_size(self.axis_name),
            grad_compress=self.grad_compress,
            param_compress=self.param_compress,
            block_size=self.compress_block_size,
            message_size=self.message_size)

    def _init_bucket(self, leaves, bucket):
        world = _axis_size(self.axis_name)
        flat = jnp.concatenate(
            [leaves[i].reshape(-1).astype(jnp.float32)
             for i in bucket.leaf_idx])
        flat = jnp.pad(flat, (0, bucket.padded - bucket.n))
        if world > 1:
            rank = lax.axis_index(self.axis_name)
            shard = lax.dynamic_slice_in_dim(
                flat, rank * (bucket.padded // world),
                bucket.padded // world)
        else:
            shard = flat
        bstate = {
            "master_shard": shard,
            "exp_avg_shard": jnp.zeros_like(shard),
            "exp_avg_sq_shard": jnp.zeros_like(shard),
        }
        if compression.needs_residual(self.grad_compress):
            bstate["grad_residual"] = jnp.zeros((bucket.padded,),
                                                jnp.float32)
        return bstate

    def bucket_reduce(self, flat_g, bstate):
        """Reduce-scatter ONE bucket's padded flat gradient; returns
        ``(averaged local shard, new residual or None)`` — the same
        policy as :meth:`_sync_grads`, scoped to the bucket."""
        world = _axis_size(self.axis_name)
        if world == 1:
            return flat_g, bstate.get("grad_residual")
        with _telemetry_trace.span("zero/grad_reduce_scatter",
                                   compress=self.grad_compress or "none",
                                   overlap=True):
            if self.grad_compress is None:
                _telemetry_comm.record_collective(
                    "psum_scatter", elements=flat_g.size,
                    dtype=flat_g.dtype, axis_name=self.axis_name,
                    world=world)
                g_shard = lax.psum_scatter(flat_g, self.axis_name,
                                           tiled=True)
                return g_shard / world, None
            g_shard, residual = compression.psum_scatter_compressed(
                flat_g, self.axis_name, mode=self.grad_compress,
                residual=bstate.get("grad_residual"),
                block_size=self.compress_block_size)
            return g_shard / world, residual

    def _shard_adam_math(self, g_shard, bstate, *, lr, step):
        """The fused Adam update on one local fp32 shard — ONE
        multi-tensor kernel call per shard/bucket
        (:func:`apex_tpu.kernels.optim.fused_adam_update`; the jnp
        oracle is byte-for-byte the math this method used to inline,
        and :meth:`step` runs the same call on the monolithic shard)."""
        from apex_tpu.kernels import optim as _koptim

        b1, b2 = self.betas
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step
            bc2 = 1.0 - b2 ** step
        else:
            bc1 = bc2 = 1.0
        return _koptim.fused_adam_update(
            g_shard, bstate["master_shard"], bstate["exp_avg_shard"],
            bstate["exp_avg_sq_shard"], lr=lr, bc1=bc1, bc2=bc2,
            b1=b1, b2=b2, eps=self.eps, weight_decay=self.weight_decay,
            adam_w=not (self.adam_w_mode == 0 or not self.adam_w_mode))

    def bucket_update_gather(self, g_shard, bstate, bucket, p_leaves, *,
                             lr=None, step, noop, clip=None,
                             new_residual=None):
        """Sharded optimizer update + param all-gather for ONE bucket.
        Data-dependent only on this bucket's scattered grads (``clip``
        must stay None for Adam — there is no global-norm coupling).
        Returns ``(new param leaves, new bucket state)``."""
        if clip is not None:
            raise ValueError("DistributedFusedAdam has no global-norm "
                             "clip; clip must be None")
        lr = self.lr if lr is None else lr
        world = _axis_size(self.axis_name)
        p = bstate["master_shard"]
        p_new, m, v = self._shard_adam_math(g_shard, bstate, lr=lr,
                                            step=step)
        keep = noop > 0
        p_new = jnp.where(keep, p, p_new)
        m = jnp.where(keep, bstate["exp_avg_shard"], m)
        v = jnp.where(keep, bstate["exp_avg_sq_shard"], v)
        flat_p = self._gather_params(p_new, world)
        new_bstate = {"master_shard": p_new, "exp_avg_shard": m,
                      "exp_avg_sq_shard": v}
        if compression.needs_residual(self.grad_compress):
            new_bstate["grad_residual"] = jnp.where(
                keep, bstate["grad_residual"], new_residual)
        from apex_tpu.parallel.distributed import unflatten

        new_leaves = unflatten(flat_p[:bucket.n], p_leaves)
        return new_leaves, new_bstate

    def _init_overlapped(self, params):
        segs, _ = _as_segments(params)
        plan = self.overlap_plan(segs)
        buckets = []
        for params_k, seg_plan in zip(segs, plan):
            leaves = jax.tree_util.tree_leaves(params_k)
            buckets.append(tuple(self._init_bucket(leaves, b)
                                 for b in seg_plan))
        return {"step": jnp.zeros((), jnp.int32),
                "buckets": tuple(buckets)}

    def _step_overlapped(self, grads, state, params, *, lr, found_inf,
                         scale):
        lr = self.lr if lr is None else lr
        g_segs, was_list = _as_segments(grads)
        p_segs, _ = _as_segments(params)
        plan = self.overlap_plan(p_segs)
        noop = (jnp.zeros((), jnp.float32) if found_inf is None
                else jnp.asarray(found_inf, jnp.float32))
        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        new_params, new_buckets = [], []
        for k, (grads_k, params_k, seg_plan) in enumerate(
                zip(g_segs, p_segs, plan)):
            g_leaves, treedef = jax.tree_util.tree_flatten(grads_k)
            p_leaves = list(jax.tree_util.tree_leaves(params_k))
            seg_states = []
            for bi, bucket in enumerate(seg_plan):
                bstate = state["buckets"][k][bi]
                flat_g = jnp.concatenate(
                    [g_leaves[i].reshape(-1).astype(jnp.float32)
                     for i in bucket.leaf_idx]) / scale
                flat_g = jnp.pad(flat_g, (0, bucket.padded - bucket.n))
                g_shard, new_residual = self.bucket_reduce(flat_g, bstate)
                new_leaves, nb = self.bucket_update_gather(
                    g_shard, bstate, bucket,
                    [p_leaves[i] for i in bucket.leaf_idx],
                    lr=lr, step=step, noop=noop,
                    new_residual=new_residual)
                for i, leaf in zip(bucket.leaf_idx, new_leaves):
                    p_leaves[i] = leaf
                seg_states.append(nb)
            new_params.append(
                jax.tree_util.tree_unflatten(treedef, p_leaves))
            new_buckets.append(tuple(seg_states))
        new_state = {"step": step, "buckets": tuple(new_buckets)}
        out_params = new_params if was_list else new_params[0]
        if self.numerics:
            stats = {}
            depth = (_numerics.default_prefix_depth()
                     if self.numerics is True else int(self.numerics))
            for grads_k in g_segs:
                stats.update(_numerics.tree_stats(
                    grads_k, prefix_depth=depth, prefix="grads"))
            return out_params, new_state, stats
        return out_params, new_state

    def _grad_stats(self, grads):
        depth = (_numerics.default_prefix_depth() if self.numerics is True
                 else int(self.numerics))
        return _numerics.tree_stats(grads, prefix_depth=depth,
                                    prefix="grads")

    def state_bytes(self, params, *, world=None, registry=None,
                    record=True):
        """Per-device sharded vs unsharded optimizer-state bytes for
        ``params`` at ``world``-way ZeRO sharding (default: the bound
        axis size, or 1 outside shard_map — pass ``world=`` host-side).
        See :func:`zero_state_bytes`."""
        if world is None:
            world = _axis_size(self.axis_name)
        return zero_state_bytes(
            params, world=world, grad_compress=self.grad_compress,
            param_compress=self.param_compress,
            block_size=self.compress_block_size,
            axis_name=self.axis_name, optimizer="DistributedFusedAdam",
            registry=registry, record=record)

    # -- elastic re-sharding (host-side; docs/resilience.md) ------------

    def topology(self, world):
        """The writing-topology record for
        ``checkpoint.save_training_state(topology=...)`` — what
        :meth:`load_state_dict_resharded` needs to re-partition this
        state onto a different world size. ``world`` is the dp replica
        count, or a ``(dp, tp)`` pair for a 2-D ``(data, model)``
        mesh."""
        if isinstance(world, (tuple, list)):
            world = [int(w) for w in world]
        else:
            world = int(world)
        return {"optimizer": type(self).__name__, "world": world,
                "axis_name": str(self.axis_name),
                "grad_compress": self.grad_compress,
                "param_compress": self.param_compress,
                "block_size": int(self.compress_block_size)}

    def state_dict_full(self, state, params, *, world,
                        partition_dims=None):
        """Host-side: the run's ZeRO state -> one full UNPADDED
        state_dict that :meth:`load_state_dict_resharded` can
        re-partition onto any topology. ``world`` is explicit because
        the axis is unbound on the host.

        Three layouts are understood:

        - monolithic (``world`` an int): each ``*_shard`` leaf the
          ``(padded,)`` concatenation of the per-rank shards — the
          ``out_specs=P(axis)`` carry idiom — or a ``(world, shard)``
          stack (:func:`consolidate_zero_state`);
        - ``overlap=True`` bucket-partitioned state (detected by its
          ``"buckets"`` key): consolidated bucket-by-bucket into the
          SAME format-1 dict (:func:`consolidate_zero_overlap_state`);
        - 2-D ``(data, model)`` (``world`` a ``(dp, tp)`` pair):
          ``state`` is a LIST of per-model-rank states (either layout)
          and ``partition_dims`` names each leaf's model-axis split dim
          (:func:`consolidate_zero_state_2d`).
        """
        kw = dict(grad_compress=self.grad_compress,
                  param_compress=self.param_compress,
                  block_size=self.compress_block_size,
                  optimizer=type(self).__name__)
        if isinstance(world, (tuple, list)):
            if partition_dims is None:
                raise ValueError(
                    "state_dict_full: a 2-D/3-D world needs "
                    "partition_dims (the per-leaf model-axis split "
                    "table)")
            if len(world) == 3:
                dp, tp, pp = world
                return consolidate_zero_state_3d(
                    state, params, partition_dims, dp_world=dp,
                    tp_world=tp, pp_world=pp,
                    message_size=self.message_size, **kw)
            dp, tp = world
            return consolidate_zero_state_2d(
                state, params, partition_dims, dp_world=dp, tp_world=tp,
                message_size=self.message_size, **kw)
        if isinstance(state, dict) and "buckets" in state:
            return consolidate_zero_overlap_state(
                state, params, world=world,
                message_size=self.message_size, **kw)
        return consolidate_zero_state(state, params, world=world, **kw)

    def load_state_dict_resharded(self, full, params, *, world,
                                  partition_dims=None):
        """Host-side: a :meth:`state_dict_full` dict (written at ANY
        topology, by either step mode) -> this optimizer's state
        re-partitioned for the target topology, shard padding
        recomputed (int8 block alignment included). fp32
        masters/moments and the EF residual restore bit-exactly; only
        the zero pad tail changes length. ``world`` an int restores the
        1-D layout (bucket-partitioned iff this optimizer runs
        ``overlap=True``); a ``(dp, tp)`` pair restores the list of
        per-model-rank states for a 2-D mesh (``partition_dims``
        required). See :func:`reshard_zero_state`,
        :func:`reshard_zero_overlap_state`,
        :func:`reshard_zero_state_2d`."""
        kw = dict(grad_compress=self.grad_compress,
                  param_compress=self.param_compress,
                  block_size=self.compress_block_size)
        if isinstance(world, (tuple, list)):
            if partition_dims is None:
                raise ValueError(
                    "load_state_dict_resharded: a 2-D/3-D world needs "
                    "partition_dims (the per-leaf model-axis split "
                    "table)")
            if len(world) == 3:
                dp, tp, pp = world
                return reshard_zero_state_3d(
                    full, params, partition_dims, dp_world=dp,
                    tp_world=tp, pp_world=pp,
                    message_size=self.message_size,
                    overlap=bool(self.overlap), **kw)
            dp, tp = world
            return reshard_zero_state_2d(
                full, params, partition_dims, dp_world=dp, tp_world=tp,
                message_size=self.message_size,
                overlap=bool(self.overlap), **kw)
        if self.overlap:
            return reshard_zero_overlap_state(
                full, params, world=world,
                message_size=self.message_size, **kw)
        return reshard_zero_state(full, params, world=world, **kw)

    def _shard_info(self, params):
        n = _flat_size(params)
        world = _axis_size(self.axis_name)
        # int8 modes need every rank's shard to cover whole quantization
        # blocks (scales slice cleanly at shard boundaries)
        padded = _padded_size(n, world, self.grad_compress,
                              self.param_compress,
                              self.compress_block_size)
        return n, padded, world

    def init(self, params):
        """State: local fp32 master/moment shards of size padded/world
        (+ the full-length error-feedback residual when the grad sync is
        int8-compressed). With ``overlap=True`` the state is instead
        bucket-partitioned (``{"step", "buckets": ...}``) and ``params``
        may be a list of per-segment pytrees."""
        if self.overlap:
            return self._init_overlapped(params)
        n, padded, world = self._shard_info(params)
        flat = _flatten_f32(params)
        flat = jnp.pad(flat, (0, padded - n))
        if world > 1:
            rank = lax.axis_index(self.axis_name)
            shard = lax.dynamic_slice_in_dim(flat, rank * (padded // world),
                                             padded // world)
        else:
            shard = flat
        state = {
            "step": jnp.zeros((), jnp.int32),
            "master_shard": shard,
            "exp_avg_shard": jnp.zeros_like(shard),
            "exp_avg_sq_shard": jnp.zeros_like(shard),
        }
        if compression.needs_residual(self.grad_compress):
            state["grad_residual"] = jnp.zeros((padded,), jnp.float32)
        return state

    def _sync_grads(self, flat_g, state, world):
        """Reduce-scatter the flat grads, optionally through the
        compressed payload; returns (averaged local shard, new residual
        or None)."""
        if world == 1:
            return flat_g, state.get("grad_residual")
        with _telemetry_trace.span("zero/grad_reduce_scatter",
                                   compress=self.grad_compress or "none"):
            if self.grad_compress is None:
                # overlapped reduce-scatter grad sync (reference hook
                # pipeline); compressed paths record their own bytes
                _telemetry_comm.record_collective(
                    "psum_scatter", elements=flat_g.size,
                    dtype=flat_g.dtype, axis_name=self.axis_name,
                    world=world)
                g_shard = lax.psum_scatter(flat_g, self.axis_name,
                                           tiled=True)
                return g_shard / world, None
            g_shard, residual = compression.psum_scatter_compressed(
                flat_g, self.axis_name, mode=self.grad_compress,
                residual=state.get("grad_residual"),
                block_size=self.compress_block_size)
            return g_shard / world, residual

    def _gather_params(self, p_new, world):
        if world == 1:
            return p_new
        with _telemetry_trace.span("zero/param_all_gather",
                                   compress=self.param_compress or "none"):
            if self.param_compress is None:
                _telemetry_comm.record_collective(
                    "all_gather", elements=p_new.size, dtype=p_new.dtype,
                    axis_name=self.axis_name, world=world)
                return lax.all_gather(p_new, self.axis_name, tiled=True)
            return compression.all_gather_compressed(
                p_new, self.axis_name, mode=self.param_compress,
                block_size=self.compress_block_size)

    def step(self, grads, state, params, *, lr: Optional[float] = None,
             found_inf=None, scale: float = 1.0):
        if self.overlap:
            return self._step_overlapped(grads, state, params, lr=lr,
                                         found_inf=found_inf,
                                         scale=scale)
        lr = self.lr if lr is None else lr
        stats = self._grad_stats(grads) if self.numerics else None
        n, padded, world = self._shard_info(params)
        noop = (jnp.zeros((), jnp.float32) if found_inf is None
                else jnp.asarray(found_inf, jnp.float32))

        flat_g = _flatten_f32(grads) / scale
        flat_g = jnp.pad(flat_g, (0, padded - n))
        g_shard, grad_residual = self._sync_grads(flat_g, state, world)

        step = state["step"] + jnp.where(noop > 0, 0, 1).astype(jnp.int32)
        p = state["master_shard"]
        p_new, m, v = self._shard_adam_math(g_shard, state, lr=lr,
                                            step=step)

        keep = noop > 0
        p_new = jnp.where(keep, p, p_new)
        m = jnp.where(keep, state["exp_avg_shard"], m)
        v = jnp.where(keep, state["exp_avg_sq_shard"], v)

        flat_p = self._gather_params(p_new, world)
        new_params = _unflatten_like(flat_p[:n], params)
        new_state = {
            "step": step,
            "master_shard": p_new,
            "exp_avg_shard": m,
            "exp_avg_sq_shard": v,
        }
        if compression.needs_residual(self.grad_compress):
            # an overflow-skipped step consumed a bogus gradient — drop
            # its quantization error instead of feeding it back
            new_state["grad_residual"] = jnp.where(
                keep, state["grad_residual"], grad_residual)
        if self.numerics:
            return new_params, new_state, stats
        return new_params, new_state
