"""Channel-permutation search for 2:4 structured sparsity.

Parity: reference apex/contrib/sparsity/permutation_lib.py (927 LoC) +
permutation_search_kernels/ (exhaustive + greedy CUDA search): permute a
weight's input channels so that large-magnitude weights land in positions
the m4n2 mask keeps ("Channel Permutations for N:M Sparsity",
NeurIPS 2021). The reference drives this through a torch.fx graph walk to
propagate permutations across layers; here the graph plumbing is the
user's (JAX models are functional pytrees), and this module provides the
search itself, fully vectorized:

- :func:`sum_after_2_to_4` — magnitude retained by the 2:4 mask.
- :func:`search_for_good_permutation` — greedy pairwise column-swap
  search; each sweep scores ALL (i, j) swap gains as one batched
  computation (the XLA analog of the reference's CUDA search kernels)
  and applies the best non-conflicting swaps.
- :func:`apply_permutation_in_C_dim` / ``..._K_dim`` — apply a found
  permutation to weights (and the inverse to producing layers).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _group_kept_sum(groups):
    """groups: [..., K, 4] -> [...]: magnitude kept by keep-2-of-4."""
    a = jnp.abs(groups)
    top2 = jnp.sort(a, axis=-1)[..., 2:]
    return jnp.sum(top2, axis=tuple(range(top2.ndim - 2, top2.ndim)))


def sum_after_2_to_4(weight2d):
    """Total |w| kept by the m4n2 mask (reference
    permutation_search_kernels/permutation_utilities.sum_after_2_to_4)."""
    k, c = weight2d.shape
    assert c % 4 == 0, "C must be divisible by 4"
    groups = weight2d.reshape(k, c // 4, 4).transpose(1, 0, 2)
    return jnp.sum(_group_kept_sum(groups))


@functools.partial(jax.jit, static_argnums=(1,))
def _replacement_chunk(weight2d, chunk, i_start):
    """R[i, j] = kept(group(i) with i's slot replaced by column j), for
    i in [i_start, i_start+chunk) and all j.

    Returns [chunk, C]. Memory is O(chunk * C * K * 4) — chunking over i
    bounds the replacement tensor the way the reference CUDA kernels
    stripe their search. The transposed term of the swap gain is R.T
    (kept(g_j with slot j <- col i) = R[j, i]), so only this one matrix
    is ever computed — the full gain assembles on the host.
    """
    k, c = weight2d.shape
    g = c // 4
    groups = weight2d.reshape(k, g, 4).transpose(1, 0, 2)  # [g, K, 4]
    gid = jnp.arange(c) // 4
    pos = jnp.arange(c) % 4
    cols = weight2d.T                                       # [C, K]

    def rep_row(i):
        # kept(g_i with col i replaced by col j) for all j -> [C]
        grp = groups[gid[i]]                                # [K, 4]
        def one(j):
            return _group_kept_sum(grp.at[:, pos[i]].set(cols[j]))
        return jax.vmap(one)(jnp.arange(c))

    i_idx = i_start + jnp.arange(chunk)
    return jax.vmap(rep_row)(i_idx)                         # [chunk, C]


def _swap_gains(weight2d, chunk=64):
    """Full [C, C] swap-gain matrix:
    gains[i, j] = (R[i, j] - base[g_i]) + (R[j, i] - base[g_j]),
    zeroed within a group. R is computed once in jitted chunks."""
    k, c = weight2d.shape
    chunk = min(chunk, c)
    rows = []
    for i0 in range(0, c, chunk):
        n = min(chunk, c - i0)
        rows.append(np.asarray(_replacement_chunk(weight2d, n, i0)))
    rep = np.concatenate(rows, axis=0)                      # [C, C]
    groups = np.asarray(weight2d).reshape(k, c // 4, 4).transpose(1, 0, 2)
    base = np.asarray(_group_kept_sum(jnp.asarray(groups)))  # [g]
    gid = np.arange(c) // 4
    gains = (rep - base[gid][:, None]) + (rep.T - base[gid][None, :])
    same_group = gid[:, None] == gid[None, :]
    return np.where(same_group, 0.0, gains)


def _disjoint_positive_swaps(gains, tol=1e-7):
    """Greedy selection of non-conflicting positive-gain (i, j) swaps:
    best first, skipping any pair touching an already-swapped group."""
    c = gains.shape[0]
    order = np.argsort(gains, axis=None)[::-1]
    used_groups = set()
    chosen = []
    for flat in order:
        i, j = divmod(int(flat), c)
        if gains[i, j] <= tol:
            break
        gi, gj = i // 4, j // 4
        if gi in used_groups or gj in used_groups:
            continue
        used_groups.update((gi, gj))
        chosen.append((i, j))
    return chosen


def search_for_good_permutation(weight2d, num_iters=10, chunk=64):
    """Greedy vectorized permutation search.

    Each sweep scores all pairwise swaps (jitted, chunked to bound
    memory) and applies EVERY positive-gain swap whose groups don't
    conflict, so convergence takes a handful of sweeps independent of C.
    Returns (permutation indices [C], permuted weight).
    """
    w = jnp.asarray(weight2d, jnp.float32)
    k, c = w.shape
    assert c % 4 == 0, "C must be divisible by 4"
    perm = np.arange(c)
    for _ in range(num_iters):
        gains = _swap_gains(w, chunk=chunk)
        swaps = _disjoint_positive_swaps(gains)
        if not swaps:
            break
        src = np.arange(c)
        for i, j in swaps:
            src[[i, j]] = src[[j, i]]
        perm = perm[src]
        w = w[:, src]
    return perm, w


def apply_permutation_in_C_dim(weight, perm):
    """Permute input channels (last dim of a [K, C] weight; reference
    permutation_lib.apply_permutation_in_C_dim)."""
    return jnp.asarray(weight)[:, jnp.asarray(perm)]


def apply_permutation_in_K_dim(weight, perm):
    """Permute output channels (first dim) — applied to the producing
    layer so the network function is preserved (reference
    apply_permutation_in_K_dim)."""
    return jnp.asarray(weight)[jnp.asarray(perm)]


def permutation_improvement(weight2d, perm):
    """(kept_before, kept_after) magnitude for reporting."""
    before = float(sum_after_2_to_4(jnp.asarray(weight2d, jnp.float32)))
    after = float(sum_after_2_to_4(
        apply_permutation_in_C_dim(jnp.asarray(weight2d, jnp.float32), perm)))
    return before, after
