from apex_tpu.contrib.sparsity.asp import ASP  # noqa: F401
from apex_tpu.contrib.sparsity.sparse_masklib import (  # noqa: F401
    create_mask,
    m4n2_1d,
    m4n2_2d_best,
    unstructured_fraction,
)
from apex_tpu.contrib.sparsity.permutation_lib import (  # noqa: F401
    apply_permutation_in_C_dim,
    apply_permutation_in_K_dim,
    permutation_improvement,
    search_for_good_permutation,
    sum_after_2_to_4,
)
from apex_tpu.contrib.sparsity.propagation import (  # noqa: F401
    PermSpec,
    PermutationGroup,
    gpt_attention_permutation_groups,
    gpt_permutation_groups,
    propagate_permutations,
    resnet_permutation_groups,
    t5_permutation_groups,
)
