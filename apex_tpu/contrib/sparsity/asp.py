"""ASP — automatic structured sparsity.

Parity: reference apex/contrib/sparsity/asp.py (318 LoC):
``ASP.init_model_for_pruning`` (select prunable params, allocate masks),
``compute_sparse_masks``, ``restore_pruned_weights``,
``is_sparsity_enabled``, and the optimizer-step mask re-application
(``init_optimizer_for_pruning``).

TPU design: masks are a pytree parallel to params; pruning is
``params * masks`` applied functionally — either once
(inference) or inside the train step after each optimizer update (the
reference wraps optimizer.step the same way).
"""


import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask


def _default_allow(path, leaf):
    name = "/".join(str(getattr(k, "key", k)) for k in path).lower()
    if leaf.ndim < 2:
        return False
    if any(b in name for b in ("norm", "bias", "embedding", "bn")):
        return False
    # reference prunes weights with both dims >= 16 and divisible by 8/16
    return leaf.shape[-1] % 4 == 0 and min(leaf.shape[-2:]) >= 16


class ASP:
    __model = None
    __masks = None
    __pattern = "m4n2_1d"
    __allow = staticmethod(_default_allow)
    __enabled = False

    @classmethod
    def init_model_for_pruning(cls, params, mask_calculator="m4n2_1d",
                               verbosity=2, whitelist=None,
                               allowed_layer_names=None,
                               disallowed_layer_names=(),
                               allow_recompute_mask=False,
                               custom_layer_dict=None):
        """Allocate all-ones masks for prunable params."""
        cls.__pattern = mask_calculator

        def allow(path, leaf):
            name = "/".join(str(getattr(k, "key", k)) for k in path)
            if allowed_layer_names is not None and not any(
                    a in name for a in allowed_layer_names):
                return False
            if any(d in name for d in disallowed_layer_names):
                return False
            return _default_allow(path, leaf)

        cls.__allow = allow
        cls.__masks = jax.tree_util.tree_map_with_path(
            lambda p, l: (jnp.ones_like(l) if allow(p, l) else None), params,
            is_leaf=lambda x: x is None)
        cls.__enabled = False
        return cls.__masks

    @classmethod
    def compute_sparse_masks(cls, params):
        """Magnitude-search masks on current weights
        (reference compute_sparse_masks)."""
        def mk(path, leaf):
            if cls.__allow(path, leaf):
                return create_mask(leaf, cls.__pattern)
            return None

        cls.__masks = jax.tree_util.tree_map_with_path(mk, params)
        cls.__enabled = True
        return cls.__masks

    @classmethod
    def apply_masks(cls, params, masks=None):
        """params * mask (identity where no mask)."""
        masks = masks if masks is not None else cls.__masks

        def apply(m, p):
            return p if m is None else p * m.astype(p.dtype)

        return jax.tree_util.tree_map(
            apply, masks, params, is_leaf=lambda x: x is None)

    @classmethod
    def restore_pruned_weights(cls, params):
        """Disable sparsity (reference restore_pruned_weights) — masks
        become ones; dense values were never destroyed (functional)."""
        cls.__enabled = False
        return params

    @classmethod
    def is_sparsity_enabled(cls):
        return cls.__enabled

    @classmethod
    def prune_trained_model(cls, params, optimizer=None):
        """One-shot recipe (reference prune_trained_model): init + compute
        + apply."""
        cls.init_model_for_pruning(params)
        masks = cls.compute_sparse_masks(params)
        return cls.apply_masks(params, masks)
