"""Cross-layer channel-permutation propagation for 2:4 sparsity.

Parity: reference apex/contrib/sparsity/permutation_lib.py — the torch.fx
graph walk that finds, for every prunable layer, the set of tensors that
must be co-permuted so the network function is preserved (producer output
channels, elementwise/norm params on the channel, consumer input
channels), then applies one jointly-searched permutation per group.

TPU design: JAX models are functional pytrees, not traced module graphs,
so the "graph" is expressed directly as :class:`PermutationGroup` specs —
pytree paths + axes (+ optional regions for packed projections like the
fused [gate | up] swiglu weight). Builders for the in-repo model zoo
(:func:`gpt_permutation_groups`, :func:`t5_permutation_groups`,
:func:`resnet_permutation_groups`) produce the same producer/consumer
pairs the reference's fx walk would discover, without the user plumbing
anything by hand.

Orientation note: ``sparse_masklib.create_mask`` groups 4-wide along the
LAST axis of each 2-D weight (the flax [in, out] layout's output dim), so
the searched/permuted channels are the producer's *output* channels; each
consumer compensates along its *input* axis with the SAME index vector
(a' = a[perm]  ⇒  w_consumer' = w_consumer[perm, :]), and 1-D channel
params (biases, BN scale/bias/mean/var) permute elementwise. Residual-
stream channels are never permuted (same restriction the reference's
group-segmentation enforces at ops it cannot pass through).
"""

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.contrib.sparsity.permutation_lib import (
    search_for_good_permutation,
    sum_after_2_to_4,
)


@dataclasses.dataclass(frozen=True)
class PermSpec:
    """One tensor's participation in a permutation group.

    ``path``: key tuple into the variables pytree (collections included,
    e.g. ``("params", "transformer", "layer_0", ...)``).
    ``axis``: the axis holding the permuted channels.
    ``search``: whether this tensor's retained 2:4 magnitude is part of
    the search objective (True for the masked producer weights; False
    for compensating consumers/passthroughs, whose masks are invariant
    under this permutation).
    ``region``: optional (start, size) slice along ``axis`` for packed
    projections; the permutation acts within the region.
    """

    path: Tuple[Any, ...]
    axis: int
    search: bool = False
    region: Optional[Tuple[int, int]] = None


@dataclasses.dataclass(frozen=True)
class PermutationGroup:
    """Tensors sharing one channel permutation."""

    name: str
    specs: Tuple[PermSpec, ...]


def _get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _set(tree, path, value):
    if not path:
        return value
    head, rest = path[0], path[1:]
    out = dict(tree)
    out[head] = _set(tree[head], rest, value)
    return out


def _channels_last_2d(leaf, axis, region):
    """Slice the region, move ``axis`` last, flatten to [K, C]."""
    if region is not None:
        leaf = jax.lax.slice_in_dim(leaf, region[0], region[0] + region[1],
                                    axis=axis)
    moved = jnp.moveaxis(leaf, axis, -1)
    return moved.reshape(-1, moved.shape[-1])


def _apply_perm(leaf, axis, region, perm):
    perm = jnp.asarray(perm)
    if region is None:
        return jnp.take(leaf, perm, axis=axis)
    leaf = jnp.asarray(leaf)  # .at[] needs a jax array (numpy trees ok)
    start, size = region
    sl = jax.lax.slice_in_dim(leaf, start, start + size, axis=axis)
    sl = jnp.take(sl, perm, axis=axis)
    idx = [slice(None)] * leaf.ndim
    idx[axis] = slice(start, start + size)
    return leaf.at[tuple(idx)].set(sl)


def propagate_permutations(variables, groups: Sequence[PermutationGroup],
                           num_iters: int = 10, chunk: int = 64,
                           verbose: bool = False):
    """Search one permutation per group on the masked producer weights
    and apply it to every member tensor.

    Returns ``(permuted_variables, report)`` where report maps group name
    to ``{"kept_before", "kept_after", "perm"}``. Groups whose search
    finds no improvement are left untouched (identity perm recorded).
    The network function is preserved exactly (up to dtype rounding):
    producers permute outputs, consumers permute the matching inputs.
    """
    report = {}
    for grp in groups:
        search_specs = [s for s in grp.specs if s.search]
        if not search_specs:
            raise ValueError(f"group {grp.name!r} has no search tensors")
        mats = [np.asarray(_channels_last_2d(_get(variables, s.path),
                                             s.axis, s.region),
                           np.float32) for s in search_specs]
        c = mats[0].shape[-1]
        for s, m in zip(search_specs, mats):
            if m.shape[-1] != c:
                raise ValueError(
                    f"group {grp.name!r}: search tensor {s.path} has "
                    f"{m.shape[-1]} channels, expected {c}")
        if c % 4:
            raise ValueError(
                f"group {grp.name!r}: channel count {c} not divisible "
                f"by 4")
        joint = np.concatenate(mats, axis=0)  # [sum K, C]
        before = float(sum_after_2_to_4(jnp.asarray(joint)))
        perm, _ = search_for_good_permutation(joint, num_iters=num_iters,
                                              chunk=chunk)
        after = float(sum_after_2_to_4(jnp.asarray(joint[:, perm])))
        if after > before:
            for s in grp.specs:
                leaf = _get(variables, s.path)
                variables = _set(variables, s.path,
                                 _apply_perm(leaf, s.axis, s.region, perm))
        else:
            perm = np.arange(c)
        report[grp.name] = {"kept_before": before, "kept_after": after,
                            "perm": np.asarray(perm)}
        if verbose:
            print(f"[ASP perm] {grp.name}: kept {before:.2f} -> "
                  f"{after:.2f} ({(after / max(before, 1e-9) - 1) * 100:+.2f}%)")
    return variables, report


# -- model-zoo group builders -------------------------------------------------

def _gpt_layer_root(cfg, variables):
    """Shared root/prefix resolution + scan_layers guard for the GPT
    group builders (one source of truth for the param-tree layout)."""
    if getattr(cfg, "scan_layers", False):
        raise ValueError(
            "permutation groups need per-layer leaves; scan_layers "
            "stacks all layers into one param (a single shared "
            "permutation would be wrong per layer)")
    params = variables["params"]
    if "transformer" in params:
        return params["transformer"], ("params", "transformer")
    return params, ("params",)


def gpt_permutation_groups(cfg, variables):
    """Producer/consumer groups for GPTModel / the parallel transformer
    stack (models/transformer_lm.py): per layer, the MLP interior
    channels — dense_h_to_4h output columns (the masked search target),
    its bias, and dense_4h_to_h input rows. With swiglu/geglu the packed
    [gate | up] projection contributes two same-permutation regions whose
    channels align with the gated product feeding dense_4h_to_h.

    Residual-stream dims are left alone (the same restriction the
    reference's fx walk enforces); attention interiors have their own
    per-head groups in :func:`gpt_attention_permutation_groups`.

    ``variables``: the full ``{"params": ...}`` dict.
    """
    gated = cfg.activation in ("swiglu", "geglu")
    groups = []
    root, prefix = _gpt_layer_root(cfg, variables)
    for name in sorted(k for k in root if k.startswith("layer_")):
        mlp = root[name].get("mlp")
        if mlp is None or "dense_h_to_4h" not in mlp:
            continue  # MoE layer: expert interiors have their own layout
        base = prefix + (name, "mlp")
        specs = []
        if gated:
            # regions from the LOCAL leaf (a tp shard holds 2*ffn/tp
            # columns — [local gate | local up]); cfg.ffn_size would
            # straddle the shard's gate/up boundary under tp>1
            half = mlp["dense_h_to_4h"]["weight"].shape[-1] // 2
            specs.append(PermSpec(base + ("dense_h_to_4h", "weight"),
                                  axis=-1, search=True, region=(0, half)))
            specs.append(PermSpec(base + ("dense_h_to_4h", "weight"),
                                  axis=-1, search=True,
                                  region=(half, half)))
        else:
            specs.append(PermSpec(base + ("dense_h_to_4h", "weight"),
                                  axis=-1, search=True))
            if "bias" in mlp["dense_h_to_4h"]:
                specs.append(PermSpec(base + ("dense_h_to_4h", "bias"),
                                      axis=-1))
        specs.append(PermSpec(base + ("dense_4h_to_h", "weight"), axis=0))
        groups.append(PermutationGroup(f"{name}/mlp", tuple(specs)))
    return groups


def gpt_attention_permutation_groups(cfg, variables):
    """Attention-interior groups for GPTModel (beyond the reference's fx
    walk, which segments at attention): per head, (a) the V-channel
    block of the fused QKV — context channels pass through softmax
    opaquely, so the output projection's matching rows compensate — and
    (b) a JOINT Q+K permutation (scores contract q·k per head, so one
    shared in-head permutation of both leaves them invariant; no
    consumer needed). Q/K groups are skipped under rotary embeddings
    (RoPE pairs specific channel indices) — V groups remain valid there.
    MHA only: the GQA packing interleaves q-blocks and kv-groups.

    ``variables``: the full ``{"params": ...}`` dict.
    """
    if cfg.query_groups != cfg.num_attention_heads:
        raise ValueError(
            "attention permutation groups support MHA only (the GQA "
            "fused layout packs [q heads | kv groups])")
    kv = cfg.kv_channels
    rope = cfg.position_embedding_type == "rope"
    root, prefix = _gpt_layer_root(cfg, variables)
    groups = []
    for name in sorted(k for k in root if k.startswith("layer_")):
        attn = root[name].get("self_attention")
        if attn is None:
            continue
        w = attn["query_key_value"]["weight"]
        n_local = w.shape[-1] // (3 * kv)  # per-rank heads (tp shards)
        base = prefix + (name, "self_attention")
        has_bias = "bias" in attn["query_key_value"]
        for n in range(n_local):
            off = n * 3 * kv
            # (a) V block + output-projection rows
            specs = [PermSpec(base + ("query_key_value", "weight"),
                              axis=-1, search=True,
                              region=(off + 2 * kv, kv))]
            if has_bias:
                specs.append(PermSpec(base + ("query_key_value", "bias"),
                                      axis=-1,
                                      region=(off + 2 * kv, kv)))
            specs.append(PermSpec(base + ("dense", "weight"), axis=0,
                                  region=(n * kv, kv)))
            groups.append(PermutationGroup(f"{name}/attn_v/head_{n}",
                                           tuple(specs)))
            if rope:
                continue  # RoPE pins q/k channel identities
            # (b) joint Q+K in-head permutation (scores invariant)
            specs = [PermSpec(base + ("query_key_value", "weight"),
                              axis=-1, search=True, region=(off, kv)),
                     PermSpec(base + ("query_key_value", "weight"),
                              axis=-1, search=True,
                              region=(off + kv, kv))]
            if has_bias:
                specs += [PermSpec(base + ("query_key_value", "bias"),
                                   axis=-1, region=(off, kv)),
                          PermSpec(base + ("query_key_value", "bias"),
                                   axis=-1, region=(off + kv, kv))]
            groups.append(PermutationGroup(f"{name}/attn_qk/head_{n}",
                                           tuple(specs)))
    return groups


def t5_permutation_groups(cfg, variables):
    """Groups for T5Model (models/t5.py): encoder and decoder FFN
    interiors — wi (or the wi_0/wi_1 pair, jointly searched with one
    shared permutation) output columns + wo input rows.

    ``variables``: the full ``{"params": ...}`` dict."""
    groups = []
    for side, depth in (("encoder", cfg.num_layers),
                        ("decoder", cfg.decoder_layers)):
        for i in range(depth):
            base = ("params", side, f"block_{i}", "ffn")
            ffn = _get(variables, base)
            specs = []
            if "wi" in ffn:
                specs.append(PermSpec(base + ("wi", "weight"), axis=-1,
                                      search=True))
            else:
                specs.append(PermSpec(base + ("wi_0", "weight"), axis=-1,
                                      search=True))
                specs.append(PermSpec(base + ("wi_1", "weight"), axis=-1,
                                      search=True))
            specs.append(PermSpec(base + ("wo", "weight"), axis=0))
            groups.append(PermutationGroup(f"{side}/block_{i}/ffn",
                                           tuple(specs)))
    return groups


def _bn_specs(variables, bn_path_params, bn_path_stats):
    specs = [PermSpec(bn_path_params + ("scale",), axis=0),
             PermSpec(bn_path_params + ("bias",), axis=0)]
    # batch_stats exists once the model has run at least one train step
    try:
        _get(variables, bn_path_stats)
        specs += [PermSpec(bn_path_stats + ("mean",), axis=0),
                  PermSpec(bn_path_stats + ("var",), axis=0)]
    except (KeyError, TypeError):
        pass
    return specs


def resnet_permutation_groups(variables):
    """Groups for the ResNet family (models/resnet.py): inside every
    Basic/Bottleneck block, each conv -> BN -> relu -> conv chain that
    does not touch the residual stream. Conv kernels are NHWC
    [kh, kw, cin, cout]: producers permute axis -1, consumers axis 2,
    and the BatchNorm between permutes scale/bias (+ running mean/var in
    ``batch_stats`` when present)."""
    params = variables["params"]
    groups = []
    for block in sorted(k for k in params
                        if k.startswith(("BottleneckBlock_",
                                         "BasicBlock_"))):
        convs = sorted(k for k in params[block] if k.startswith("Conv_"))
        # chain pairs: Conv_0 -> BN_0 -> Conv_1 (-> BN_1 -> Conv_2);
        # the LAST conv's output feeds the residual sum — locked.
        for a, b in zip(convs[:-1], convs[1:]):
            bn = "BatchNorm_" + a.split("_")[1]
            norm_name = bn if bn in params[block] else (
                "SyncBatchNorm_" + a.split("_")[1])
            specs = [
                PermSpec(("params", block, a, "kernel"), axis=-1,
                         search=True),
                *_bn_specs(variables, ("params", block, norm_name),
                           ("batch_stats", block, norm_name)),
                PermSpec(("params", block, b, "kernel"), axis=2),
            ]
            groups.append(PermutationGroup(f"{block}/{a}->{b}",
                                           tuple(specs)))
    return groups
