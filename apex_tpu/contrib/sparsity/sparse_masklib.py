"""2:4 structured sparsity mask search.

Parity: reference apex/contrib/sparsity/sparse_masklib.py (187 LoC):
``create_mask`` with patterns m4n2_1d (keep the 2 largest of every 4
contiguous weights) and m4n2_2d variants, magnitude-based.

TPU design: fully vectorized top-k over reshaped [N/4, 4] groups — one
fused XLA op chain, no permutation loops.
"""

import jax.numpy as jnp


def m4n2_1d(weights2d):
    """Keep the top-2 |w| in every contiguous group of 4 along the last
    dim. Returns a 0/1 mask of the same shape."""
    h, w = weights2d.shape
    assert w % 4 == 0, "m4n2 requires the last dim divisible by 4"
    g = jnp.abs(weights2d.astype(jnp.float32)).reshape(h, w // 4, 4)
    # rank within each group; keep the two largest
    order = jnp.argsort(g, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = (ranks >= 2).astype(weights2d.dtype)
    return mask.reshape(h, w)


def m4n2_2d_best(weights2d):
    """2D variant: apply 1d masks along rows and pick per-4x4-block the
    orientation with larger retained magnitude (a vectorized stand-in for
    the reference's exhaustive permutation search)."""
    row_mask = m4n2_1d(weights2d)
    if weights2d.shape[0] % 4 != 0:
        return row_mask  # column orientation unavailable for this shape
    col_mask = m4n2_1d(weights2d.T).T
    row_score = jnp.sum(jnp.abs(weights2d) * row_mask)
    col_score = jnp.sum(jnp.abs(weights2d) * col_mask)
    return jnp.where(row_score >= col_score, row_mask, col_mask)


def unstructured_fraction(weights2d, fraction=0.5):
    """Magnitude pruning to a global fraction (reference 'unstructured')."""
    flat = jnp.abs(weights2d).reshape(-1)
    k = int(flat.shape[0] * (1 - fraction))
    thresh = jnp.sort(flat)[-max(k, 1)]
    return (jnp.abs(weights2d) >= thresh).astype(weights2d.dtype)


_PATTERNS = {
    "m4n2_1d": m4n2_1d,
    "m4n2_2d_best": m4n2_2d_best,
}


def create_mask(tensor, pattern="m4n2_1d", density=0.5):
    """Create a sparsity mask (reference sparse_masklib.create_mask).
    Works on [out, in] 2D weights; >2D weights are masked over the last
    dim after flattening leading dims (conv weights: reshape like the
    reference's NHWC handling)."""
    shape = tensor.shape
    t2d = tensor.reshape(-1, shape[-1])
    if t2d.shape[-1] % 4 != 0:
        return jnp.ones_like(tensor)
    mask = _PATTERNS[pattern](t2d)
    return mask.reshape(shape)
