"""Peer-to-peer halo exchange for spatial parallelism.

Parity: reference apex/contrib/peer_memory (peer_memory.py:87 raw peer
pools, peer_halo_exchanger_1d.py:74) + apex/contrib/csrc/nccl_p2p: direct
GPU peer-memory halo exchange used by spatial-parallel convolutions.

TPU design: the peer-memory pool + IPC machinery is replaced by a single
``lax.ppermute`` per direction on the spatial mesh axis — XLA lowers it to
ICI sends that overlap with compute. Interface mirrors
``PeerHaloExchanger1d.__call__`` (halo along the H dim of NHWC tensors).
"""


import jax.numpy as jnp
from jax import lax


class PeerMemoryPool:
    """No-op stand-in (reference peer_memory.py allocates IPC pools; XLA
    manages collective buffers internally)."""

    def __init__(self, static_size=0, dynamic_size=0, peer_ranks=None):
        self.peer_ranks = peer_ranks


def halo_exchange_1d(x, halo: int, axis_name: str = "spatial",
                     dim: int = 1):
    """Exchange ``halo`` rows with spatial neighbors along ``dim``.

    x: local NHWC shard [N, H_local, W, C] (dim=1 -> H). Returns
    (top_halo_from_prev, bottom_halo_from_next): boundary ranks receive
    zeros, matching the reference's explicit-zero boundary handling.
    """
    world = lax.axis_size(axis_name)
    top = lax.slice_in_dim(x, 0, halo, axis=dim)
    bottom = lax.slice_in_dim(x, x.shape[dim] - halo, x.shape[dim], axis=dim)
    # my bottom rows -> next rank's top halo; my top rows -> prev rank's
    # bottom halo
    from_prev = lax.ppermute(bottom, axis_name,
                             [(i, i + 1) for i in range(world - 1)])
    from_next = lax.ppermute(top, axis_name,
                             [(i + 1, i) for i in range(world - 1)])
    return from_prev, from_next


class PeerHaloExchanger1d:
    """Interface parity with reference peer_halo_exchanger_1d.py."""

    def __init__(self, ranks=None, rank_in_group=None, peer_pool=None,
                 half_halo=1, axis_name="spatial"):
        self.half_halo = half_halo
        self.axis_name = axis_name

    def __call__(self, y, H_split: bool = True):
        dim = 1 if H_split else 2
        from_prev, from_next = halo_exchange_1d(
            y, self.half_halo, self.axis_name, dim)
        return jnp.concatenate([from_prev, y, from_next], axis=dim)
