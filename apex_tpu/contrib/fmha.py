"""Fused multi-head attention — Pallas flash attention for TPU.

Parity: reference apex/contrib/fmha (fixed-seq-len fused flash-style
attention, fmha_api.cpp:363 — fp16, seq in {128,256,384,512}, d=64) and
apex/contrib/multihead_attn (CUTLASS-based fused attention). The TPU
version is a general flash-attention: online-softmax over KV blocks, fp32
accumulators, causal or full, any seq multiple of the block size.

Forward is a Pallas kernel (grid: batch*heads x q-blocks; inner
lax.fori_loop over kv blocks with running max/sum). Backward currently
rematerializes through the reference einsum path under ``jax.checkpoint``
semantics (a Pallas backward kernel is the planned next optimization).
"""

import functools

import jax
import jax.numpy as jnp

_INTERPRET = False

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _use_pallas():
    import os

    if os.environ.get("APEX_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal,
                      block_q, block_k, seq_len):
    # q_ref: [1, block_q, d]; k_ref/v_ref: [1, seq, d]; o_ref: [1, block_q, d]
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    d = q.shape[-1]
    num_kv = seq_len // block_k

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    if causal:
        # only blocks j with j*block_k <= (qi+1)*block_q - 1 contribute
        num_kv_eff = jnp.minimum(
            num_kv, (qi + 1) * block_q // block_k + (1 if block_q % block_k else 0))
    else:
        num_kv_eff = num_kv
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv_eff, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, s, d = q.shape
    q3 = q.reshape(b * n, s, d)
    k3 = k.reshape(b * n, s, d)
    v3 = v.reshape(b * n, s, d)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    grid = (b * n, s // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, seq_len=s)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s, d), lambda h, i: (h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s, d), lambda h, i: (h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda h, i: (h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * n, s, d), q.dtype),
        interpret=_INTERPRET,
    )(q3, k3, v3)
    return out.reshape(b, n, s, d)


def _attention_reference(q, k, v, scale, causal):
    """Reference einsum attention (fp32 softmax), used for the backward
    rematerialization and the non-TPU fallback."""
    s = jnp.einsum("bnqd,bnkd->bnqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=True, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Flash attention over [batch, heads, seq, head_dim] inputs."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if _use_pallas() and q.shape[-2] % min(block_q, q.shape[-2]) == 0:
        return _flash_fwd_pallas(q, k, v, scale, causal, block_q, block_k)
    return _attention_reference(q, k, v, scale, causal)


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_reference(q_, k_, v_, scale, causal),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


class FMHA:
    """Class-style entry point (parity: apex/contrib/fmha/fmha.py FMHAFun).
    The reference restricts to seq in {128,256,384,512}, d=64; the TPU
    kernel is general but the same restriction check is exposed."""

    supported_seq_lens = (128, 256, 384, 512)

    def __init__(self, causal=False):
        self.causal = causal

    def __call__(self, qkv, cu_seqlens=None, seqlen=None):
        # qkv: [total, 3, heads, d] packed like the reference; here assume
        # dense [b, s, 3, n, d]
        q, k, v = (qkv[..., i, :, :] for i in range(3))
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        out = flash_attention(q, k, v, self.causal)
        return out.transpose(0, 2, 1, 3)
