"""Fused multi-head attention — Pallas flash attention for TPU.

Parity: reference apex/contrib/fmha (fixed-seq-len fused flash-style
attention, fmha_api.cpp:363 — fp16, seq in {128,256,384,512}, d=64) and
apex/contrib/multihead_attn (CUTLASS-based fused attention). The TPU
version is a general flash-attention: online-softmax over KV blocks, fp32
accumulators, causal or full, any seq multiple of the block size.

Forward is a Pallas kernel over a 3-D grid (batch*heads x q-blocks x
kv-blocks, kv innermost/"arbitrary"): K/V stream through VMEM one
[block_k, d] tile at a time with running (acc, max, sum) scratch state, so
VMEM use is independent of sequence length (validated to seq 65536
on-chip; see PERF.md). Backward rematerializes through the reference
einsum path (a Pallas backward kernel is the planned next optimization).
"""

import functools

import jax
import jax.numpy as jnp

_INTERPRET = False

# 512x512 measured fastest on-chip at seq 8192 (8.0 TFLOP/s vs 3.8 at
# 128x128); both are min()'d down for shorter sequences.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _use_pallas():
    import os

    if os.environ.get("APEX_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                      scale, causal, block_q, block_k, num_kv):
    """One (head, q-block, kv-block) grid cell of online-softmax attention.

    K/V arrive as [1, block_k, d] VMEM tiles streamed by the grid — VMEM
    use is independent of sequence length (the previous design staged the
    FULL [seq, d] K/V per program, which Mosaic refuses to compile beyond
    seq ~8k). The kv axis is the innermost, "arbitrary" grid dimension;
    running (acc, m, l) state lives in scratch across its iterations.
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: kv blocks entirely above the diagonal contribute nothing.
    run = (kj * block_k <= (qi + 1) * block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if causal:
            q_ids = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1)[:, None]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1)[:, None]
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(kj == num_kv - 1)
    def _finish():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, s, d = q.shape
    q3 = q.reshape(b * n, s, d)
    k3 = k.reshape(b * n, s, d)
    v3 = v.reshape(b * n, s, d)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    num_kv = s // block_k
    grid = (b * n, s // block_q, num_kv)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_kv=num_kv)

    if causal:
        # Clamp masked kv blocks to the last contributing one: Pallas
        # skips the DMA when a block index repeats, so fully-above-diagonal
        # K/V tiles are never fetched (the fori_loop design's early exit).
        def kv_index(h, i, j):
            last = ((i + 1) * block_q - 1) // block_k
            return (h, jnp.minimum(j, last), 0)
    else:
        def kv_index(h, i, j):
            return (h, j, 0)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_index,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * n, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_INTERPRET,
    )(q3, k3, v3)
    return out.reshape(b, n, s, d)


def _attention_reference(q, k, v, scale, causal):
    """Reference einsum attention (fp32 softmax), used for the backward
    rematerialization and the non-TPU fallback."""
    s = jnp.einsum("bnqd,bnkd->bnqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _fit_block(block, s):
    """Largest of (block, 256, 128, s) that divides s, so seq lengths that
    are 128-multiples but not block-multiples stay on the kernel instead
    of silently falling back to the O(s^2) reference path."""
    for cand in (block, 256, 128):
        b = min(cand, s)
        if s % b == 0:
            return b
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=True, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Flash attention over [batch, heads, seq, head_dim] inputs."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = q.shape[-2]
    bq, bk = _fit_block(block_q, s), _fit_block(block_k, s)
    if _use_pallas() and bq is not None and bk is not None:
        return _flash_fwd_pallas(q, k, v, scale, causal, bq, bk)
    return _attention_reference(q, k, v, scale, causal)


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_reference(q_, k_, v_, scale, causal),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


class FMHA:
    """Class-style entry point (parity: apex/contrib/fmha/fmha.py FMHAFun).
    The reference restricts to seq in {128,256,384,512}, d=64; the TPU
    kernel is general but the same restriction check is exposed."""

    supported_seq_lens = (128, 256, 384, 512)

    def __init__(self, causal=False):
        self.causal = causal

    def __call__(self, qkv, cu_seqlens=None, seqlen=None):
        # qkv: [total, 3, heads, d] packed like the reference; here assume
        # dense [b, s, 3, n, d]
        q, k, v = (qkv[..., i, :, :] for i in range(3))
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        out = flash_attention(q, k, v, self.causal)
        return out.transpose(0, 2, 1, 3)
