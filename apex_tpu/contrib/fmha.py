"""Fused multi-head attention — Pallas flash attention for TPU.

Parity: reference apex/contrib/fmha (fixed-seq-len fused flash-style
attention, fmha_api.cpp:363 — fp16, seq in {128,256,384,512}, d=64) and
apex/contrib/multihead_attn (CUTLASS-based fused attention). The TPU
version is a general flash-attention: online-softmax over KV blocks, fp32
accumulators, causal or full, any seq multiple of the block size.

Forward and backward are Pallas kernels over 3-D grids (batch*heads x
outer-blocks x streamed-blocks, innermost/"arbitrary"): K/V (forward, dq)
or Q/dO (dk/dv) stream through VMEM one tile at a time with fp32 scratch
accumulators, so VMEM use is independent of sequence length (validated to
seq 65536 on-chip; see PERF.md). The forward emits the per-row
log-sum-exp; the backward recomputes p = exp(q k^T scale - lse) per tile
(flash-attention v2 style) instead of materializing the [s, s] matrix.
Off-TPU both passes fall back to the reference einsum path; on TPU,
sequence lengths that no block fits (not a multiple of any of 512/256/128
and larger than 512) fall back the same way, while short sequences use
the whole sequence as one block.
"""

import functools
import numbers

import jax
import jax.numpy as jnp

_INTERPRET = False

# 512x512 measured fastest on-chip at seq 8192 (8.0 TFLOP/s vs 3.8 at
# 128x128); both are min()'d down for shorter sequences.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _use_pallas():
    import os

    if os.environ.get("APEX_TPU_DISABLE_PALLAS", "0") == "1":
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _causal_mask(scores, qi, kj, block_q, block_k, window=None):
    """Mask score entries above the diagonal for a (qi, kj) block pair;
    with ``window`` also below the sliding-window band (key j visible to
    query i iff 0 <= i - j < window)."""
    q_ids = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_ids = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    visible = q_ids >= k_ids
    if window is not None:
        visible = visible & (q_ids - k_ids < window)
    return jnp.where(visible, scores, NEG_INF)


def _alibi_bias(slopes_ref, kj, block_q, block_k):
    """Key-position-only alibi bias for a (qi, kj) block pair: row
    constants cancel in softmax, so slope * absolute-key-index is the
    whole bias (HF build_alibi_tensor form)."""
    k_ids = kj * block_k + jax.lax.broadcasted_iota(
        jnp.float32, (block_q, block_k), 1)
    return slopes_ref[0, 0, 0] * k_ids


def _stream_kv_run(qi, kj, block_q, block_k, causal, window):
    """Does kv block kj contribute to q block qi? (fwd / dq kernels)"""
    if not causal:
        return True
    run = kj * block_k <= (qi + 1) * block_q - 1
    if window is not None:
        run = run & ((kj + 1) * block_k - 1 >= qi * block_q - window + 1)
    return run


def _stream_q_run(qi, kj, block_q, block_k, causal, window):
    """Does q block qi contribute to kv block kj? (dkv kernel)"""
    if not causal:
        return True
    run = (qi + 1) * block_q - 1 >= kj * block_k
    if window is not None:
        run = run & (qi * block_q <= _window_last_q_pos(kj, block_k,
                                                        window))
    return run


def _window_first_kv_block(qi, block_q, block_k, window):
    """First kv block inside the band for q block qi (index-map clamp;
    must stay consistent with _stream_kv_run's lower bound)."""
    return jnp.maximum(qi * block_q - window + 1, 0) // block_k


def _window_last_q_pos(kj, block_k, window):
    """Largest query index that can see any key in kv block kj."""
    return (kj + 1) * block_k - 1 + window - 1


def _flash_fwd_kernel(q_ref, k_ref, v_ref, slopes_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *, scale, causal, block_q,
                      block_k, num_kv, window, alibi):
    """One (head, q-block, kv-block) grid cell of online-softmax attention.

    K/V arrive as [1, block_k, d] VMEM tiles streamed by the grid — VMEM
    use is independent of sequence length (the previous design staged the
    FULL [seq, d] K/V per program, which Mosaic refuses to compile beyond
    seq ~8k). The kv axis is the innermost, "arbitrary" grid dimension;
    running (acc, m, l) state lives in scratch across its iterations.
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: kv blocks entirely above the diagonal (or, windowed, fully
    # below the band) contribute nothing.
    run = _stream_kv_run(qi, kj, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if alibi:
            s = s + _alibi_bias(slopes_ref, kj, block_q, block_k)
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, window)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1)[:, None]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1)[:, None]
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(kj == num_kv - 1)
    def _finish():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # log-sum-exp of the scaled scores, for the backward kernels
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _slopes_input(alibi_slopes, b, n):
    """[n] per-head slopes -> [b*n, 1, 1] grid input (zeros when alibi
    is off — the kernel branch is static, the input just needs a shape)."""
    if alibi_slopes is None:
        return jnp.zeros((b * n, 1, 1), jnp.float32)
    return jnp.broadcast_to(
        alibi_slopes.astype(jnp.float32)[None, :], (b, n)
    ).reshape(b * n, 1, 1)


def _flash_fwd_pallas(q, k, v, scale, causal, block_q, block_k,
                      window=None, alibi_slopes=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, s, d = q.shape
    q3 = q.reshape(b * n, s, d)
    k3 = k.reshape(b * n, s, d)
    v3 = v.reshape(b * n, s, d)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    num_kv = s // block_k
    grid = (b * n, s // block_q, num_kv)
    slopes3 = _slopes_input(alibi_slopes, b, n)
    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_kv=num_kv, window=window,
        alibi=alibi_slopes is not None)

    if causal:
        # Clamp masked kv blocks into the contributing range: Pallas
        # skips the DMA when a block index repeats, so fully-above-diagonal
        # (and, windowed, fully-below-band) K/V tiles are never fetched.
        def kv_index(h, i, j):
            last = ((i + 1) * block_q - 1) // block_k
            j = jnp.minimum(j, last)
            if window is not None:
                j = jnp.maximum(j, _window_first_kv_block(
                    i, block_q, block_k, window))
            return (h, j, 0)
    else:
        def kv_index(h, i, j):
            return (h, j, 0)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1), lambda h, i, j: (h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * n, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * n, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running sum
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_INTERPRET,
    )(q3, k3, v3, slopes3)
    return out.reshape(b, n, s, d), lse.reshape(b, n, s)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     slopes_ref, dq_ref, dq_acc, *, scale, causal,
                     block_q, block_k, num_kv, window, alibi):
    """dq for one q block, streaming kv blocks (innermost grid dim):
    p = exp(q k^T scale - lse); ds = p * (do v^T - delta); dq += ds k scale.
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = _stream_kv_run(qi, kj, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if alibi:
            s = s + _alibi_bias(slopes_ref, kj, block_q, block_k)
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, window)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[...] += jnp.dot(ds, k,
                               preferred_element_type=jnp.float32) * scale

    @pl.when(kj == num_kv - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                      slopes_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                      scale, causal, block_q, block_k, num_q, window,
                      alibi):
    """dk/dv for one kv block, streaming q blocks (innermost grid dim):
    dv += p^T do;  dk += ds^T q scale."""
    from jax.experimental import pallas as pl

    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # Causal: q blocks entirely above this kv block (or, windowed, beyond
    # the band) contribute nothing.
    run = _stream_q_run(qi, kj, block_q, block_k, causal, window)

    @pl.when(run)
    def _step():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if alibi:
            s = s + _alibi_bias(slopes_ref, kj, block_q, block_k)
        if causal:
            s = _causal_mask(s, qi, kj, block_q, block_k, window)
        p = jnp.exp(s - lse)
        dv_acc[...] += jnp.dot(p.T, do,
                               preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[...] += jnp.dot(ds.T, q,
                               preferred_element_type=jnp.float32) * scale

    @pl.when(qi == num_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, scale, causal, block_q,
                      block_k, window=None, alibi_slopes=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, s, d = q.shape
    q3, k3, v3 = (x.reshape(b * n, s, d) for x in (q, k, v))
    o3, do3 = (x.reshape(b * n, s, d) for x in (o, do))
    lse3 = lse.reshape(b * n, s, 1)
    # delta_i = rowsum(do_i * o_i) — cheap elementwise+reduce, XLA-fused
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)
    num_q = s // block_q
    num_kv = s // block_k
    slopes3 = _slopes_input(alibi_slopes, b, n)
    alibi = alibi_slopes is not None

    if causal:
        def kv_index(h, i, j):
            last = ((i + 1) * block_q - 1) // block_k
            j = jnp.minimum(j, last)
            if window is not None:
                j = jnp.maximum(j, _window_first_kv_block(
                    i, block_q, block_k, window))
            return (h, j, 0)

        def q_index_for_kv(h, j, i):
            first = (j * block_k) // block_q
            i = jnp.maximum(i, first)
            if window is not None:
                i = jnp.minimum(
                    i, _window_last_q_pos(j, block_k, window) // block_q)
            return (h, i, 0)
    else:
        kv_index = lambda h, i, j: (h, j, 0)            # noqa: E731
        q_index_for_kv = lambda h, j, i: (h, i, 0)      # noqa: E731

    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_kv=num_kv,
                          window=window, alibi=alibi),
        grid=(b * n, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), kv_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1), lambda h, i, j: (h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda h, i, j: (h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * n, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_INTERPRET,
    )(q3, k3, v3, do3, lse3, delta, slopes3)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, num_q=num_q,
                          window=window, alibi=alibi),
        grid=(b * n, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), q_index_for_kv,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), q_index_for_kv,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), q_index_for_kv,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), q_index_for_kv,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1), lambda h, j, i: (h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda h, j, i: (h, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * n, s, d), k.dtype),
            jax.ShapeDtypeStruct((b * n, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_INTERPRET,
    )(k3, v3, q3, do3, lse3, delta, slopes3)

    rs = lambda x: x.reshape(b, n, s, d)  # noqa: E731
    return rs(dq), rs(dk), rs(dv)


def _attention_reference(q, k, v, scale, causal, window=None,
                         alibi_slopes=None):
    """Reference einsum attention (fp32 softmax), used for the backward
    rematerialization and the non-TPU fallback."""
    s = jnp.einsum("bnqd,bnkd->bnqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if alibi_slopes is not None:
        s = s + (alibi_slopes.astype(jnp.float32)[None, :, None, None]
                 * jnp.arange(s.shape[-1], dtype=jnp.float32
                              )[None, None, None, :])
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        if window is not None:
            mask = mask & jnp.triu(jnp.ones((sq, sk), bool),
                                   k=sk - sq - window + 1)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bnqk,bnkd->bnqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _fit_block(block, s):
    """Largest of (block, 256, 128, s) that divides s, so seq lengths that
    are 128-multiples but not block-multiples stay on the kernel instead
    of silently falling back to the O(s^2) reference path."""
    for cand in (block, 256, 128):
        b = min(cand, s)
        if s % b == 0:
            return b
    return None


def _resolve(q, scale, block_q, block_k):
    import numbers

    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    elif not isinstance(scale, numbers.Number):
        # scale sits in custom_vjp nondiff_argnums: a traced value (e.g.
        # 1/jnp.sqrt(d)) surfaces as a cryptic UnexpectedTracerError deep
        # inside autodiff — fail fast with the actual contract instead.
        raise TypeError(
            "flash_attention scale must be a python number (it is a "
            f"static argument of the custom_vjp), got {type(scale)}; "
            "pass scale=None for the 1/sqrt(head_dim) default")
    s = q.shape[-2]
    return scale, _fit_block(block_q, s), _fit_block(block_k, s)


def _check_window(window, causal):
    if window is None:
        return
    if not causal:
        raise ValueError("flash_attention window requires causal=True")
    # numbers.Integral admits numpy scalars from parsed configs; bool is
    # an int subclass and must not silently mean window=1.
    if (isinstance(window, bool) or not isinstance(window, numbers.Integral)
            or window < 1):
        raise ValueError(f"flash_attention window must be a positive "
                         f"static int, got {window!r}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal=True, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    window=None, alibi_slopes=None):
    """Flash attention over [batch, heads, seq, head_dim] inputs.

    ``window``: sliding-window band (key j visible to query i iff
    0 <= i - j < window); blocks fully outside the band are skipped, so
    compute scales with seq * window instead of seq^2.
    ``alibi_slopes``: per-head [heads] slopes adding the key-position
    alibi bias inside the kernel. Treated as NON-DIFFERENTIABLE (the
    returned cotangent is zero, matching the CUDA flash-attention
    convention) — trained-ALiBi variants must not route slope gradients
    through this op."""
    _check_window(window, causal)
    scale, bq, bk = _resolve(q, scale, block_q, block_k)
    if _use_pallas() and bq is not None and bk is not None:
        return _flash_fwd_pallas(q, k, v, scale, causal, bq, bk,
                                 window, alibi_slopes)[0]
    return _attention_reference(q, k, v, scale, causal, window,
                                alibi_slopes)


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k,
                    window=None, alibi_slopes=None):
    _check_window(window, causal)
    scale_, bq, bk = _resolve(q, scale, block_q, block_k)
    if _use_pallas() and bq is not None and bk is not None:
        out, lse = _flash_fwd_pallas(q, k, v, scale_, causal, bq, bk,
                                     window, alibi_slopes)
        return out, (q, k, v, out, lse, alibi_slopes)
    return (_attention_reference(q, k, v, scale_, causal, window,
                                 alibi_slopes),
            (q, k, v, None, None, alibi_slopes))


def _flash_bwd_rule(causal, scale, block_q, block_k, window, res, g):
    q, k, v, out, lse, alibi_slopes = res
    scale_, bq, bk = _resolve(q, scale, block_q, block_k)
    none_slope_grad = (None if alibi_slopes is None
                       else jnp.zeros_like(alibi_slopes))
    if lse is not None and _use_pallas():
        dq, dk, dv = _flash_bwd_pallas(q, k, v, out, lse, g, scale_,
                                       causal, bq, bk, window,
                                       alibi_slopes)
        return dq, dk, dv, none_slope_grad
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_reference(q_, k_, v_, scale_,
                                                causal, window,
                                                alibi_slopes),
        q, k, v)
    return (*vjp(g), none_slope_grad)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


class FMHA:
    """Class-style entry point (parity: apex/contrib/fmha/fmha.py FMHAFun).
    The reference restricts to seq in {128,256,384,512}, d=64; the TPU
    kernel is general but the same restriction check is exposed."""

    supported_seq_lens = (128, 256, 384, 512)

    def __init__(self, causal=False):
        self.causal = causal

    def __call__(self, qkv, cu_seqlens=None, seqlen=None):
        # qkv: [total, 3, heads, d] packed like the reference; here assume
        # dense [b, s, 3, n, d]
        q, k, v = (qkv[..., i, :, :] for i in range(3))
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        out = flash_attention(q, k, v, self.causal)
        return out.transpose(0, 2, 1, 3)
