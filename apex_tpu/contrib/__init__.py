"""apex_tpu.contrib — fused extras.

Parity: reference apex/contrib (each subpackage behind its own build flag,
README.md:155-182). On TPU no build flags are needed; everything is
importable, with Pallas kernels engaging on TPU backends.
"""

from apex_tpu.contrib import bottleneck  # noqa: F401
from apex_tpu.contrib import clip_grad  # noqa: F401
from apex_tpu.contrib import conv_bias_relu  # noqa: F401
from apex_tpu.contrib import cudnn_gbn  # noqa: F401
from apex_tpu.contrib import fmha  # noqa: F401
from apex_tpu.contrib import focal_loss  # noqa: F401
from apex_tpu.contrib import groupbn  # noqa: F401
from apex_tpu.contrib import layer_norm  # noqa: F401
from apex_tpu.contrib import index_mul_2d  # noqa: F401
from apex_tpu.contrib import multihead_attn  # noqa: F401
from apex_tpu.contrib import optimizers  # noqa: F401
from apex_tpu.contrib import peer_memory  # noqa: F401
from apex_tpu.contrib import sparsity  # noqa: F401
from apex_tpu.contrib import transducer  # noqa: F401
from apex_tpu.contrib import xentropy  # noqa: F401
