"""Fused softmax cross-entropy with label smoothing.

Parity: reference apex/contrib/xentropy (softmax_xentropy.py:30 +
csrc/xentropy/xentropy_kernel.cu:718) — ``SoftmaxCrossEntropyLoss`` with
``label_smoothing``, ``padding_idx``, half-to-float.

TPU design: one jitted fp32 log-softmax chain; XLA fuses it into a single
pass (the CUDA kernel's job). Differentiable via autodiff — the backward
(softmax - smoothed-onehot) falls out of the vjp.
"""

import jax
import jax.numpy as jnp


def softmax_cross_entropy_loss(logits, labels, smoothing=0.0,
                               padding_idx=0, half_to_float=False):
    """Per-token loss [N] over logits [N, V] (reference SoftmaxCrossEntropyLoss
    semantics; ``padding_idx`` tokens get zero loss)."""
    logits32 = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits32, axis=-1)
    picked = jnp.take_along_axis(logits32, labels[..., None], axis=-1)[..., 0]
    loss = logz - picked
    if smoothing > 0:
        mean_logits = jnp.mean(logits32, axis=-1)
        smooth_loss = logz - mean_logits
        loss = (1.0 - smoothing) * loss + smoothing * smooth_loss
    if padding_idx is not None:
        loss = jnp.where(labels == padding_idx, 0.0, loss)
    if half_to_float:
        return loss  # fp32 regardless of input dtype
    return loss.astype(logits.dtype)


class SoftmaxCrossEntropyLoss:
    """Module-style alias (reference softmax_xentropy.py)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=0,
              half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, smoothing,
                                          padding_idx, half_to_float)
