"""FastLayerNorm — the contrib high-performance LayerNorm entry point.

Parity: reference apex/contrib/layer_norm/layer_norm.py:34-56
(``FastLayerNorm(hidden_size, eps)`` module + ``_fast_layer_norm``
functional, backed by csrc/layer_norm/ kernels for hidden sizes up to
64k). On TPU the same Pallas layernorm kernel that serves
``apex_tpu.normalization.FusedLayerNorm`` is the fast path — there is one
kernel, exposed under both entry points like the reference wires contrib
FastLayerNorm into transformer/layers/layer_norm.py:11-16.
"""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.normalization.fused_layer_norm import fused_layer_norm_affine


def _fast_layer_norm(x, weight, bias, epsilon):
    """Functional form (reference layer_norm.py:34-37)."""
    return fused_layer_norm_affine(x, weight, bias, (x.shape[-1],),
                                   eps=epsilon)


class FastLayerNorm(nn.Module):
    """Module parity with reference FastLayerNorm(hidden_size, eps=1e-5):
    affine LayerNorm over the last dim; param names match FusedLayerNorm
    so checkpoints interchange between the two entry points."""

    hidden_size: int
    eps: float = 1e-5
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        weight = self.param("weight", nn.initializers.ones,
                            (self.hidden_size,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.hidden_size,), self.param_dtype)
        return _fast_layer_norm(x, weight, bias, self.eps)
