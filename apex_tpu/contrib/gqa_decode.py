"""Pallas streaming kernel for KV-cache decode attention (TPU).

The serving hot loop (transformer_lm.py ParallelAttention
._decode_attention, single-token steps) scores each new query against
the whole cache buffer with an XLA einsum: [b, g, rep, T] fp32 scores
materialize in HBM, the cache is read twice (scores + combine), and the
masked dead tail beyond the live prefix is still fetched. This kernel
streams K/V through VMEM in ``block_t`` tiles ONCE per (batch, kv-group)
with an online softmax over the tile axis; all ``rep`` query heads of a
group share the tile (the GQA memory saving survives into the kernel).
Scalar-prefetched prefix length clamps the tile index map, so tiles
beyond the live prefix — and, for sliding-window layers, tiles before
``length - window`` — are never DMA'd: windowed decode cost is
O(window), not O(max_len).

Gemma-2-style tanh soft-capping is applied in-kernel (elementwise on
scores before masking — the online softmax is unaffected). ALiBi decode
stays on the einsum path.

Reference analog: apex/contrib/fmha exists purely to make attention
fast (fmha_api.cpp:363); this is the same move for the decode loop the
way contrib/mla_decode.py is for the MLA latent cache. Off TPU the
public entry falls back to the einsum formulation (also the parity
oracle for the kernel tests).
"""

import functools

import jax
import jax.numpy as jnp

from apex_tpu.contrib._pallas_gate import PallasGate, choose_block

NEG_INF = -1e30
DEFAULT_BLOCK_T = 512

_GATE = PallasGate("APEX_TPU_DECODE_FLASH")


def force_interpret(on: bool):
    """Run the kernel in interpreter mode regardless of backend (tests:
    exercises the real kernel dataflow on the CPU mesh)."""
    _GATE.force_interpret(on)


def gqa_decode_reference(q, k, v, length, sm_scale, window=None,
                         softcap=None):
    """Einsum formulation (the oracle): q [b, g, rep, d], k/v
    [T, b, g, d], length [] int32 -> ctx [b, g, rep, d] fp32."""
    s = jnp.einsum("bgrd,tbgd->bgrt", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale
    if softcap is not None:
        cap = jnp.float32(softcap)
        s = cap * jnp.tanh(s / cap)
    t = jnp.arange(k.shape[0])[None, None, None, :]
    masked = t >= length
    if window is not None:
        masked = masked | (t < length - window)
    s = jnp.where(masked, NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bgrt,tbgd->bgrd", p, v.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, sm_scale, softcap, window, block_t, num_t):
    """One (batch, group, cache-tile) grid cell: the group's rep query
    heads share the tile, online softmax across the streamed tile
    axis."""
    from jax.experimental import pallas as pl

    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[0]
    live = j * block_t < length
    if window is not None:
        start = jnp.maximum(length - window, 0)
        live = live & ((j + 1) * block_t > start)

    @pl.when(live)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * sm_scale  # [rep, d]
        k = k_ref[:, 0, 0, :].astype(jnp.float32)       # [block_t, d]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        if softcap is not None:
            cap = jnp.float32(softcap)
            s = cap * jnp.tanh(s / cap)
        t_ids = j * block_t + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        masked = t_ids >= length
        if window is not None:
            masked = masked | (t_ids < length - window)
        s = jnp.where(masked, NEG_INF, s)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1)[:, None])
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1)[:, None]
        m_ref[...] = m_new
        vv = v_ref[:, 0, 0, :].astype(jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, vv, preferred_element_type=jnp.float32)

    @pl.when(j == num_t - 1)
    def _finish():
        o_ref[0, 0] = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)


def _decode_pallas(q, k, v, length, sm_scale, softcap, window, block_t):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, g, rep, d = q.shape
    T = k.shape[0]
    num_t = T // block_t
    kernel = functools.partial(_decode_kernel, sm_scale=sm_scale,
                               softcap=softcap, window=window,
                               block_t=block_t, num_t=num_t)

    def kv_index(bi, gi, j, len_ref):
        # clamp into the live tile range: a repeated block index skips
        # the DMA, so neither the dead tail nor (with a window) the
        # expired head of the cache is ever fetched
        last = jnp.maximum(len_ref[0] - 1, 0) // block_t
        if window is None:
            first = 0
        else:
            first = jnp.maximum(len_ref[0] - window, 0) // block_t
        return (jnp.clip(j, first, last), bi, gi, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, g, num_t),
        in_specs=[
            pl.BlockSpec((1, 1, rep, d),
                         lambda bi, gi, j, len_ref: (bi, gi, 0, 0)),
            pl.BlockSpec((block_t, 1, 1, d), kv_index),
            pl.BlockSpec((block_t, 1, 1, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, d),
                               lambda bi, gi, j, len_ref: (bi, gi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, d), jnp.float32),  # acc
            pltpu.VMEM((rep, 1), jnp.float32),  # running max
            pltpu.VMEM((rep, 1), jnp.float32),  # running sum
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, g, rep, d), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_GATE.interpret,
    )(jnp.asarray(length, jnp.int32).reshape(1), q, k, v)


def use_flash(cache_len: int, block_t: int = DEFAULT_BLOCK_T) -> bool:
    """True when the kernel would actually run (TPU/interpret AND the
    block ladder finds a tile dividing the cache buffer). Callers gate
    on this so the non-kernel path is their own production einsum
    formulation."""
    return _GATE.enabled() and choose_block(cache_len, block_t) is not None


def gqa_flash_decode(q, k, v, length, sm_scale, window=None, softcap=None,
                     block_t=DEFAULT_BLOCK_T):
    """Streaming KV-cache decode attention for one token step.

    q:      [b, g, rep, d] grouped queries (rep = heads per kv group).
    k, v:   [T, b, g, d] cache buffers (transformer_lm decode layout).
    length: [] int32 — live prefix length INCLUDING the current token.
    window: optional sliding window (Mistral semantics).
    softcap: optional Gemma-2 tanh score cap.
    Returns ctx [b, g, rep, d] fp32.

    Falls back to the einsum oracle off-TPU or when no block divides
    the cache buffer (``use_flash`` tells a caller which way it goes).
    """
    T = k.shape[0]
    if not use_flash(T, block_t):
        return gqa_decode_reference(q, k, v, length, sm_scale, window,
                                    softcap)
    return _decode_pallas(q, k, v, length, sm_scale, softcap, window,
                          choose_block(T, block_t))
