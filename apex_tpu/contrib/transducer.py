"""Transducer (RNN-T) joint and loss.

Parity: reference apex/contrib/transducer (transducer.py:195 TransducerJoint
/ TransducerLoss + csrc joint 979 + loss 767 LoC CUDA, with a pure-Python
oracle _transducer_ref.py:109).

TPU design: the joint is a broadcast add (+ optional relu/dropout) that XLA
fuses; the loss is the standard RNN-T forward-backward recursion expressed
as a ``lax.scan`` over anti-diagonals (wavefront) so the whole alpha/beta
computation is one compiled loop. Gradients come from autodiff of the
log-partition (numerically identical to the hand-written backward).
"""

import jax
import jax.numpy as jnp
from jax import lax


class TransducerJoint:
    """f[t] (+) g[u] joint (reference TransducerJoint: pack/relu/dropout
    options; packing is a GPU memory trick — unneeded with XLA fusion)."""

    def __init__(self, pack_output=False, relu=False, dropout=False,
                 dropout_prob=0.0):
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob

    def __call__(self, f, g, f_len=None, g_len=None, batch_offset=None,
                 packed_batch=0, rng=None):
        # f: [B, T, H], g: [B, U, H] -> [B, T, U, H]
        out = f[:, :, None, :] + g[:, None, :, :]
        if self.relu:
            out = jnp.maximum(out, 0.0)
        if self.dropout and rng is not None and self.dropout_prob > 0:
            keep = jax.random.bernoulli(rng, 1 - self.dropout_prob, out.shape)
            out = jnp.where(keep, out / (1 - self.dropout_prob), 0.0)
        return out


def _logsumexp2(a, b):
    m = jnp.maximum(a, b)
    m = jnp.where(jnp.isinf(m) & (m < 0), 0.0, m)
    return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m))


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx=0):
    """RNN-T negative log-likelihood.

    log_probs: [B, T, U+1, V] log-softmax over vocab; labels: [B, U];
    f_len: [B] valid time steps; y_len: [B] valid label lengths.
    Forward variable alpha computed row-by-row with lax.scan (each row is
    a length-(U+1) associative recursion along u).
    """
    B, T, U1, V = log_probs.shape
    U = U1 - 1
    blank_lp = log_probs[..., blank_idx]  # [B, T, U+1]
    lbl_lp = jnp.take_along_axis(
        log_probs[:, :, :U, :], labels[:, None, :, None], axis=-1)[..., 0]
    # pad label emissions to U+1 with -inf at u=U
    lbl_lp = jnp.pad(lbl_lp, ((0, 0), (0, 0), (0, 1)),
                     constant_values=-jnp.inf)  # [B, T, U+1]

    def scan_t(alpha_prev, t):
        # emit from the previous time step: alpha_prev[u] + blank[t-1, u]
        from_blank = alpha_prev + blank_lp[:, t - 1, :]
        # label advance within this time step: sequential over u — do with
        # an associative scan: alpha[u] = logsumexp(from_blank[u],
        # alpha[u-1] + lbl_lp[t, u-1])
        def scan_u(carry, inp):
            fb, lbl_prev = inp
            a = _logsumexp2(fb, carry + lbl_prev)
            return a, a

        lbl_shift = lbl_lp[:, t, :]  # [B, U+1]; at position u-1 when used
        # process u=0 separately (no label entry)
        a0 = from_blank[:, 0]
        _, rest = lax.scan(
            scan_u, a0,
            (from_blank[:, 1:].swapaxes(0, 1),
             lbl_shift[:, :-1].swapaxes(0, 1)))
        alpha = jnp.concatenate([a0[:, None], rest.swapaxes(0, 1)], axis=1)
        return alpha, alpha

    # t = 0 row: only label advances from alpha[0,0]=0
    def init_row():
        def scan_u(carry, lbl_prev):
            a = carry + lbl_prev
            return a, a

        a0 = jnp.zeros((B,))
        _, rest = lax.scan(scan_u, a0, lbl_lp[:, 0, :-1].swapaxes(0, 1))
        return jnp.concatenate([a0[:, None], rest.swapaxes(0, 1)], axis=1)

    alpha0 = init_row()
    _, alphas = lax.scan(scan_t, alpha0, jnp.arange(1, T))
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U+1]
    alphas = alphas.transpose(1, 0, 2)  # [B, T, U+1]

    # NLL = -(alpha[f_len-1, y_len] + blank[f_len-1, y_len])
    t_idx = jnp.clip(f_len - 1, 0, T - 1)
    u_idx = jnp.clip(y_len, 0, U)
    final_alpha = alphas[jnp.arange(B), t_idx, u_idx]
    final_blank = blank_lp[jnp.arange(B), t_idx, u_idx]
    return -(final_alpha + final_blank)


class TransducerLoss:
    def __init__(self, fuse_softmax_backward=True, opt=1,
                 packed_input=False):
        pass

    def __call__(self, x, label, f_len, y_len, blank_idx=0, batch_offset=None,
                 max_f_len=None, debug_list=None):
        log_probs = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        return transducer_loss(log_probs, label, f_len, y_len, blank_idx)
