"""Fused indexed multiply.

Parity: reference apex/contrib/index_mul_2d (index_mul_2d.py:144 +
csrc/index_mul_2d) — ``out[i] = in1[idx[i]] * in2[i]`` fused
gather-multiply with matching backward. One XLA gather+mul on TPU.
"""



def index_mul_2d(in1, in2, idx1):
    """out[i, :] = in1[idx1[i], :] * in2[i, :]."""
    return in1[idx1] * in2
