"""Fused ResNet bottleneck + spatial-parallel variant.

Parity: reference apex/contrib/bottleneck (bottleneck.py:749 ``Bottleneck``
/ ``SpatialBottleneck`` + csrc/bottleneck.cpp 4,073 LoC cuDNN-frontend
fusions; halo_exchangers.py:180) and apex/contrib/conv_bias_relu.

TPU design: the conv+bias+relu fusion is XLA's bread and butter (one
fused HLO); the spatial-parallel 3x3 conv shards H across the 'spatial'
mesh axis and stitches a 1-row halo per side with
:func:`apex_tpu.contrib.peer_memory.halo_exchange_1d` before a VALID conv.
"""

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.contrib.peer_memory import halo_exchange_1d


def conv_bias_relu(x, kernel, bias=None, stride=1, padding="SAME",
                   relu=True):
    """Fused Conv+Bias[+ReLU] (parity: apex/contrib/conv_bias_relu)."""
    import jax

    y = jax.lax.conv_general_dilated(
        x, kernel, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if bias is not None:
        y = y + bias
    if relu:
        y = jnp.maximum(y, 0.0)
    return y


def conv_bias_mask_relu(x, kernel, bias, mask, stride=1):
    """Parity: ConvBiasMaskReLU (reference conv_bias_relu.py)."""
    y = conv_bias_relu(x, kernel, bias, stride, relu=False)
    return jnp.maximum(y * mask, 0.0)


class Bottleneck(nn.Module):
    """Standard ResNet bottleneck with fused epilogues
    (reference bottleneck.py Bottleneck: 1x1 -> 3x3 -> 1x1 + residual)."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    dtype: Any = jnp.bfloat16
    use_cudnn: bool = True  # accepted for parity; XLA always fuses

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype, param_dtype=jnp.float32, name=name)
        residual = x
        y = nn.Conv(self.bottleneck_channels, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.relu(norm("bn1")(y))
        y = nn.Conv(self.bottleneck_channels, (3, 3),
                    strides=(self.stride, self.stride), use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = nn.relu(norm("bn2")(y))
        y = nn.Conv(self.out_channels, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv3")(y)
        y = norm("bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.out_channels, (1, 1),
                               strides=(self.stride, self.stride),
                               use_bias=False, dtype=self.dtype,
                               name="conv_proj")(x)
            residual = norm("bn_proj")(residual)
        return nn.relu(y + residual)


class SpatialBottleneck(nn.Module):
    """Bottleneck whose 3x3 conv runs on an H-sharded input with halo
    exchange (reference SpatialBottleneck + halo_exchangers.py).

    Must run inside shard_map with ``spatial_axis`` bound; the input is the
    local H shard [N, H/world, W, C].
    """

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    spatial_axis: str = "spatial"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        assert self.stride == 1, "spatial-parallel stride-1 blocks only"
        norm = lambda name: nn.BatchNorm(  # noqa: E731
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.dtype, param_dtype=jnp.float32, name=name,
            axis_name=self.spatial_axis)
        residual = x
        y = nn.Conv(self.bottleneck_channels, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.relu(norm("bn1")(y))
        # 3x3 with halo: fetch one row from each neighbor, then VALID conv
        # over H (padding stays SAME over W).
        top, bottom = halo_exchange_1d(y, 1, self.spatial_axis, dim=1)
        y_h = jnp.concatenate([top, y, bottom], axis=1)
        import jax

        kernel = self.param("conv2_kernel", nn.initializers.lecun_normal(),
                            (3, 3, self.bottleneck_channels,
                             self.bottleneck_channels), jnp.float32)
        y = jax.lax.conv_general_dilated(
            y_h.astype(self.dtype), kernel.astype(self.dtype),
            window_strides=(1, 1), padding=[(0, 0), (1, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = nn.relu(norm("bn2")(y))
        y = nn.Conv(self.out_channels, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv3")(y)
        y = norm("bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.out_channels, (1, 1), use_bias=False,
                               dtype=self.dtype, name="conv_proj")(x)
            residual = norm("bn_proj")(residual)
        return nn.relu(y + residual)
