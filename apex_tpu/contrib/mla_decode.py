"""Pallas streaming kernel for MLA latent-cache decode (TPU).

The absorbed-projection decode (models/mla.py MLAAttention._decode_tail)
scores each new query against the per-token LATENT rows c_t =
[normed kv latent | rotated shared k_pe] — every head contracts the SAME
cache row, and the value path reuses the first ``lat`` columns of that
row (ctx_lat = sum_t p_t * c_t[:lat]). That makes the per-step prefix
attention exactly a multi-query flash attention whose K *and* V are
views of one buffer:

    scores[n, t] = (q_full[n] . c_t) * scale,   q_full = [q_lat | q_pe]
    ctx_lat[n]   = softmax_t(scores) @ c[:, :lat]

(the nope and rope score terms of the einsum path are one concatenated
contraction — same arithmetic, one pass). The XLA einsum formulation
materializes [b, n, 1, T] fp32 scores in HBM and reads the cache twice
(scores + combine); this kernel streams the cache through VMEM in
``block_t`` tiles ONCE with an online softmax, fp32 accumulators, and
skips tiles beyond the live prefix via scalar-prefetched length (the
clamped index map repeats the last contributing tile, so Mosaic never
fetches dead cache rows).

Reference analog: apex/contrib/fmha exists purely to make attention
fast (fmha_api.cpp:363); this is the same move for the MLA decode hot
loop. Off TPU the public entry falls back to the einsum formulation
(also the parity oracle for the kernel tests).
"""

import functools

import jax
import jax.numpy as jnp

from apex_tpu.contrib._pallas_gate import PallasGate, choose_block

NEG_INF = -1e30
DEFAULT_BLOCK_T = 512

_GATE = PallasGate("APEX_TPU_MLA_FLASH")


def force_interpret(on: bool):
    """Run the kernel in interpreter mode regardless of backend (tests:
    exercises the real kernel dataflow on the CPU mesh)."""
    _GATE.force_interpret(on)


def mla_decode_reference(q_full, cache, length, lat, scale):
    """Einsum formulation (the oracle): q_full [b, n, L], cache
    [T, b, L], length [] int32 -> ctx_lat [b, n, lat] fp32."""
    scores = jnp.einsum("bnl,tbl->bnt", q_full.astype(jnp.float32),
                        cache.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    t = jnp.arange(cache.shape[0])[None, None, :]
    scores = jnp.where(t >= length, NEG_INF, scores)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bnt,tbl->bnl", probs,
                      cache[..., :lat].astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def _decode_kernel(len_ref, q_ref, c_ref, o_ref, acc_ref, m_ref, l_ref, *,
                   scale, lat, block_t, num_t):
    """One (batch, cache-tile) grid cell: all heads at once (they share
    the tile), online softmax across the streamed tile axis."""
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = len_ref[0]

    @pl.when(j * block_t < length)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale      # [n, L]
        c = c_ref[:, 0, :].astype(jnp.float32)        # [block_t, L]
        s = jnp.dot(q, c.T, preferred_element_type=jnp.float32)
        t_ids = j * block_t + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(t_ids >= length, NEG_INF, s)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1)[:, None])
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=-1)[:, None]
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, c[:, :lat], preferred_element_type=jnp.float32)

    @pl.when(j == num_t - 1)
    def _finish():
        o_ref[0] = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)


def _decode_pallas(q_full, cache, length, lat, scale, block_t):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, n, L = q_full.shape
    T = cache.shape[0]
    num_t = T // block_t
    kernel = functools.partial(_decode_kernel, scale=scale, lat=lat,
                               block_t=block_t, num_t=num_t)

    def cache_index(bi, j, len_ref):
        # clamp to the last live tile: a repeated block index skips the
        # DMA, so dead prefix tiles are never fetched
        last = jnp.maximum(len_ref[0] - 1, 0) // block_t
        return (jnp.minimum(j, last), bi, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, num_t),
        in_specs=[
            pl.BlockSpec((1, n, L), lambda bi, j, len_ref: (bi, 0, 0)),
            pl.BlockSpec((block_t, 1, L), cache_index),
        ],
        out_specs=pl.BlockSpec((1, n, lat),
                               lambda bi, j, len_ref: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n, lat), jnp.float32),  # acc
            pltpu.VMEM((n, 1), jnp.float32),    # running max
            pltpu.VMEM((n, 1), jnp.float32),    # running sum
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n, lat), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_GATE.interpret,
    )(jnp.asarray(length, jnp.int32).reshape(1), q_full, cache)


def use_flash(cache_len: int, block_t: int = DEFAULT_BLOCK_T) -> bool:
    """True when the kernel would actually run (TPU/interpret AND the
    block ladder finds a tile dividing the cache). Callers gate on this
    so the non-kernel path is their own production einsum formulation,
    not this module's fp32 reference fallback."""
    return _GATE.enabled() and choose_block(cache_len, block_t) is not None


def mla_flash_decode(q_full, cache, length, lat, scale,
                     block_t=DEFAULT_BLOCK_T):
    """Streaming latent-cache decode attention for one step.

    q_full: [b, n, lat + rope] absorbed queries ([q_lat | q_pe]).
    cache:  [T, b, lat + rope] latent rows (models/mla.py layout).
    length: [] int32 — live prefix length INCLUDING the current token.
    Returns ctx_lat [b, n, lat] fp32 (caller expands through W_v).

    Falls back to the einsum oracle off-TPU or when no block divides the
    cache length (``use_flash`` tells a caller which way it will go).
    """
    T = cache.shape[0]
    if not use_flash(T, block_t):
        return mla_decode_reference(q_full, cache, length, lat, scale)
    return _decode_pallas(q_full, cache, length, lat, scale,
                          choose_block(T, block_t))
