"""Top-k expert routing with static capacity (GShard / Switch Transformer).

The routing decision is materialized as dense one-hot dispatch/combine
tensors so the whole layer is static-shaped einsums — the TPU-idiomatic
formulation (no gather/scatter, everything lands on the MXU and fuses).

``compute_routing`` is the functional core; ``TopKRouter`` wraps it as a
flax module owning the (dense, replicated) gate projection.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass
class RoutingResult:
    """Static-shaped routing tensors for T tokens, E experts, capacity C."""

    dispatch_mask: jnp.ndarray    # [T, E, C] {0,1} — token t fills slot (e, c)
    combine_weights: jnp.ndarray  # [T, E, C] fp32 — gate weight per filled slot
    aux_loss: jnp.ndarray         # scalar load-balancing loss (Switch eq. 4-6)
    z_loss: jnp.ndarray           # scalar router z-loss (ST-MoE eq. 5)
    probs: jnp.ndarray            # [T, E] softmax router probabilities
    dropped_fraction: jnp.ndarray = None  # scalar: routed slots lost to capacity


def expert_capacity(num_tokens: int, num_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Static per-expert slot count; always a multiple of 8 for TPU lane
    layout, capped near num_tokens (ADVICE r2: tiny configs otherwise get
    more slots per expert than there are tokens, pure padding waste; the
    cap itself rounds up to 8 so the lane invariant survives)."""
    raw = max(1, int(num_tokens * top_k * capacity_factor / num_experts))
    rounded = -(-raw // 8) * 8
    cap = -(-max(1, num_tokens) // 8) * 8
    return min(rounded, cap)


def _router_losses(logits, probs, expert_fractions):
    """Shared Switch aux loss + ST-MoE z-loss. ``expert_fractions`` [E]
    is the PRE-DROP fraction of routed assignments per expert — both the
    dense and sorted formulations must feed the same quantity, or the
    dispatch-mode parity contract (test_moe_dispatch.py) breaks."""
    E = logits.shape[-1]
    aux_loss = E * jnp.sum(expert_fractions * probs.mean(axis=0))
    z = jax.scipy.special.logsumexp(logits, axis=-1)
    return aux_loss, jnp.mean(z * z)


def compute_routing(logits, top_k: int, capacity: int,
                    normalize_topk: bool = True) -> RoutingResult:
    """Route tokens from fp32 router ``logits`` [T, E].

    Position-in-expert is a cumsum over the token dim (arrival order, the
    GShard discipline); tokens beyond ``capacity`` are dropped — their
    combine weights are zero, so they ride the residual connection.
    """
    logits = logits.astype(jnp.float32)
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    # Iterative top-k: mask out prior choices and re-argmax.
    choice_masks = []
    remaining = probs
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        choice_masks.append(onehot)
        remaining = remaining * (1.0 - onehot)

    gates = [jnp.sum(probs * m, axis=-1) for m in choice_masks]  # k x [T]
    if normalize_topk and top_k > 1:
        denom = sum(gates)
        gates = [g / jnp.maximum(denom, 1e-9) for g in gates]

    # Slot assignment: earlier choices claim slots before later ones.
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    expert_fill = jnp.zeros((1, E), jnp.float32)
    for onehot, gate in zip(choice_masks, gates):
        pos = jnp.cumsum(onehot, axis=0) - 1.0 + expert_fill  # [T, E]
        expert_fill = expert_fill + jnp.sum(onehot, axis=0, keepdims=True)
        keep = onehot * (pos < capacity)
        slot = jax.nn.one_hot(jnp.sum(pos * onehot, axis=-1).astype(jnp.int32),
                              capacity, dtype=jnp.float32)  # [T, C]
        dispatch = dispatch + keep[:, :, None] * slot[:, None, :]
        combine = combine + (keep * gate[:, None])[:, :, None] * slot[:, None, :]

    # Load-balancing aux loss: E * sum_e f_e * P_e with f_e the fraction of
    # routed (pre-drop) assignments and P_e the mean router probability.
    f = sum(choice_masks).sum(axis=0) / (top_k * T)  # [E]
    aux_loss, z_loss = _router_losses(logits, probs, f)
    dropped = 1.0 - jnp.sum(dispatch) / (top_k * T)
    return RoutingResult(dispatch, combine, aux_loss, z_loss, probs,
                         lax.stop_gradient(dropped))


@dataclasses.dataclass
class SortedRouting:
    """Sorted token->expert assignments for T tokens, E experts, k choices.

    N = k*T assignment rows, ordered by expert id (stable within an
    expert: choice rank major, then token order — exactly the slot-fill
    order of ``compute_routing``'s cumsum, so capacity drops are
    bit-identical between the dense and sorted formulations). This is
    the O(T log T + T E) routing representation: no [T, E, C] one-hot
    tensors anywhere, so dispatch/combine cost scales linearly in T
    instead of quadratically (the dropless C ~ T regime that serves
    converted Mixtral/DeepSeek checkpoints at real sequence lengths).
    """

    token_idx: jnp.ndarray   # [N] int32 — source token of assignment i
    expert_idx: jnp.ndarray  # [N] int32 — expert of assignment i (ascending)
    gate: jnp.ndarray        # [N] fp32 — combine weight (0 for dropped rows)
    counts: jnp.ndarray      # [E] int32 — pre-drop assignments per expert
    slot: jnp.ndarray        # [N] int32 in [0, E*C]; E*C = dropped sentinel
                             # (None when capacity is None: dropless)
    aux_loss: jnp.ndarray    # scalar load-balancing loss (same formula as
                             # compute_routing — counts are pre-drop)
    z_loss: jnp.ndarray      # scalar router z-loss
    probs: jnp.ndarray       # [T, E] softmax router probabilities
    dropped_fraction: jnp.ndarray = None


def compute_routing_sorted(logits, top_k: int, capacity: Optional[int],
                           normalize_topk: bool = True) -> SortedRouting:
    """Sort-based routing from fp32 ``logits`` [T, E].

    ``capacity=None`` is truly dropless (every assignment kept, no slot
    layout — feed ``ExpertMLP`` via ragged grouping). With a capacity,
    assignments beyond C per expert get zero gate and the E*C slot
    sentinel; the kept set matches ``compute_routing`` exactly because
    the pre-sort order (choice rank major, token minor) reproduces its
    "earlier choices claim slots first" cumsum discipline.
    """
    logits = logits.astype(jnp.float32)
    T, E = logits.shape
    N = top_k * T
    probs = jax.nn.softmax(logits, axis=-1)

    # lax.top_k returns descending values, ties broken toward the lower
    # index — the same choice sequence as compute_routing's iterative
    # argmax-and-mask.
    topv, topi = lax.top_k(probs, top_k)  # [T, k], [T, k]
    gates = topv
    if normalize_topk and top_k > 1:
        gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)

    # Choice-rank-major flatten, then a stable sort by expert: within an
    # expert, rows appear in (rank, token) order — compute_routing's fill
    # order — so "first C rows win" is the identical drop rule.
    flat_e = topi.T.reshape(N)
    flat_t = jnp.tile(jnp.arange(T, dtype=jnp.int32), top_k)
    flat_g = gates.T.reshape(N)
    order = jnp.argsort(flat_e, stable=True)
    expert_sorted = flat_e[order].astype(jnp.int32)
    token_sorted = flat_t[order]
    gate_sorted = flat_g[order]

    counts = jnp.zeros((E,), jnp.int32).at[flat_e].add(1)  # pre-drop
    group_start = jnp.cumsum(counts) - counts  # exclusive prefix
    pos_in_expert = jnp.arange(N, dtype=jnp.int32) - group_start[expert_sorted]

    if capacity is None:
        slot = None
        dropped = jnp.zeros((), jnp.float32)
    else:
        kept = pos_in_expert < capacity
        slot = jnp.where(kept, expert_sorted * capacity + pos_in_expert,
                         E * capacity).astype(jnp.int32)
        gate_sorted = jnp.where(kept, gate_sorted, 0.0)
        dropped = 1.0 - jnp.sum(kept) / N

    f = counts.astype(jnp.float32) / N  # pre-drop fraction, as compute_routing
    aux_loss, z_loss = _router_losses(logits, probs, f)
    return SortedRouting(token_sorted, expert_sorted, gate_sorted, counts,
                         slot, aux_loss, z_loss, probs,
                         lax.stop_gradient(dropped))


def compute_expert_choice_routing(logits, capacity: int) -> RoutingResult:
    """Expert-choice routing (Zhou et al. 2022, arXiv 2202.09368): each
    expert picks its top-``capacity`` tokens by router probability.

    Perfectly load-balanced by construction (every expert fills exactly C
    slots), so the Switch aux loss degenerates — it is returned as 0. A
    token may be chosen by several experts (contributions sum) or by none
    (rides the residual; tracked in ``dropped_fraction``). TPU-friendly:
    one ``lax.top_k`` over tokens per expert plus the same one-hot
    dispatch/combine einsums as top-k routing.
    """
    logits = logits.astype(jnp.float32)
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    # per expert: weights + token indices of its top-C tokens
    gates, idx = lax.top_k(probs.T, min(capacity, T))  # [E, C], [E, C]
    dispatch = jax.nn.one_hot(idx, T, dtype=jnp.float32)  # [E, C, T]
    dispatch = dispatch.transpose(2, 0, 1)                # [T, E, C]
    combine = dispatch * gates[None, :, :]
    picked = jnp.clip(jnp.sum(dispatch, axis=(1, 2)), 0.0, 1.0)  # [T]
    dropped = 1.0 - jnp.mean(picked)
    z = jax.scipy.special.logsumexp(logits, axis=-1)
    z_loss = jnp.mean(z * z)
    return RoutingResult(dispatch, combine, jnp.zeros((), jnp.float32),
                         z_loss, probs, lax.stop_gradient(dropped))


def _tp_uniform_key(key):
    """Broadcast tp-rank-0's rng key across the tp axis (no-op outside
    shard_map / when tp is unbound)."""
    from jax import lax

    from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS

    try:
        rank = lax.axis_index(TENSOR_PARALLEL_AXIS)
    except Exception:
        return key
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        data = jax.random.key_data(key)
        data = lax.psum(jnp.where(rank == 0, data, jnp.zeros_like(data)),
                        TENSOR_PARALLEL_AXIS)
        return jax.random.wrap_key_data(data)
    return lax.psum(jnp.where(rank == 0, key, jnp.zeros_like(key)),
                    TENSOR_PARALLEL_AXIS)


class TopKRouter(nn.Module):
    """Learned gate: fp32 projection to expert logits + optional jitter.

    ``router_type`` selects the assignment rule: "top_k" (tokens choose
    experts — GShard/Switch) or "expert_choice" (experts choose tokens —
    balanced by construction, no aux loss). The gate weight is a dense
    (replicated) param — with expert parallelism its grads must sync over
    the full dp x ep replica set like any other dense param.
    """

    num_experts: int
    top_k: int = 1
    capacity_factor: float = 1.25
    jitter_eps: float = 0.0
    normalize_topk: bool = True
    router_type: str = "top_k"
    params_dtype: Any = jnp.float32
    capacity: Optional[int] = None  # override for tests
    # "dense" -> RoutingResult ([T,E,C] one-hots for the einsum path);
    # "sorted" -> SortedRouting with capacity slots (scatter dispatch);
    # "sorted_dropless" -> SortedRouting, capacity=None (ragged dispatch).
    routing_format: str = "dense"

    @nn.compact
    def __call__(self, tokens) -> RoutingResult:
        """tokens: [T, h] -> RoutingResult with C from ``expert_capacity``.

        Jitter activates when ``jitter_eps > 0`` AND the caller supplies a
        'jitter' rng stream (``apply(..., rngs={"jitter": key})``) — eval
        runs without the stream are deterministic by construction.
        """
        T = tokens.shape[0]
        gate = self.param("gate_weight", nn.initializers.lecun_normal(),
                          (tokens.shape[-1], self.num_experts),
                          self.params_dtype)
        x = tokens.astype(jnp.float32)
        if self.jitter_eps > 0.0 and self.has_rng("jitter"):
            # Routing must agree across tp ranks (the ExpertMLP copy/reduce
            # pairing assumes identical dispatch per rank), so the jitter
            # key is forced tp-uniform even if the caller folded the tp
            # rank into it (the dropout-key discipline would).
            key = _tp_uniform_key(self.make_rng("jitter"))
            x = x * jax.random.uniform(
                key, x.shape, jnp.float32,
                1.0 - self.jitter_eps, 1.0 + self.jitter_eps)
        logits = x @ gate.astype(jnp.float32)
        cap = self.capacity if self.capacity is not None else expert_capacity(
            T, self.num_experts, self.top_k, self.capacity_factor)
        if self.router_type == "expert_choice":
            return compute_expert_choice_routing(logits, cap)
        if self.router_type != "top_k":
            raise ValueError(f"unknown router_type {self.router_type!r}; "
                             "expected 'top_k' or 'expert_choice'")
        if self.routing_format == "sorted":
            return compute_routing_sorted(logits, self.top_k, cap,
                                          self.normalize_topk)
        if self.routing_format == "sorted_dropless":
            return compute_routing_sorted(logits, self.top_k, None,
                                          self.normalize_topk)
        if self.routing_format != "dense":
            raise ValueError(
                f"unknown routing_format {self.routing_format!r}")
        return compute_routing(logits, self.top_k, cap, self.normalize_topk)
