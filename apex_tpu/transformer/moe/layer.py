"""Expert-parallel Switch/GShard MLP layer.

Dataflow per device (T local tokens, E global experts, C slots/expert,
ep-way expert parallelism, tp-way tensor parallelism inside each expert):

    [s, b, h] -> [T, h] -> router -> dispatch [T, E, C]
    einsum dispatch: [E, C, h]
    all_to_all over 'ep': [E/ep, ep*C, h]     (experts gain all ranks' slots)
    grouped FFN (einsum over leading E/ep dim; ffn dim sharded over 'tp')
    all_to_all back: [E, C, h]
    einsum combine: [T, h] -> [s, b, h]

Everything is static-shaped; dropped tokens get zero combine weight and
ride the residual. Expert weights are per-(ep, tp)-rank shards initialized
from rank-folded keys (the partitioned-init discipline of
tensor_parallel/layers.py); dense params (router gate) replicate over ep
and must be grad-synced over the full dp x ep set — see
``parallel_state.get_data_parallel_axes`` and ``is_expert_param``.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.moe.router import TopKRouter, expert_capacity
from apex_tpu.transformer.parallel_state import (
    EXPERT_PARALLEL_AXIS,
    TENSOR_PARALLEL_AXIS,
    get_expert_model_parallel_world_size,
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_sequence_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide


def moe_loss_from_variables(variables, aux_loss_coeff: float = 1e-2,
                            z_loss_coeff: float = 0.0):
    """Total auxiliary MoE loss from the 'moe_losses' collection returned
    by ``model.apply(..., mutable=["moe_losses"])``. Accepts either the
    full mutated-variables dict or the collection itself."""
    import flax

    losses = variables.get("moe_losses", variables)
    aux = jnp.zeros((), jnp.float32)
    z = jnp.zeros((), jnp.float32)
    for path, val in flax.traverse_util.flatten_dict(dict(losses)).items():
        total = sum(val) if isinstance(val, (tuple, list)) else val
        total = jnp.sum(total)  # scan-stacked layers sow [L]-shaped entries
        if path[-1] == "aux_loss":
            aux = aux + total
        elif path[-1] == "z_loss":
            z = z + total
    return aux_loss_coeff * aux + z_loss_coeff * z


_WARNED_DROPPED_LOSSES = False


def _warn_dropped_losses_once():
    global _WARNED_DROPPED_LOSSES
    if _WARNED_DROPPED_LOSSES:
        return
    _WARNED_DROPPED_LOSSES = True
    import warnings

    warnings.warn(
        "SwitchMLP router aux/z losses were discarded: apply the model "
        "with mutable=['moe_losses'] and add moe_loss_from_variables(...) "
        "to the training loss (for inference/eval, construct with "
        "warn_on_dropped_losses=False).", stacklevel=3)


def is_expert_param(path: str) -> bool:
    """Param-path predicate: expert shards (different on every ep/tp rank)
    vs dense params. Grad-sync rule: expert params average over 'dp' only;
    dense params over ``get_data_parallel_axes()`` (dp and ep). Matches the
    whole 'experts' path segment (a user module merely *containing* the
    substring, e.g. 'experts_gate', holds dense params)."""
    return "experts" in path.split("/")


def _expert_rank_key(key):
    """Fold ep and tp ranks into an init key so every expert shard draws
    distinct weights (partitioned-init parity, tensor_parallel/layers.py:76)."""
    for axis in (EXPERT_PARALLEL_AXIS, TENSOR_PARALLEL_AXIS):
        try:
            rank = lax.axis_index(axis)
        except Exception:
            rank = 0
        key = jax.random.fold_in(key, rank)
    return key


class ExpertMLP(nn.Module):
    """Grouped FFN over experts: h -> ffn/tp -> h per expert, activation
    in fp32, tp-reduced output. Two input layouts, identical params:

    - slotted [E_local, S, h] (default): per-expert einsum over the
      leading dim — the all_to_all-compatible layout.
    - ragged [N, h] with ``group_sizes`` [E_local] (rows grouped by
      expert, consecutively): ``lax.ragged_dot`` grouped matmul — zero
      capacity padding, the dropless serving layout. XLA lowers this to
      the TPU grouped-matmul kernel (the MegaBlocks dMoE idea without
      hand-written block-sparsity: the "blocks" are the ragged groups).

    ``activation="swiglu"`` makes w1 a fused per-rank [gate | up]
    projection (2 * ffn/tp local columns, bias-free — the Llama/Mixtral
    expert shape); "gelu" is the Switch-Transformer shape with biases
    (ragged layout gathers per-row biases via ``expert_idx``).
    """

    hidden_size: int
    ffn_hidden_size: int
    num_local_experts: int
    activation: str = "gelu"
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, group_sizes=None, expert_idx=None):
        tp = get_tensor_model_parallel_world_size()
        ffn_local = divide(self.ffn_hidden_size, tp)
        init = nn.initializers.lecun_normal(batch_axis=(0,))
        swiglu = self.activation == "swiglu"
        if not swiglu and self.activation != "gelu":
            raise ValueError(f"unknown activation {self.activation!r}")
        ragged = group_sizes is not None
        if not swiglu and ragged and expert_idx is None:
            raise ValueError("ragged gelu experts need expert_idx for "
                             "per-row bias gathers")

        def shard_init(key, shape, dtype):
            return init(_expert_rank_key(key), shape, dtype)

        w1 = self.param("w1", shard_init,
                        (self.num_local_experts, self.hidden_size,
                         ffn_local * (2 if swiglu else 1)),
                        self.params_dtype)
        w2 = self.param("w2", shard_init,
                        (self.num_local_experts, ffn_local, self.hidden_size),
                        self.params_dtype)
        if not swiglu:
            b1 = self.param("b1", nn.initializers.zeros,
                            (self.num_local_experts, ffn_local),
                            self.params_dtype)
            b2 = self.param("b2", nn.initializers.zeros,
                            (self.num_local_experts, self.hidden_size),
                            self.params_dtype)

        # Column-parallel in, row-parallel out (identity/psum vjp pairing).
        x = copy_to_tensor_model_parallel_region(x)
        x = x.astype(self.compute_dtype)
        if ragged:
            h1 = lax.ragged_dot(x, w1.astype(self.compute_dtype),
                                group_sizes,
                                preferred_element_type=jnp.float32)
        else:
            h1 = jnp.einsum("ech,ehf->ecf", x, w1.astype(self.compute_dtype),
                            preferred_element_type=jnp.float32)
        if swiglu:
            gate, up = jnp.split(h1, 2, axis=-1)
            a = (jax.nn.silu(gate) * up).astype(self.compute_dtype)
        else:
            bias1 = (b1[expert_idx] if ragged else b1[:, None, :])
            h1 = h1 + bias1.astype(jnp.float32)
            a = jax.nn.gelu(h1).astype(self.compute_dtype)
        if ragged:
            y = lax.ragged_dot(a, w2.astype(self.compute_dtype),
                               group_sizes,
                               preferred_element_type=jnp.float32)
        else:
            y = jnp.einsum("ecf,efh->ech", a, w2.astype(self.compute_dtype),
                           preferred_element_type=jnp.float32)
        y = reduce_from_tensor_model_parallel_region(y)
        if swiglu:
            return y
        bias2 = (b2[expert_idx] if ragged else b2[:, None, :])
        return y + bias2.astype(jnp.float32)


class SharedExpertMoE(nn.Module):
    """Routed SwitchMLP plus an always-on shared expert (the Qwen2-MoE
    block shape): out = routed(x) + sigmoid(gate(x)) * shared(x), the
    scalar sigmoid gate optional. The shared expert is a dense SwiGLU
    MLP (column-parallel fused [gate | up], row-parallel down) of its
    own width — distinct from DeepSeek's ungated shared expert, which
    lives in models/mla.py. Aux losses sow through the nested SwitchMLP
    as usual."""

    hidden_size: int
    ffn_hidden_size: int            # routed expert width
    shared_expert_size: int         # shared expert width
    num_experts: int
    top_k: int = 1
    capacity_factor: float = 1.25
    jitter_eps: float = 0.0
    normalize_topk: bool = True
    dispatch_mode: str = "auto"
    # the block shape is tied to top-k routing over SwiGLU experts; other
    # router/activation combinations raise rather than silently ignore
    # the request (a config-driven caller would otherwise train a
    # different model than it asked for)
    router_type: str = "top_k"
    activation: str = "swiglu"
    shared_expert_gated: bool = True
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    sequence_parallel_enabled: bool = False
    warn_on_dropped_losses: bool = True

    @nn.compact
    def __call__(self, hidden_states):
        from apex_tpu.transformer.tensor_parallel.layers import (
            ColumnParallelLinear,
            RowParallelLinear,
        )

        if self.router_type != "top_k":
            raise ValueError(
                f"SharedExpertMoE supports top_k routing only, got "
                f"{self.router_type!r}")
        if self.activation != "swiglu":
            raise ValueError(
                f"SharedExpertMoE experts are SwiGLU (the Qwen2-MoE "
                f"shape), got activation {self.activation!r}")
        routed = SwitchMLP(
            hidden_size=self.hidden_size,
            ffn_hidden_size=self.ffn_hidden_size,
            num_experts=self.num_experts, top_k=self.top_k,
            capacity_factor=self.capacity_factor,
            jitter_eps=self.jitter_eps,
            normalize_topk=self.normalize_topk,
            dispatch_mode=self.dispatch_mode, activation="swiglu",
            params_dtype=self.params_dtype,
            compute_dtype=self.compute_dtype,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            warn_on_dropped_losses=self.warn_on_dropped_losses,
            name="routed")(hidden_states)

        x = hidden_states.astype(self.compute_dtype)
        gate_up = ColumnParallelLinear(
            input_size=self.hidden_size,
            output_size=2 * self.shared_expert_size,
            gather_output=False, bias=False,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            params_dtype=self.params_dtype, name="shared_gate_up")(x)
        g, up = jnp.split(gate_up.astype(jnp.float32), 2, axis=-1)
        h = (jax.nn.silu(g) * up).astype(self.compute_dtype)
        shared = RowParallelLinear(
            input_size=self.shared_expert_size,
            output_size=self.hidden_size, input_is_parallel=True,
            bias=False,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            params_dtype=self.params_dtype, name="shared_down")(h)
        if self.shared_expert_gated:
            gate_w = self.param("shared_expert_gate",
                                nn.initializers.zeros,
                                (self.hidden_size, 1), self.params_dtype)
            scale = jax.nn.sigmoid(
                (x.astype(jnp.float32) @ gate_w.astype(jnp.float32)))
            shared = shared * scale.astype(shared.dtype)
        return routed + shared.astype(routed.dtype)


class SwitchMLP(nn.Module):
    """Drop-in MoE replacement for ParallelMLP (Megatron names this
    SwitchMLP). Sows 'aux_loss'/'z_loss' into the 'moe_losses' collection;
    apply with ``mutable=["moe_losses"]`` to collect them.

    ``dispatch_mode`` picks the dispatch/combine algorithm:

    - "einsum": dense [T, E, C] one-hot einsums. O(T*E*C) — quadratic in
      T once C ~ T (the dropless capacity serving converted checkpoints
      uses). Kept as the reference formulation and ep-compatible.
    - "scatter": sort assignments by expert, invert the slot map with an
      int scatter, dispatch/combine as gathers + one scatter-add.
      O(T log T + T*E) routing + O(T*h) data movement; same [E, C, h]
      slot layout, so expert parallelism (all_to_all) and capacity-drop
      semantics are unchanged — drop decisions are bit-identical to
      "einsum" (see compute_routing_sorted).
    - "ragged": no capacity slots at all — tokens sorted by expert feed
      ``lax.ragged_dot`` grouped matmuls ([k*T, h] rows, zero padding).
      Truly dropless and the fastest serving path; ep must be 1 (the
      all_to_all needs static per-rank splits).
    - "auto" (default): "scatter" when ep > 1 or when the capacity can
      actually drop tokens (capacity < T — preserving drop semantics),
      else "ragged". expert_choice routing always uses its dense path
      (C is small by design there).
    """

    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    top_k: int = 1
    capacity_factor: float = 1.25
    jitter_eps: float = 0.0
    router_type: str = "top_k"  # or "expert_choice" (balanced, no aux)
    # renormalize the selected top-k gates to sum to 1 (Mixtral); False
    # keeps raw softmax mass (DeepSeek greedy gate, norm_topk_prob=False)
    normalize_topk: bool = True
    activation: str = "gelu"  # or "swiglu" (Llama/Mixtral-style experts)
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    sequence_parallel_enabled: bool = False
    dispatch_mode: str = "auto"  # auto | einsum | scatter | ragged
    # Warn (once per process) when aux losses are silently dropped because
    # the caller didn't pass mutable=["moe_losses"]; set False for
    # inference/eval modules where dropping them is intended.
    warn_on_dropped_losses: bool = True

    def _resolve_dispatch(self, ep: int, capacity: int, num_tokens: int):
        mode = self.dispatch_mode
        if mode not in ("auto", "einsum", "scatter", "ragged"):
            raise ValueError(f"unknown dispatch_mode {mode!r}")
        if self.router_type != "top_k":
            if mode in ("scatter", "ragged"):
                raise ValueError(
                    f"dispatch_mode {mode!r} requires the top_k router; "
                    "expert_choice routing has only its dense path")
            return "einsum"
        if mode == "auto":
            if ep > 1 or capacity < num_tokens:
                return "scatter"
            return "ragged"
        if mode == "ragged" and ep > 1:
            raise ValueError(
                "ragged dispatch has no static per-rank slot layout for "
                "the expert-parallel all_to_all; use 'scatter' with ep > 1")
        return mode

    @nn.compact
    def __call__(self, hidden_states):
        ep = get_expert_model_parallel_world_size()
        n_local = divide(self.num_experts, ep)

        if self.sequence_parallel_enabled:
            # Full sequence on every tp rank; routing is deterministic so
            # tp ranks agree. The dispatch-path input grad is already
            # tp-psummed by the copy_to region inside ExpertMLP and the
            # router-path grad is tp-replicated, so the gather's backward
            # must be a plain split (tensor_parallel_output_grad=False),
            # and the exit below a plain scatter — a reduce-scatter pair
            # here would double-count by tp.
            hidden_states = gather_from_sequence_parallel_region(
                hidden_states, False)
        orig_shape = hidden_states.shape  # [s, b, h]
        tokens = hidden_states.reshape(-1, orig_shape[-1])

        num_tokens = tokens.shape[0]
        capacity = expert_capacity(num_tokens, self.num_experts, self.top_k,
                                   self.capacity_factor)
        mode = self._resolve_dispatch(ep, capacity, num_tokens)
        routing = TopKRouter(
            num_experts=self.num_experts, top_k=self.top_k,
            capacity_factor=self.capacity_factor, jitter_eps=self.jitter_eps,
            router_type=self.router_type,
            normalize_topk=self.normalize_topk,
            routing_format={"einsum": "dense", "scatter": "sorted",
                            "ragged": "sorted_dropless"}[mode],
            params_dtype=self.params_dtype, name="router")(tokens)
        sown = self.sow("moe_losses", "aux_loss", routing.aux_loss)
        self.sow("moe_losses", "z_loss", routing.z_loss)
        # observability, not a loss: moe_loss_from_variables sums only the
        # *_loss keys; watch this to tune capacity_factor
        self.sow("moe_losses", "dropped_fraction", routing.dropped_fraction)
        if (not sown and not self.is_initializing()
                and self.warn_on_dropped_losses):
            # sow() into a non-mutable collection is a silent no-op; a
            # training step that forgets mutable=["moe_losses"] would run
            # with zero load-balancing pressure and collapse the router.
            _warn_dropped_losses_once()

        experts = ExpertMLP(
            hidden_size=self.hidden_size,
            ffn_hidden_size=self.ffn_hidden_size,
            num_local_experts=n_local, activation=self.activation,
            params_dtype=self.params_dtype,
            compute_dtype=self.compute_dtype, name="experts")
        x = tokens.astype(self.compute_dtype)
        hidden = orig_shape[-1]

        if mode == "ragged":
            # Zero-padding dropless path: gather rows into expert-sorted
            # order (grad = scatter-add, the gather's XLA transpose), run
            # the grouped matmuls, weight by gate, scatter-add back.
            sorted_x = x[routing.token_idx]  # [N, h]
            y = experts(sorted_x, group_sizes=routing.counts,
                        expert_idx=routing.expert_idx)
            contrib = y.astype(jnp.float32) * routing.gate[:, None]
            out = jnp.zeros((num_tokens, hidden), jnp.float32)
            out = out.at[routing.token_idx].add(contrib)
        elif mode == "scatter":
            EC = self.num_experts * capacity
            # Invert slot -> source token with an int scatter (N int32
            # elements, not N*h floats), then dispatch is one gather.
            # Dropped assignments hit the sentinel row EC (discarded);
            # empty slots read the zero row appended at token index T.
            inv = jnp.full((EC + 1,), num_tokens, jnp.int32)
            inv = inv.at[routing.slot].set(routing.token_idx)
            x_pad = jnp.concatenate(
                [x, jnp.zeros((1, hidden), x.dtype)], axis=0)
            expert_in = x_pad[inv[:EC]].reshape(
                self.num_experts, capacity, hidden)
            if ep > 1:
                # [E, C, h] -> [E/ep, ep*C, h] (tiled: see einsum branch).
                expert_in = lax.all_to_all(expert_in, EXPERT_PARALLEL_AXIS,
                                           split_axis=0, concat_axis=1,
                                           tiled=True)
            expert_out = experts(expert_in).astype(self.compute_dtype)
            if ep > 1:
                expert_out = lax.all_to_all(expert_out, EXPERT_PARALLEL_AXIS,
                                            split_axis=1, concat_axis=0,
                                            tiled=True)
            flat = expert_out.reshape(EC, hidden)
            # Dropped rows gather garbage through the clamped index but
            # carry gate 0, so they contribute (and backprop) nothing.
            safe = jnp.minimum(routing.slot, EC - 1)
            contrib = flat[safe].astype(jnp.float32) * routing.gate[:, None]
            out = jnp.zeros((num_tokens, hidden), jnp.float32)
            out = out.at[routing.token_idx].add(contrib)
        else:  # einsum
            # Dispatch: [T, h] x [T, E, C] -> [E, C, h]
            expert_in = jnp.einsum(
                "th,tec->ech", x,
                routing.dispatch_mask.astype(self.compute_dtype))
            if ep > 1:
                # [E, C, h] -> [E/ep, ep*C, h]: local expert shards gain
                # every ep rank's capacity slots (rank r's block at offset
                # r*C). Tiled form: the non-tiled reshape/all_to_all/
                # reshape chain trips a JAX transpose bug when two
                # all_to_alls are chained through reshapes (wrong
                # cotangent shape at lowering).
                expert_in = lax.all_to_all(expert_in, EXPERT_PARALLEL_AXIS,
                                           split_axis=0, concat_axis=1,
                                           tiled=True)
            # compute_dtype over the wire: the return all_to_all otherwise
            # ships fp32 (2x the dispatch path's ICI bytes).
            expert_out = experts(expert_in).astype(self.compute_dtype)
            if ep > 1:
                # [E/ep, ep*C, h] -> [E, C, h]: return each rank's slots.
                expert_out = lax.all_to_all(expert_out, EXPERT_PARALLEL_AXIS,
                                            split_axis=1, concat_axis=0,
                                            tiled=True)
            # Combine: [E, C, h] x [T, E, C] -> [T, h]; bf16 operands on
            # the MXU (gates are probabilities — bf16 rounding is on par
            # with the activations), fp32 accumulation.
            out = jnp.einsum("ech,tec->th", expert_out,
                             routing.combine_weights.astype(
                                 self.compute_dtype),
                             preferred_element_type=jnp.float32)

        out = out.reshape(orig_shape).astype(self.compute_dtype)
        if self.sequence_parallel_enabled:
            out = scatter_to_sequence_parallel_region(out)
        return out
