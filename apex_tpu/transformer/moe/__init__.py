"""Mixture-of-experts with expert parallelism (TPU-native).

No reference equivalent: juncongmoo/apex has no MoE / expert parallelism
(SURVEY.md §2.3 note). This subsystem is a new capability, designed
TPU-first: capacity-based GShard/Switch routing (one-hot einsums or the
O(T log T) sorted formulation), grouped expert FFNs — batched over a
leading expert dim, or ragged via ``lax.ragged_dot`` grouped matmuls
with zero capacity padding (the dropless serving path) — and
expert-parallel dispatch via ``lax.all_to_all`` over the 'ep' mesh axis
(ICI all-to-all), with the expert hidden dim tensor-parallel over 'tp'.
"""

from apex_tpu.transformer.moe.layer import (
    ExpertMLP,
    SharedExpertMoE,
    SwitchMLP,
    is_expert_param,
    moe_loss_from_variables,
)
from apex_tpu.transformer.moe.router import (
    SortedRouting,
    TopKRouter,
    compute_expert_choice_routing,
    compute_routing,
    compute_routing_sorted,
)

__all__ = [
    "ExpertMLP",
    "SharedExpertMoE",
    "SortedRouting",
    "SwitchMLP",
    "TopKRouter",
    "compute_expert_choice_routing",
    "compute_routing",
    "compute_routing_sorted",
    "is_expert_param",
    "moe_loss_from_variables",
]
