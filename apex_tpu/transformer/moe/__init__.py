"""Mixture-of-experts with expert parallelism (TPU-native).

No reference equivalent: juncongmoo/apex has no MoE / expert parallelism
(SURVEY.md §2.3 note). This subsystem is a new capability, designed
TPU-first: capacity-based GShard/Switch routing expressed as one-hot
einsums (static shapes, MXU-friendly), grouped expert FFNs batched over a
leading expert dim, and expert-parallel dispatch via ``lax.all_to_all``
over the 'ep' mesh axis (ICI all-to-all), with the expert hidden dim
tensor-parallel over 'tp'.
"""

from apex_tpu.transformer.moe.layer import (
    ExpertMLP,
    SwitchMLP,
    is_expert_param,
    moe_loss_from_variables,
)
from apex_tpu.transformer.moe.router import (
    TopKRouter,
    compute_expert_choice_routing,
    compute_routing,
)

__all__ = [
    "ExpertMLP",
    "SwitchMLP",
    "TopKRouter",
    "compute_expert_choice_routing",
    "compute_routing",
    "is_expert_param",
    "moe_loss_from_variables",
]
