"""Sequence-parallel-aware LayerNorm wrappers.

Parity: reference apex/transformer/layers/layer_norm.py:33-99 — subclasses
of FusedLayerNorm / FastLayerNorm / MixedFusedLayerNorm that tag their
params with ``sequence_parallel_enabled`` so grad sync knows to allreduce
them across the TP group.

TPU design: under shard_map the LN params of a sequence-parallel region are
*replicated* over tp while activations are seq-sharded; their grads need a
tp psum. The tag is a module attribute; ``allreduce_sequence_parallel_grads``
in pipeline_parallel.utils consumes it.
"""



from apex_tpu import normalization as _norm


class FusedLayerNorm(_norm.FusedLayerNorm):
    """LayerNorm carrying the sequence_parallel_enabled tag
    (reference layer_norm.py:33-64)."""

    sequence_parallel_enabled: bool = False


class FastLayerNorm(FusedLayerNorm):
    """Contrib FastLayerNorm alias (reference layer_norm.py:66-80): same
    Pallas kernel; the CUDA distinction (hidden sizes <= 64k fast path)
    does not exist on TPU."""


class MixedFusedLayerNorm(_norm.MixedFusedLayerNorm):
    """Mixed-dtype LayerNorm with the SP tag (reference layer_norm.py:82-99)."""

    sequence_parallel_enabled: bool = False
