"""Named wall-clock timers with device sync.

Parity: reference apex/transformer/pipeline_parallel/_timers.py:6-83 —
cuda-synchronized named timers with tensorboard write + rank-0 logging.

Re-based as a thin shim over :mod:`apex_tpu.telemetry.trace` spans: each
``_Timer`` drives a device-sync-fenced :class:`telemetry.trace.Span`
(``jax.effects_barrier`` on both edges — the ``torch.cuda.synchronize``
analog), so pipeline timers show up in profiler traces and, when
telemetry is enabled, land in the registry as ``span/timers/<name>``
histograms + JSONL events. The clock is ``time.perf_counter``
(monotonic): ``time.time`` steps under NTP skew and corrupted elapsed
times. The public ``_Timer``/``_Timers`` API is unchanged.
"""

import time

from apex_tpu.telemetry.trace import Span


class _Timer:
    def __init__(self, name):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = time.perf_counter()
        self._span = None

    def start(self):
        assert not self.started_, "timer has already been started"
        self._span = Span(f"timers/{self.name_}", sync=True).start()
        self.start_time = self._span.start_time
        self.started_ = True

    def stop(self):
        assert self.started_, "timer is not started"
        self.elapsed_ += self._span.stop()
        self._span = None
        self.started_ = False

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False
        self._span = None

    def elapsed(self, reset=True):
        started_ = self.started_
        if self.started_:
            self.stop()
        elapsed_ = self.elapsed_
        if reset:
            self.reset()
        if started_:
            self.start()
        return elapsed_


class _Timers:
    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names, writer, iteration, normalizer=1.0, reset=False):
        """Tensorboard-style write (reference _timers.py:57-66)."""
        assert normalizer > 0.0
        for name in names:
            value = self.timers[name].elapsed(reset=reset) / normalizer
            if writer is not None:
                writer.add_scalar(name + "-time", value, iteration)

    def log(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
            string += " | {}: {:.2f}".format(name, elapsed_time)
        print(string, flush=True)
