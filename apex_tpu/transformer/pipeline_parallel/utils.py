"""Pipeline-parallel utilities.

Parity: reference apex/transformer/pipeline_parallel/utils.py (357 LoC):
microbatch slicing, ``listify_model``/``unwrap_model``, params-l2-norm
across model-parallel ranks, ``average_losses_across_data_parallel_group``,
``report_memory``, ``print_rank_0``/``print_rank_last``,
``get_ltor_masks_and_position_ids``, microbatch-calculator globals, timers.
"""


import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.microbatches import (
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.parallel_state import (  # noqa: F401
    # the get_* helpers are re-exported for parity with the reference
    # apex.transformer.pipeline_parallel.utils public surface
    DATA_PARALLEL_AXIS,
    TENSOR_PARALLEL_AXIS,
    get_pipeline_model_parallel_rank,
    get_pipeline_model_parallel_world_size,
    get_tensor_model_parallel_rank,
)
from apex_tpu.transformer.pipeline_parallel._timers import _Timers

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TIMERS = None
_GLOBAL_AUTORESUME = None


def setup_microbatch_calculator(rank, rampup_batch_size, global_batch_size,
                                micro_batch_size, data_parallel_size):
    """Reference pipeline_parallel/utils.py:58-77."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _ensure_var_is_not_initialized(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR, "num microbatches calculator")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def _reconfigure_microbatch_calculator(rank, rampup_batch_size,
                                       global_batch_size, micro_batch_size,
                                       data_parallel_size):
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def _ensure_var_is_initialized(var, name):
    assert var is not None, "{} is not initialized.".format(name)


def _ensure_var_is_not_initialized(var, name):
    assert var is None, "{} is already initialized.".format(name)


def get_micro_batch_size():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.micro_batch_size


def get_num_microbatches():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True):
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples, consistency_check)


def get_timers():
    """Reference pipeline_parallel/utils.py:146-157."""
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _GLOBAL_TIMERS = _Timers()
    return _GLOBAL_TIMERS


def get_autoresume():
    """ADLR autoresume hook (reference utils.py:142-144) — None unless an
    external autoresume module is installed."""
    return _GLOBAL_AUTORESUME


def listify_model(model):
    if isinstance(model, list):
        return model
    return [model]


def unwrap_model(model, module_instances=None):
    """Reference utils.py:185-198; JAX models are pure pytrees/callables,
    wrappers expose ``.module``."""
    return_list = True
    if not isinstance(model, list):
        model = [model]
        return_list = False
    unwrapped = []
    for m in model:
        while hasattr(m, "module"):
            m = m.module
        unwrapped.append(m)
    if not return_list:
        return unwrapped[0]
    return unwrapped


def get_kth_microbatch(batch, k):
    """Slice microbatch k out of a global batch pytree
    (reference utils.py:122-137)."""
    if batch is None:
        return None
    micro = get_micro_batch_size()
    return jax.tree_util.tree_map(
        lambda x: lax.dynamic_slice_in_dim(x, k * micro, micro, axis=0), batch)


def split_into_microbatches(batch, num_microbatches):
    """Reshape a global batch [G, ...] into [M, G/M, ...] for lax.scan-style
    schedules (TPU-native companion to get_kth_microbatch)."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((num_microbatches, x.shape[0] // num_microbatches)
                            + x.shape[1:]), batch)


def average_losses_across_data_parallel_group(losses, axis_name=None):
    """Reference utils.py:242-250. Defaults to the full data-parallel
    replica set — ('dp', 'ep') when expert parallelism is on (losses are
    data-domain, so every replica cell participates)."""
    if axis_name is None:
        from apex_tpu.transformer.parallel_state import get_data_parallel_axes

        # Use only the axes actually bound in this collective context, so
        # a dp-only shard_map still averages over dp when global state has
        # ep on (the except below must not swallow dp-averaging).
        bound = []
        for a in get_data_parallel_axes():
            try:
                lax.axis_size(a)
                bound.append(a)
            except Exception:
                pass
        axis_name = tuple(bound) if bound else DATA_PARALLEL_AXIS
        axis_name = axis_name[0] if len(axis_name) == 1 else axis_name
    averaged = jnp.stack([l.astype(jnp.float32) for l in losses])
    try:
        averaged = lax.pmean(averaged, axis_name)
    except Exception:
        pass
    return averaged


def calc_params_l2_norm(params, tp_duplicate_mask=None,
                        axis_names=(TENSOR_PARALLEL_AXIS,)):
    """Global param l2 norm excluding TP duplicates
    (reference utils.py:213-241).

    ``tp_duplicate_mask``: pytree of bools, True where a param is replicated
    over tp (counted on tp-rank 0 only).
    """
    leaves = jax.tree_util.tree_leaves(params)
    masks = (jax.tree_util.tree_leaves(tp_duplicate_mask)
             if tp_duplicate_mask is not None else [False] * len(leaves))
    try:
        tp_rank = lax.axis_index(TENSOR_PARALLEL_AXIS)
    except Exception:
        tp_rank = 0
    sq = jnp.zeros((), jnp.float32)
    for p, dup in zip(leaves, masks):
        s = jnp.sum(jnp.square(p.astype(jnp.float32)))
        if dup:
            s = jnp.where(tp_rank == 0, s, 0.0)
        sq = sq + s
    for ax in axis_names:
        try:
            sq = lax.psum(sq, ax)
        except Exception:
            pass
    return jnp.sqrt(sq)


def report_memory(name):
    """Device memory report (reference utils.py:253-263; NVML -> jax
    memory_stats)."""
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        string = name + " memory (MB)"
        string += " | allocated: {:.1f}".format(
            stats.get("bytes_in_use", 0) / 1024 / 1024)
        string += " | peak: {:.1f}".format(
            stats.get("peak_bytes_in_use", 0) / 1024 / 1024)
        string += " | limit: {:.1f}".format(
            stats.get("bytes_limit", 0) / 1024 / 1024)
        print(string, flush=True)
    except Exception:
        pass


def print_rank_0(message):
    """Reference utils.py:159-166."""
    if jax.process_index() == 0:
        print(message, flush=True)


def is_last_rank():
    return jax.process_index() == jax.process_count() - 1


def print_rank_last(message):
    if is_last_rank():
        print(message, flush=True)


def param_is_not_shared(attrs) -> bool:
    return not (attrs or {}).get("shared", False)


def get_ltor_masks_and_position_ids(data, eod_token=None,
                                    reset_position_ids=False,
                                    reset_attention_mask=False,
                                    eod_mask_loss=False):
    """Left-to-right masks and position ids (reference utils.py:303-357).

    The per-document reset variants require data-dependent segment ids; on
    TPU these are expressed with segment-id comparisons instead of mask
    mutation loops.
    """
    micro_batch_size, seq_length = data.shape
    att_mask = jnp.tril(jnp.ones((seq_length, seq_length), bool))
    loss_mask = jnp.ones(data.shape, jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)
    position_ids = jnp.broadcast_to(
        jnp.arange(seq_length, dtype=jnp.int32)[None, :], data.shape)
    if reset_position_ids or reset_attention_mask:
        assert eod_token is not None
        # segment id = number of EODs strictly before each position
        eod = (data == eod_token).astype(jnp.int32)
        seg = jnp.cumsum(eod, axis=1) - eod
        if reset_attention_mask:
            same_seg = seg[:, :, None] == seg[:, None, :]
            att_mask = att_mask[None, :, :] & same_seg
            att_mask = att_mask[:, None, :, :]  # [b, 1, s, s]
        else:
            att_mask = jnp.broadcast_to(
                att_mask[None, None, :, :],
                (micro_batch_size, 1, seq_length, seq_length))
        if reset_position_ids:
            seg_start = jnp.concatenate(
                [jnp.zeros((micro_batch_size, 1), jnp.int32),
                 jnp.where(eod[:, :-1] == 1,
                           jnp.arange(1, seq_length)[None, :], 0)], axis=1)
            seg_start = jax.lax.associative_scan(jnp.maximum, seg_start, axis=1)
            position_ids = jnp.arange(seq_length)[None, :] - seg_start
    else:
        att_mask = jnp.broadcast_to(att_mask[None, None, :, :],
                                    (micro_batch_size, 1, seq_length, seq_length))
    # Reference returns attention_mask with True where masked OUT.
    attention_mask = ~att_mask
    return attention_mask, loss_mask, position_ids
