from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    get_forward_backward_func,
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_with_split,
    make_encoder_decoder_step,
    pipeline_schedule_plan,
)
from apex_tpu.transformer.pipeline_parallel import p2p_communication  # noqa: F401
from apex_tpu.transformer.pipeline_parallel import utils  # noqa: F401
