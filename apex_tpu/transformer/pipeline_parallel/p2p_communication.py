"""Pipeline point-to-point communication.

Parity: reference apex/transformer/pipeline_parallel/p2p_communication.py —
``_communicate`` (117-~400) with batched isend/irecv, ``send_forward`` /
``recv_forward`` / ``send_forward_recv_backward`` / ... wrappers, optional
scatter-gather tensor compression over TP chunks, fp32-or-params dtype.

TPU design: stage-to-stage transfer is ``lax.ppermute`` along the 'pp'
mesh axis inside one jitted step — XLA lowers it to an ICI
collective-permute, which is asynchronous and overlapped by the
latency-hiding scheduler (the role of the reference's batch_isend_irecv +
FutureTensor). "Scatter-gather optimization" (chunking over the TP group)
is subsumed by giving the communicated tensor a tp-sharded layout.

All helpers must be called inside ``shard_map`` with the 'pp' axis bound.
By default boundary ranks receive zeros (non-circular permutes), which
schedules mask; ``circular=True`` wraps the ring (rank P-1 -> rank 0 and
back) — the interleaved schedule rides chunk handoffs on the wrap edge.

Payloads may be arbitrary pytrees of arrays (the reference's
encoder-decoder dual-shape p2p — a (encoder, decoder) activation pair per
boundary, get_tensor_shapes at ...without_interleaving.py:29-86 — is a
two-leaf pytree here); each leaf rides its own collective-permute and XLA
schedules them together.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import (
    PIPELINE_PARALLEL_AXIS,
    get_pipeline_model_parallel_world_size,
)


def _perm_fwd(world, circular=False):
    if circular:
        return [(i, (i + 1) % world) for i in range(world)]
    return [(i, i + 1) for i in range(world - 1)]


def _perm_bwd(world, circular=False):
    if circular:
        return [(i, (i - 1) % world) for i in range(world)]
    return [(i + 1, i) for i in range(world - 1)]


def send_forward_recv_forward(output_tensor, axis_name=PIPELINE_PARALLEL_AXIS,
                              world: Optional[int] = None,
                              circular: bool = False):
    """Shift activations one stage forward: rank r's value arrives at r+1;
    rank 0 receives zeros (or rank P-1's value when ``circular``).
    (reference recv_forward + send_forward pair)"""
    world = world or get_pipeline_model_parallel_world_size()
    if world == 1:
        return (output_tensor if circular
                else jax.tree_util.tree_map(jnp.zeros_like, output_tensor))
    perm = _perm_fwd(world, circular)
    return jax.tree_util.tree_map(
        lambda a: lax.ppermute(a, axis_name, perm), output_tensor)


def send_backward_recv_backward(input_tensor_grad,
                                axis_name=PIPELINE_PARALLEL_AXIS,
                                world: Optional[int] = None,
                                circular: bool = False):
    """Shift gradients one stage backward: rank r's value arrives at r-1;
    the last rank receives zeros (or rank 0's value when ``circular``)."""
    world = world or get_pipeline_model_parallel_world_size()
    if world == 1:
        return (input_tensor_grad if circular
                else jax.tree_util.tree_map(jnp.zeros_like, input_tensor_grad))
    perm = _perm_bwd(world, circular)
    return jax.tree_util.tree_map(
        lambda a: lax.ppermute(a, axis_name, perm), input_tensor_grad)


# Aliases matching the reference wrapper names
# (fwd_bwd_pipelining_without_interleaving.py:87-240). Under SPMD every
# rank runs the same ppermute, so send and recv are one op.

def recv_forward(output_tensor, **kw):
    return send_forward_recv_forward(output_tensor, **kw)


def send_forward(output_tensor, **kw):
    return send_forward_recv_forward(output_tensor, **kw)


def recv_backward(input_tensor_grad, **kw):
    return send_backward_recv_backward(input_tensor_grad, **kw)


def send_backward(input_tensor_grad, **kw):
    return send_backward_recv_backward(input_tensor_grad, **kw)


def send_forward_recv_backward(output_tensor, input_tensor_grad, **kw):
    return (send_forward_recv_forward(output_tensor, **kw),
            send_backward_recv_backward(input_tensor_grad, **kw))


def send_backward_recv_forward(input_tensor_grad, output_tensor, **kw):
    return (send_backward_recv_backward(input_tensor_grad, **kw),
            send_forward_recv_forward(output_tensor, **kw))
