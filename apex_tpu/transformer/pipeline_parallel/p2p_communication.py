"""Compat shim: the pipeline p2p helpers moved to
``apex_tpu.parallel.pipeline`` — the ppermute shift helpers and the
reference wrapper aliases are re-exported here unchanged (one
DeprecationWarning per process, shared with the ``schedules`` shim)."""

from apex_tpu.parallel.pipeline import (  # noqa: F401
    PIPELINE_PARALLEL_AXIS,
    _perm_bwd,
    _perm_fwd,
    _warn_moved,
    recv_backward,
    recv_forward,
    send_backward,
    send_backward_recv_backward,
    send_backward_recv_forward,
    send_forward,
    send_forward_recv_backward,
    send_forward_recv_forward,
)

_warn_moved("apex_tpu.transformer.pipeline_parallel.p2p_communication")
