"""Compat shim: the pipeline schedules moved to
``apex_tpu.parallel.pipeline`` (the 3-D mesh subsystem), which hosts
the reference-parity schedule machinery unchanged — this module
re-exports it so the ``apex.transformer.pipeline_parallel.schedules``
API surface keeps resolving here (one DeprecationWarning per process,
shared with the ``p2p_communication`` shim; the ``contrib._pallas_gate``
retirement pattern)."""

from apex_tpu.parallel.pipeline import (  # noqa: F401
    PIPELINE_PARALLEL_AXIS,
    _payload_spec,
    _pipelined_fwd_bwd,
    _warn_moved,
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_with_split,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    listify_model,
    make_encoder_decoder_step,
    pipeline_schedule_plan,
)

_warn_moved("apex_tpu.transformer.pipeline_parallel.schedules")
