"""Pipeline-parallel forward-backward schedules.

Parity: reference apex/transformer/pipeline_parallel/schedules/ —
``get_forward_backward_func`` (schedules/__init__.py:22-35) selecting
(a) no-pipelining with grad sync on last microbatch
    (fwd_bwd_no_pipelining.py:23-124),
(b) 1F1B non-interleaved (fwd_bwd_pipelining_without_interleaving.py:241-597),
(c) interleaved 1F1B with virtual chunks
    (fwd_bwd_pipelining_with_interleaving.py).

TPU design: the reference schedules are eager Python loops over blocking
NCCL p2p calls. Here each schedule is ONE jitted SPMD program: a
``lax.fori_loop`` over schedule ticks with ``lax.ppermute`` moving
activations/grads along the 'pp' mesh axis. Activation memory is bounded
by stashing only each microbatch's *stage input* and rematerializing the
forward in the backward tick (``jax.vjp`` over the stage fn) — the
TPU-idiomatic replacement for 1F1B's early-backward memory bound, with the
same pipeline bubble (M + P - 1 ticks per phase).

Stage-fn contract (replaces the reference's forward_step_func protocol,
common.py:253-324):

    forward_step_func(params, input_tensor, microbatch, is_first_stage)
        -> output_tensor
    loss_func(params, output_tensor, microbatch) -> scalar loss

``input_tensor`` is None under the no-pipelining schedule (one stage owns
the whole model — build the input from the microbatch unconditionally).

Every pp rank holds ``params`` with the same pytree structure (its own
stage's weights). ``is_first_stage`` is a traced bool that is True only on
the *global* first stage (chunk 0 of rank 0 under virtual pipelining) —
the stage fn builds its input from the microbatch there (embedding) via
``jnp.where(is_first_stage, embed(mb), input_tensor)``. ``loss_func`` is
evaluated on the last stage only (masked by the schedule).
"""

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import (
    PIPELINE_PARALLEL_AXIS,
    get_pipeline_model_parallel_world_size,
    get_virtual_pipeline_model_parallel_world_size,
)
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_backward_recv_backward,
    send_forward_recv_forward,
)


def listify_model(model):
    if isinstance(model, list):
        return model
    return [model]


def get_forward_backward_func(virtual_pipeline_model_parallel_size=None,
                              pipeline_model_parallel_size=None):
    """Select a schedule (reference schedules/__init__.py:22-35)."""
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = get_pipeline_model_parallel_world_size()
    if virtual_pipeline_model_parallel_size is None:
        virtual_pipeline_model_parallel_size = (
            get_virtual_pipeline_model_parallel_world_size())
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


def forward_backward_no_pipelining(forward_step_func, loss_func, params,
                                   microbatches, *, num_microbatches,
                                   grad_scale=1.0, **unused):
    """Accumulate grads over microbatches without pipelining
    (reference fwd_bwd_no_pipelining.py:23-124; grad sync deferral to the
    last microbatch is automatic — sync happens once on the returned
    accumulated grads)."""

    def one_microbatch(params, mb):
        def full(p):
            y = forward_step_func(p, None, mb, jnp.asarray(True))
            return loss_func(p, y, mb)

        loss, grads = jax.value_and_grad(full)(params)
        return loss, grads

    def scan_body(carry, mb):
        loss_sum, grads_acc = carry
        loss, grads = one_microbatch(params, mb)
        grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
        return (loss_sum + loss, grads_acc), loss

    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (loss_sum, grads), losses = lax.scan(
        scan_body, (jnp.zeros((), jnp.float32), zero_grads), microbatches)
    n = jnp.asarray(num_microbatches, jnp.float32)
    grads = jax.tree_util.tree_map(lambda g: g * (grad_scale / n), grads)
    return losses, grads


def forward_backward_pipelining_without_interleaving(
        forward_step_func: Callable, loss_func: Callable, params,
        microbatches, *, num_microbatches: int,
        tensor_shape, dtype=jnp.float32,
        axis_name: str = PIPELINE_PARALLEL_AXIS,
        grad_scale: float = 1.0,
        pp_size: Optional[int] = None,
        **unused):
    """Pipelined forward-backward over the 'pp' axis (one jitted program).

    Parity target: fwd_bwd_pipelining_without_interleaving.py:241-597.
    Returns (per-microbatch losses [M] — nonzero on the last stage only,
    grads pytree scaled by grad_scale / num_microbatches).

    Must run inside shard_map with the 'pp' axis bound; ``tensor_shape`` is
    the (seq, microbatch, hidden) activation shape crossing stage
    boundaries (reference get_tensor_shapes, ...without_interleaving.py:29-86).
    """
    P = pp_size or get_pipeline_model_parallel_world_size()
    M = num_microbatches
    rank = lax.axis_index(axis_name)
    is_first = rank == 0
    is_last = rank == P - 1

    def take_mb(i):
        return jax.tree_util.tree_map(lambda a: a[i], microbatches)

    def stage_and_loss(p, h, mb):
        y = forward_step_func(p, h, mb, is_first)
        loss = loss_func(p, y, mb)
        return y, loss

    zero_h = jnp.zeros(tensor_shape, dtype)
    ticks = M + P - 1

    # ---------------- forward phase ----------------
    def fwd_tick(t, carry):
        # named_scope = the reference's NVTX/timer annotations around
        # forward_step (_timers.py usage in the schedules)
        with jax.named_scope("pp_fwd_tick"):
            xs, y_prev, losses = carry
            recv = send_forward_recv_forward(y_prev, axis_name, world=P)
            mb_idx = t - rank
            active = (mb_idx >= 0) & (mb_idx < M)
            mb_safe = jnp.clip(mb_idx, 0, M - 1)
            mb = take_mb(mb_safe)
            h_in = jnp.where(is_first, zero_h, recv).astype(dtype)
            y, loss = stage_and_loss(params, h_in, mb)
            # stash the stage input for rematerialized backward
            xs = lax.dynamic_update_index_in_dim(
                xs, jnp.where(active, h_in, xs[mb_safe]), mb_safe, 0)
            losses = losses.at[mb_safe].add(
                jnp.where(active & is_last, loss, 0.0))
            y_prev = jnp.where(active, y, jnp.zeros_like(y))
            return xs, y_prev, losses

    xs0 = jnp.zeros((M,) + tuple(tensor_shape), dtype)
    losses0 = jnp.zeros((M,), jnp.float32)
    xs, _, losses = lax.fori_loop(
        0, ticks, fwd_tick, (xs0, zero_h, losses0))

    # ---------------- backward phase ----------------
    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def bwd_tick(t, carry):
        with jax.named_scope("pp_bwd_tick"):
            grads_acc, dx_prev = carry
            dy_recv = send_backward_recv_backward(dx_prev, axis_name, world=P)
            mb_idx = (M - 1) - (t - (P - 1 - rank))
            active = (mb_idx >= 0) & (mb_idx < M)
            mb_safe = jnp.clip(mb_idx, 0, M - 1)
            mb = take_mb(mb_safe)
            h_in = xs[mb_safe]
            _, pullback = jax.vjp(
                lambda p, h: stage_and_loss(p, h, mb), params, h_in)
            dy_cot = jnp.where(active & ~is_last, dy_recv,
                               jnp.zeros_like(dy_recv)).astype(dtype)
            loss_cot = jnp.where(active & is_last,
                                 jnp.asarray(grad_scale, jnp.float32), 0.0)
            dparams, dh = pullback((dy_cot, loss_cot))
            grads_acc = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(active, d.astype(jnp.float32), 0.0),
                grads_acc, dparams)
            dx_prev = jnp.where(active, dh, jnp.zeros_like(dh)).astype(dtype)
            return grads_acc, dx_prev

    grads, _ = lax.fori_loop(0, ticks, bwd_tick, (zero_grads, zero_h))
    n = jnp.asarray(M, jnp.float32)
    grads = jax.tree_util.tree_map(lambda g: g / n, grads)
    return losses, grads


def forward_backward_pipelining_with_interleaving(
        forward_step_func: Callable, loss_func: Callable, params,
        microbatches, *, num_microbatches: int, tensor_shape,
        dtype=jnp.float32, axis_name: str = PIPELINE_PARALLEL_AXIS,
        grad_scale: float = 1.0, pp_size: Optional[int] = None,
        num_model_chunks: Optional[int] = None, **unused):
    """Interleaved (virtual-pipeline) schedule.

    Parity target: fwd_bwd_pipelining_with_interleaving.py (516 LoC).
    ``params`` is a pytree whose leaves carry a leading ``num_model_chunks``
    dim (stacked virtual chunks per rank); the model ring is traversed
    ``num_model_chunks`` times: chunk c on rank r is global stage
    c * P + r. Implemented as V sequential pipeline passes over the ring:
    chunk c's rank-(P-1) outputs are stored per microbatch and handed to
    chunk c+1's rank 0 with a single-edge ppermute; the backward walks the
    chunks in reverse, handing input-grads from rank 0 back to rank P-1.
    Each pass pipelines its M microbatches exactly like the
    non-interleaved schedule.
    """
    P = pp_size or get_pipeline_model_parallel_world_size()
    V = num_model_chunks or get_virtual_pipeline_model_parallel_world_size() or 1
    if V == 1:
        return forward_backward_pipelining_without_interleaving(
            forward_step_func, loss_func, params, microbatches,
            num_microbatches=num_microbatches, tensor_shape=tensor_shape,
            dtype=dtype, axis_name=axis_name, grad_scale=grad_scale,
            pp_size=P)
    M = num_microbatches
    S = V * P  # global stages
    rank = lax.axis_index(axis_name)

    def take_mb(i):
        return jax.tree_util.tree_map(lambda a: a[i], microbatches)

    def chunk_params(c):
        return jax.tree_util.tree_map(lambda a: a[c], params)

    zero_h = jnp.zeros(tensor_shape, dtype)
    ticks = M + P - 1
    losses_total = jnp.zeros((M,), jnp.float32)
    # per-chunk stashed stage inputs for rematerialized backward
    xs_all = jnp.zeros((V, M) + tuple(tensor_shape), dtype)
    # chunk-boundary activations: outputs of rank P-1, inputs for next chunk
    boundary = jnp.zeros((M,) + tuple(tensor_shape), dtype)

    # ---------------- forward: V sequential ring passes ----------------
    for c in range(V):
        p_c = chunk_params(c)
        is_first = (rank == 0) & (c == 0)
        is_last = (rank == P - 1) & (c == V - 1)

        def stage_and_loss(p, h, mb, is_first=is_first, is_last=is_last):
            y = forward_step_func(p, h, mb, is_first)
            loss = jnp.where(is_last, loss_func(p, y, mb), 0.0)
            return y, loss

        def fwd_tick(t, carry, c=c, p_c=p_c, is_first=is_first,
                     stage_and_loss=stage_and_loss):
            xs, y_prev, losses, new_boundary = carry
            recv = send_forward_recv_forward(y_prev, axis_name, world=P)
            # hand chunk c-1's stored boundary from rank P-1 to rank 0
            if c > 0:
                mb_t = jnp.clip(t, 0, M - 1)
                handoff = lax.ppermute(boundary[mb_t], axis_name, [(P - 1, 0)])
                first_in = handoff
            else:
                first_in = zero_h
            mb_idx = t - rank
            active = (mb_idx >= 0) & (mb_idx < M)
            mb_safe = jnp.clip(mb_idx, 0, M - 1)
            mb = take_mb(mb_safe)
            h_in = jnp.where(rank == 0, first_in, recv).astype(dtype)
            y, loss = stage_and_loss(p_c, h_in, mb)
            xs = lax.dynamic_update_index_in_dim(
                xs, jnp.where(active, h_in, xs[mb_safe]), mb_safe, 0)
            losses = losses.at[mb_safe].add(jnp.where(active, loss, 0.0))
            new_boundary = lax.dynamic_update_index_in_dim(
                new_boundary,
                jnp.where(active & (rank == P - 1), y, new_boundary[mb_safe]),
                mb_safe, 0)
            y_prev = jnp.where(active, y, jnp.zeros_like(y))
            return xs, y_prev, losses, new_boundary

        xs0 = jnp.zeros((M,) + tuple(tensor_shape), dtype)
        xs_c, _, losses_total, boundary = lax.fori_loop(
            0, ticks, fwd_tick,
            (xs0, zero_h, losses_total, jnp.zeros_like(boundary)))
        xs_all = xs_all.at[c].set(xs_c)

    # ---------------- backward: V reverse ring passes ----------------
    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    grads = zero_grads
    # grads of chunk c's first-stage input (on rank 0), cotangent for
    # chunk c-1's boundary outputs (needed on rank P-1)
    dboundary = jnp.zeros((M,) + tuple(tensor_shape), dtype)

    for c in reversed(range(V)):
        p_c = chunk_params(c)
        is_last = (rank == P - 1) & (c == V - 1)

        is_first_c = (rank == 0) & (c == 0)

        def stage_and_loss(p, h, mb, is_first=is_first_c, is_last=is_last):
            y = forward_step_func(p, h, mb, is_first)
            loss = jnp.where(is_last, loss_func(p, y, mb), 0.0)
            return y, loss

        def bwd_tick(t, carry, c=c, p_c=p_c, is_last=is_last,
                     stage_and_loss=stage_and_loss):
            grads_acc, dx_prev, new_dboundary = carry
            dy_recv = send_backward_recv_backward(dx_prev, axis_name, world=P)
            if c < V - 1:
                # cotangent for this chunk's rank-(P-1) outputs, stored on
                # rank 0 during chunk c+1's pass
                mb_t = jnp.clip(M - 1 - t, 0, M - 1)
                handoff = lax.ppermute(dboundary[mb_t], axis_name, [(0, P - 1)])
                last_dy = handoff
            else:
                last_dy = jnp.zeros_like(zero_h)
            mb_idx = (M - 1) - (t - (P - 1 - rank))
            active = (mb_idx >= 0) & (mb_idx < M)
            mb_safe = jnp.clip(mb_idx, 0, M - 1)
            mb = take_mb(mb_safe)
            h_in = xs_all[c, mb_safe]
            _, pullback = jax.vjp(
                lambda p, h: stage_and_loss(p, h, mb), p_c, h_in)
            dy_cot = jnp.where(rank == P - 1, last_dy, dy_recv)
            dy_cot = jnp.where(active & ~is_last, dy_cot,
                               jnp.zeros_like(dy_cot)).astype(dtype)
            loss_cot = jnp.where(active & is_last,
                                 jnp.asarray(grad_scale, jnp.float32), 0.0)
            dparams, dh = pullback((dy_cot, loss_cot))
            grads_acc = jax.tree_util.tree_map(
                lambda a, d: a.at[c].add(
                    jnp.where(active, d.astype(jnp.float32), 0.0)),
                grads_acc, dparams)
            new_dboundary = lax.dynamic_update_index_in_dim(
                new_dboundary,
                jnp.where(active & (rank == 0), dh.astype(dtype),
                          new_dboundary[mb_safe]),
                mb_safe, 0)
            dx_prev = jnp.where(active, dh, jnp.zeros_like(dh)).astype(dtype)
            return grads_acc, dx_prev, new_dboundary

        grads, _, dboundary = lax.fori_loop(
            0, ticks, bwd_tick, (grads, zero_h, jnp.zeros_like(dboundary)))

    n = jnp.asarray(M, jnp.float32)
    grads = jax.tree_util.tree_map(lambda g: g / n, grads)
    return losses_total, grads
