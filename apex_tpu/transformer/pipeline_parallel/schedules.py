"""Pipeline-parallel forward-backward schedules.

Parity: reference apex/transformer/pipeline_parallel/schedules/ —
``get_forward_backward_func`` (schedules/__init__.py:22-35) selecting
(a) no-pipelining with grad sync on last microbatch
    (fwd_bwd_no_pipelining.py:23-124),
(b) 1F1B non-interleaved (fwd_bwd_pipelining_without_interleaving.py:241-597,
    warmup math at :345-349),
(c) interleaved 1F1B with virtual chunks
    (fwd_bwd_pipelining_with_interleaving.py, get_model_chunk_id scheduling).

TPU design: the reference schedules are eager Python loops over blocking
NCCL p2p calls. Here both pipelined schedules are ONE jitted SPMD program
sharing one core (``_pipelined_fwd_bwd`` — non-interleaved is the V=1
case): a ``lax.fori_loop`` over *global schedule ticks* with
``lax.ppermute`` moving activations/grads along the 'pp' mesh axis. Three
phases — a forward-only warmup, a steady state in which every tick
performs one forward unit AND one backward unit (true 1F1B alternation),
and a backward-only cooldown — so the executed compute per rank is
(M + P - 1) * (t_fwd + t_bwd) at V=1, the same pipeline total as the
reference's 1F1B, instead of the 2*(M + P - 1) full-ticks of a
phase-split schedule.

Memory is bounded like the reference's 1F1B: only each in-flight
microbatch's *stage input* is stashed, in a ring buffer whose size is the
in-flight bound (min(M, 2P-1) at V=1; min(MV, 2VP) interleaved) — O(P·V),
not O(M) — and the forward is rematerialized inside the backward tick
(``jax.vjp`` over the stage fn), the TPU-idiomatic activation-recompute
tradeoff (reference random.py:237-311 makes the same trade when
activation checkpointing is on).

The loss (for GPT: the full vocab projection) is computed under a
``lax.cond`` on ``is_last_stage``, so non-last ranks skip it at runtime in
both the primal and the transpose (reference computes loss_func only on
the last stage, common.py:305-310).

Stage-fn contract (replaces the reference's forward_step_func protocol,
common.py:253-324):

    forward_step_func(params, input_tensor, microbatch, is_first_stage)
        -> output_tensor
    loss_func(params, output_tensor, microbatch) -> scalar loss

``input_tensor`` is None under the no-pipelining schedule (one stage owns
the whole model — build the input from the microbatch unconditionally).

Every pp rank holds ``params`` with the same pytree structure (its own
stage's weights; stacked [V, ...] leaves under interleaving).
``is_first_stage`` is a traced bool that is True only on the *global*
first stage (chunk 0 of rank 0 under virtual pipelining) — the stage fn
builds its input from the microbatch there (embedding) via
``jnp.where(is_first_stage, embed(mb), input_tensor)``. ``loss_func`` is
evaluated on the last global stage only.
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.transformer.parallel_state import (
    PIPELINE_PARALLEL_AXIS,
    get_pipeline_model_parallel_split_rank,
    get_pipeline_model_parallel_world_size,
    get_virtual_pipeline_model_parallel_world_size,
)
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_backward_recv_backward,
    send_forward_recv_forward,
)


def listify_model(model):
    if isinstance(model, list):
        return model
    return [model]


def pipeline_schedule_plan(pp_size: int, num_microbatches: int,
                           num_model_chunks: int = 1) -> dict:
    """Static tick/memory plan of the pipelined schedules (pure Python).

    The schedules below derive their loop bounds and stash sizes from this
    function, so its numbers are the numbers — tests assert on them.

    Forward unit k = round*P*V + c*P + j of (chunk c, microbatch
    i = round*P + j) runs on rank r at tick k + r — microbatch groups of
    size P cycling through chunks, the reference's get_model_chunk_id
    order (V=1 degenerates to k = i) — and its backward mirrors it from
    tick V*P - 1 (the last global stage's backward shares its forward's
    tick). Chunk handoffs ride a circular ppermute with exactly-one-tick
    latency, so rank 0's warmup before its first backward is
    2(P-1) + (V-1)*P units, the reference's warmup formula
    (fwd_bwd_pipelining_with_interleaving.py num_warmup_microbatches).
    """
    P, M, V = pp_size, num_microbatches, num_model_chunks
    if V == 1:
        return {
            "warmup": P - 1,            # fwd-only ticks
            "steady": M,                # fwd+bwd ticks
            "cooldown": P - 1,          # bwd-only ticks
            "total": M + 2 * P - 2,
            "fwd_ticks": M + P - 1,     # ticks executing a fwd unit
            "bwd_ticks": M + P - 1,
            "stash": min(M, 2 * P - 1),  # in-flight stage inputs: O(P)
        }
    return {
        "warmup": V * P - 1,
        "steady": M * V,
        "cooldown": P - 1,
        "total": M * V + V * P + P - 2,
        "fwd_ticks": M * V + V * P - 1,
        "bwd_ticks": M * V + P - 1,
        "stash": min(M * V, 2 * V * P),  # O(P*V) chunk-stage inputs
    }


def get_forward_backward_func(virtual_pipeline_model_parallel_size=None,
                              pipeline_model_parallel_size=None):
    """Select a schedule (reference schedules/__init__.py:22-35).

    A pipeline split rank installed via ``initialize_model_parallel``
    selects the encoder-decoder schedule (the reference routes
    ``ModelType.encoder_and_decoder`` through the same selector; its
    interleaved schedule is encoder_or_decoder-only, and so is ours)."""
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = get_pipeline_model_parallel_world_size()
    if virtual_pipeline_model_parallel_size is None:
        virtual_pipeline_model_parallel_size = (
            get_virtual_pipeline_model_parallel_world_size())
    if pipeline_model_parallel_size > 1:
        if get_pipeline_model_parallel_split_rank() is not None:
            if virtual_pipeline_model_parallel_size is not None:
                raise ValueError(
                    "interleaved (virtual-pipeline) scheduling does not "
                    "compose with an encoder-decoder split rank")
            return forward_backward_pipelining_with_split
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


def forward_backward_no_pipelining(forward_step_func, loss_func, params,
                                   microbatches, *, num_microbatches,
                                   grad_scale=1.0, **unused):
    """Accumulate grads over microbatches without pipelining
    (reference fwd_bwd_no_pipelining.py:23-124; grad sync deferral to the
    last microbatch is automatic — sync happens once on the returned
    accumulated grads)."""

    def one_microbatch(params, mb):
        def full(p):
            y = forward_step_func(p, None, mb, jnp.asarray(True))
            return loss_func(p, y, mb)

        loss, grads = jax.value_and_grad(full)(params)
        return loss, grads

    def scan_body(carry, mb):
        loss_sum, grads_acc = carry
        loss, grads = one_microbatch(params, mb)
        grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
        return (loss_sum + loss, grads_acc), loss

    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (loss_sum, grads), losses = lax.scan(
        scan_body, (jnp.zeros((), jnp.float32), zero_grads), microbatches)
    n = jnp.asarray(num_microbatches, jnp.float32)
    grads = jax.tree_util.tree_map(lambda g: g * (grad_scale / n), grads)
    return losses, grads


def _payload_spec(tensor_shape, dtype):
    """Normalize the boundary-payload description to a pytree of
    ``jax.ShapeDtypeStruct``. A plain tuple/list of ints (the common
    single-activation case) becomes one leaf of ``dtype``; anything else
    is taken as an already-built spec pytree — the encoder-decoder
    schedule passes a two-leaf dict (reference dual shapes,
    ...without_interleaving.py:29-86)."""
    if (isinstance(tensor_shape, (tuple, list))
            and all(isinstance(d, (int, np.integer)) for d in tensor_shape)):
        return jax.ShapeDtypeStruct(
            tuple(int(d) for d in tensor_shape), dtype)
    return jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(tuple(s.shape), s.dtype),
        tensor_shape)


def _pipelined_fwd_bwd(forward_step_func, loss_func, params, microbatches,
                       *, M, V, P, tensor_shape, dtype, axis_name,
                       grad_scale, aux_loss=False):
    """Shared 3-phase tick machine for both pipelined schedules
    (see pipeline_schedule_plan for the tick/unit mapping).

    The stage-boundary payload is a pytree (single activation array for
    GPT-style stacks; an {encoder, decoder} pair for split-rank models);
    every payload op below — stash, ppermute shift, masking, dtype cast —
    is tree-mapped over its leaves.

    ``aux_loss=True`` changes the stage contract to
    ``forward_step_func(...) -> (output_tensor, aux_scalar)``: each
    unit's backward injects its own stage's auxiliary loss (e.g. MoE
    router load-balancing, scaled by grad_scale like the main loss)
    alongside the downstream activation cotangent — total loss =
    last-stage loss_func + sum of per-unit aux, with aux gradients
    flowing to earlier stages through the regular backward wave. The
    reported per-microbatch losses remain the last stage's (loss_func +
    its own aux) only.
    """
    plan = pipeline_schedule_plan(P, M, V)
    S = plan["stash"]
    PV, MV = P * V, M * V
    T0 = V * P - 1  # first backward tick (mb 0 has crossed all V*P stages)
    rank = lax.axis_index(axis_name)
    interleaved = V > 1
    tmap = jax.tree_util.tree_map
    spec = _payload_spec(tensor_shape, dtype)

    def _mask(pred, tree):
        return tmap(lambda a: jnp.where(pred, a, jnp.zeros_like(a)), tree)

    def _select(pred, tree_a, tree_b):
        return tmap(lambda a, b: jnp.where(pred, a, b), tree_a, tree_b)

    def _cast(tree):
        return tmap(lambda a, s: a.astype(s.dtype), tree, spec)

    def take_mb(i):
        return jax.tree_util.tree_map(lambda a: a[i], microbatches)

    if interleaved:
        def take_params(c):
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                params)

        def add_grads(grads, dp, c, active):
            return jax.tree_util.tree_map(
                lambda a, d: a.at[c].add(
                    jnp.where(active, d.astype(jnp.float32), 0.0)),
                grads, dp)
    else:
        def take_params(c):
            return params

        def add_grads(grads, dp, c, active):
            return jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(active, d.astype(jnp.float32),
                                           0.0),
                grads, dp)

    def fwd_unit(k):
        rnd, rem = k // PV, k % PV
        c, j = rem // P, rem % P
        return c, rnd * P + j, k % S

    def bwd_unit(kb):
        rnd, rem = kb // PV, kb % PV
        c, j = (V - 1) - rem // P, rem % P
        kf = rnd * PV + c * P + j
        return c, rnd * P + j, kf % S

    zero_h = tmap(lambda s: jnp.zeros(s.shape, s.dtype), spec)

    def run_stage(p, h, mb, is_first_u):
        if aux_loss:
            return forward_step_func(p, h, mb, is_first_u)
        return (forward_step_func(p, h, mb, is_first_u),
                jnp.zeros((), jnp.float32))

    def stage_and_maybe_loss(p, h, mb, is_first_u, is_last_u):
        y, aux = run_stage(p, h, mb, is_first_u)
        # Only the last global stage pays for loss_func (for GPT: the
        # vocab projection) — lax.cond skips it at runtime elsewhere, in
        # both the primal and the transpose. Per-unit aux (module doc)
        # rides the same loss output.
        loss = lax.cond(
            is_last_u,
            lambda op: loss_func(*op).astype(jnp.float32),
            lambda op: jnp.zeros((), jnp.float32),
            (p, y, mb))
        return y, loss + aux.astype(jnp.float32)

    # state = (stash, y_prev, dx_prev, losses, grads)
    def fwd_half(t, state):
        with jax.named_scope("pp_fwd_unit"):
            xs, y_prev, dx_prev, losses, grads = state
            recv = send_forward_recv_forward(
                y_prev, axis_name, world=P, circular=interleaved)
            k = t - rank
            active = (k >= 0) & (k < MV)
            c, i, slot = fwd_unit(jnp.clip(k, 0, MV - 1))
            mb = take_mb(i)
            p_c = take_params(c)
            is_first_u = (rank == 0) & (c == 0)
            h_in = _cast(_select(is_first_u, zero_h, recv))
            y, _ = run_stage(p_c, h_in, mb, is_first_u)
            xs = tmap(
                lambda buf, h: lax.dynamic_update_index_in_dim(
                    buf, jnp.where(active, h, buf[slot]), slot, 0),
                xs, h_in)
            y_prev = _mask(active, y)
            return xs, y_prev, dx_prev, losses, grads

    def bwd_half(t, state):
        with jax.named_scope("pp_bwd_unit"):
            xs, y_prev, dx_prev, losses, grads = state
            dy_recv = send_backward_recv_backward(
                dx_prev, axis_name, world=P, circular=interleaved)
            kb = t - T0 - (P - 1 - rank)
            active = (kb >= 0) & (kb < MV)
            c, i, slot = bwd_unit(jnp.clip(kb, 0, MV - 1))
            mb = take_mb(i)
            p_c = take_params(c)
            is_first_u = (rank == 0) & (c == 0)
            is_last_u = (rank == P - 1) & (c == V - 1)
            # the last global stage's backward shares its forward's tick,
            # and fwd_half runs first in a steady tick, so the slot read
            # here is the input stashed moments ago; other reads never
            # collide with this tick's write (ring size >= in-flight).
            h_in = tmap(lambda buf: buf[slot], xs)
            (_, loss), pullback = jax.vjp(
                lambda p, h: stage_and_maybe_loss(p, h, mb, is_first_u,
                                                  is_last_u), p_c, h_in)
            dy_cot = _cast(_mask(active & ~is_last_u, dy_recv))
            # every active unit gets a loss cotangent: the main loss is
            # cond-gated to the last stage (zero transpose elsewhere),
            # while per-unit aux losses (if any) pick it up on their
            # own stage
            loss_cot = jnp.where(active,
                                 jnp.asarray(grad_scale, jnp.float32), 0.0)
            dp_c, dh = pullback((dy_cot, loss_cot))
            grads = add_grads(grads, dp_c, c, active)
            losses = losses.at[i].add(
                jnp.where(active & is_last_u, loss, 0.0))
            dx_prev = _cast(_mask(active, dh))
            return xs, y_prev, dx_prev, losses, grads

    zero_grads = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    stash0 = tmap(lambda s: jnp.zeros((S,) + tuple(s.shape), s.dtype), spec)
    state = (stash0, zero_h, zero_h,
             jnp.zeros((M,), jnp.float32), zero_grads)
    w, s = plan["warmup"], plan["steady"]
    state = lax.fori_loop(0, w, fwd_half, state)
    state = lax.fori_loop(w, w + s,
                          lambda t, st: bwd_half(t, fwd_half(t, st)), state)
    state = lax.fori_loop(w + s, plan["total"], bwd_half, state)
    _, _, _, losses, grads = state
    n = jnp.asarray(M, jnp.float32)
    grads = jax.tree_util.tree_map(lambda g: g / n, grads)
    return losses, grads


def forward_backward_pipelining_without_interleaving(
        forward_step_func: Callable, loss_func: Callable, params,
        microbatches, *, num_microbatches: int,
        tensor_shape, dtype=jnp.float32,
        axis_name: str = PIPELINE_PARALLEL_AXIS,
        grad_scale: float = 1.0,
        pp_size: Optional[int] = None,
        aux_loss: bool = False,
        **unused):
    """True 1F1B over the 'pp' axis in one jitted program (see module doc).

    Parity target: fwd_bwd_pipelining_without_interleaving.py:241-597.
    Returns (per-microbatch losses [M] — nonzero on the last stage only,
    grads pytree scaled by grad_scale / num_microbatches).

    Must run inside shard_map with the 'pp' axis bound; ``tensor_shape``
    is the (seq, microbatch, hidden) activation shape crossing stage
    boundaries (reference get_tensor_shapes,
    ...without_interleaving.py:29-86).
    """
    P = pp_size or get_pipeline_model_parallel_world_size()
    return _pipelined_fwd_bwd(
        forward_step_func, loss_func, params, microbatches,
        M=num_microbatches, V=1, P=P, tensor_shape=tensor_shape,
        dtype=dtype, axis_name=axis_name, grad_scale=grad_scale,
        aux_loss=aux_loss)


def forward_backward_pipelining_with_interleaving(
        forward_step_func: Callable, loss_func: Callable, params,
        microbatches, *, num_microbatches: int, tensor_shape,
        dtype=jnp.float32, axis_name: str = PIPELINE_PARALLEL_AXIS,
        grad_scale: float = 1.0, pp_size: Optional[int] = None,
        num_model_chunks: Optional[int] = None, aux_loss: bool = False,
        **unused):
    """Interleaved (virtual-pipeline) 1F1B in one steady state.

    Parity target: fwd_bwd_pipelining_with_interleaving.py (516 LoC).
    ``params`` is a pytree whose leaves carry a leading ``num_model_chunks``
    dim (stacked virtual chunks per rank); chunk c on rank r is global
    stage c * P + r. Unlike a sequential-passes scheme (bubble V*(P-1)
    full passes), all chunks share ONE steady state: each global tick maps
    to a (chunk, microbatch) unit per rank via the reference's
    get_model_chunk_id order, so the forward wave fills in V*P - 1 ticks
    and drains in P - 1 — per-rank overhead (V*P-1) fwd units + (P-1) bwd
    units over the M*V useful ticks, matching the reference's rank-0
    warmup of 2(P-1) + (V-1)P forward units. Chunk handoffs (rank P-1's
    chunk-c output -> rank 0's chunk c+1 input, and the reverse for
    grads) have exactly-one-tick latency under this order, so they ride
    the same *circular* ppermute as the intra-chunk shifts — no boundary
    buffers.
    """
    P = pp_size or get_pipeline_model_parallel_world_size()
    V = num_model_chunks or get_virtual_pipeline_model_parallel_world_size() or 1
    if V == 1:
        return forward_backward_pipelining_without_interleaving(
            forward_step_func, loss_func, params, microbatches,
            num_microbatches=num_microbatches, tensor_shape=tensor_shape,
            dtype=dtype, axis_name=axis_name, grad_scale=grad_scale,
            pp_size=P, aux_loss=aux_loss)
    if num_microbatches % P != 0:
        # reference fwd_bwd_pipelining_with_interleaving.py asserts
        # num_microbatches % pipeline_parallel_size == 0
        raise ValueError(
            f"interleaved schedule requires num_microbatches "
            f"({num_microbatches}) to be a multiple of "
            f"pipeline_model_parallel_size ({P})")
    return _pipelined_fwd_bwd(
        forward_step_func, loss_func, params, microbatches,
        M=num_microbatches, V=V, P=P, tensor_shape=tensor_shape,
        dtype=dtype, axis_name=axis_name, grad_scale=grad_scale,
        aux_loss=aux_loss)


def forward_backward_pipelining_with_split(
        forward_step_func: Callable, loss_func: Callable, params,
        microbatches, *, num_microbatches: int,
        encoder_tensor_shape, decoder_tensor_shape,
        dtype=jnp.float32, axis_name: str = PIPELINE_PARALLEL_AXIS,
        grad_scale: float = 1.0, pp_size: Optional[int] = None,
        split_rank: Optional[int] = None, aux_loss: bool = False,
        **unused):
    """Encoder-decoder (split-rank) 1F1B.

    Parity target: the reference's ``ModelType.encoder_and_decoder`` path —
    dual p2p tensor shapes computed from ``decoder_seq_length``
    (fwd_bwd_pipelining_without_interleaving.py:29-86's get_tensor_shapes)
    with the encoder on ranks ``< split_rank`` and the decoder at/after it
    (parallel_state.py:243-331 places embedding groups around the same
    split). The reference moves *two* tensors across decoder-side stage
    boundaries (encoder memory + decoder stream); here the boundary
    payload is the two-leaf pytree
    ``{"encoder": (enc_seq, mb, h), "decoder": (dec_seq, mb, h)}`` riding
    the same tick machine — encoder ranks advance the encoder leaf and
    pass the decoder leaf through untouched; decoder ranks advance the
    decoder leaf with the encoder leaf as cross-attention memory,
    forwarding it unchanged so every decoder stage sees the final encoder
    output. Interleaving is not supported with a split (matches the
    reference's encoder_or_decoder-only interleaved schedule).

    Stage contract (build with :func:`make_encoder_decoder_step`):

        forward_step_func(params, payload_dict, mb, is_first_stage)
            -> payload_dict
        loss_func(params, payload_dict, mb) -> scalar   # reads "decoder"

    Returns (per-microbatch losses [M] — nonzero on the last stage only,
    grads pytree scaled by grad_scale / num_microbatches).
    """
    P = pp_size or get_pipeline_model_parallel_world_size()
    split = (split_rank if split_rank is not None
             else get_pipeline_model_parallel_split_rank())
    if split is None or not 0 < split < P:
        raise ValueError(
            f"encoder-decoder pipelining needs 0 < split_rank < pp_size; "
            f"got split_rank={split}, pp_size={P} (set it via "
            f"initialize_model_parallel(..., "
            f"pipeline_model_parallel_split_rank=...) or pass split_rank=)")
    spec = {
        "encoder": jax.ShapeDtypeStruct(tuple(encoder_tensor_shape), dtype),
        "decoder": jax.ShapeDtypeStruct(tuple(decoder_tensor_shape), dtype),
    }
    return _pipelined_fwd_bwd(
        forward_step_func, loss_func, params, microbatches,
        M=num_microbatches, V=1, P=P, tensor_shape=spec, dtype=dtype,
        axis_name=axis_name, grad_scale=grad_scale, aux_loss=aux_loss)


def make_encoder_decoder_step(encoder_step: Callable, decoder_step: Callable,
                              *, split_rank: Optional[int] = None,
                              axis_name: str = PIPELINE_PARALLEL_AXIS):
    """Build the stage fn for :func:`forward_backward_pipelining_with_split`
    from per-side step functions:

        encoder_step(params, enc_h, mb, is_first_stage) -> enc_h
            (build enc_h from the microbatch when is_first_stage)
        decoder_step(params, dec_h, enc_memory, mb, is_split_stage) -> dec_h
            (build dec_h from the microbatch when is_split_stage — the
            first decoder stage, where the upstream decoder leaf is zeros)

    Rank-side selection is a runtime ``lax.cond`` on the pp mesh position
    vs the split rank — one SPMD program, each rank executes only its own
    side (consuming the split-rank bookkeeping the reference keeps in
    parallel_state.py:469-486 / is_pipeline_stage_before_split).
    ``params`` must carry both sides' weights in a uniform pytree on every
    rank (each rank's unused side receives zero grads).
    """
    split = (split_rank if split_rank is not None
             else get_pipeline_model_parallel_split_rank())
    if split is None:
        raise ValueError("make_encoder_decoder_step needs a split rank")

    def step(params, payload, mb, is_first_stage):
        rank = lax.axis_index(axis_name)

        def enc_branch(op):
            p, pl, mb_, first = op
            return {"encoder": encoder_step(p, pl["encoder"], mb_, first),
                    "decoder": pl["decoder"]}

        def dec_branch(op):
            p, pl, mb_, _ = op
            return {"encoder": pl["encoder"],
                    "decoder": decoder_step(p, pl["decoder"], pl["encoder"],
                                            mb_, rank == split)}

        return lax.cond(rank >= split, dec_branch, enc_branch,
                        (params, payload, mb, is_first_stage))

    return step
