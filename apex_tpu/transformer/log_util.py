"""Transformer logging helpers.

Parity: reference apex/transformer/log_util.py ``get_transformer_logger``
+ ``set_logging_level``, with the rank-aware formatter from
apex/__init__.py:31-43.
"""

import logging

from apex_tpu._logging import RankInfoFormatter

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(rank_info)s - %(message)s"


def get_transformer_logger(name: str) -> logging.Logger:
    name_wo_ext = name.split(".")[0]
    return logging.getLogger(name_wo_ext)


def set_logging_level(verbosity) -> None:
    """Change logging severity (reference log_util.py set_logging_level)."""
    from apex_tpu import _logging  # noqa: F401

    logger = logging.getLogger("apex_tpu")
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(RankInfoFormatter(_FORMAT))
        logger.addHandler(handler)
    logger.setLevel(verbosity)
