"""Ulysses-style all-to-all sequence parallelism.

No reference equivalent (SURVEY.md §5: the reference's only sequence
mechanism is Megatron SP). DeepSpeed-Ulysses (arXiv 2309.14509) pattern,
TPU-native: activations are sequence-sharded over the ``cp`` mesh axis;
on attention entry an ``lax.all_to_all`` redistributes so each device
holds the FULL sequence for ``heads/cp`` heads, full attention (any
kernel — here jnp, optionally flash) runs locally, and the inverse
all_to_all restores sequence sharding. Two all-to-alls per attention vs
ring's cp permutes; cheaper when heads >= cp and the sequence fits.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import CONTEXT_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import _axis_size


def all_to_all_seq_to_heads(x, axis_name=CONTEXT_PARALLEL_AXIS):
    """[s/cp, h, d] (seq-sharded) -> [s, h/cp, d] (head-sharded)."""
    if _axis_size(axis_name) == 1:
        return x
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)


def all_to_all_heads_to_seq(x, axis_name=CONTEXT_PARALLEL_AXIS):
    """[s, h/cp, d] (head-sharded) -> [s/cp, h, d] (seq-sharded)."""
    if _axis_size(axis_name) == 1:
        return x
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)


def _full_attention(q, k, v, causal, scale):
    """Plain full attention, [s, h, d] -> [s, h, d] (fp32 softmax)."""
    s = q.shape[0]
    scores = jnp.einsum("qhd,khd->hqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.where(
            jnp.arange(s)[:, None] >= jnp.arange(s)[None, :], 0.0, -jnp.inf)
        scores = scores + mask[None]
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def ulysses_attention(q, k, v, *, causal=False,
                      axis_name=CONTEXT_PARALLEL_AXIS, scale=None,
                      attention_fn=None):
    """Sequence-parallel attention via head/sequence all-to-all.

    Args:
      q, k, v: [s_local, num_heads, head_dim] sequence shards (inside
        ``shard_map`` with seq split over ``axis_name``); num_heads must
        be divisible by the axis size.
      attention_fn: optional ``f(q, k, v, causal, scale) -> out`` applied
        on the gathered-[s, h/cp, d] views (e.g. a Pallas flash kernel);
        defaults to fused jnp full attention.

    Returns [s_local, num_heads, head_dim].
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    cp = _axis_size(axis_name)
    if cp > 1 and q.shape[1] % cp != 0:
        raise ValueError(
            f"num_heads ({q.shape[1]}) not divisible by cp axis size ({cp})")
    fn = attention_fn or _full_attention
    qh = all_to_all_seq_to_heads(q, axis_name)
    kh = all_to_all_seq_to_heads(k, axis_name)
    vh = all_to_all_seq_to_heads(v, axis_name)
    out = fn(qh, kh, vh, causal, scale)
    return all_to_all_heads_to_seq(out, axis_name)


def ulysses_self_attention(q, k, v, **kw):
    """Batched variant: [batch, s_local, heads, head_dim]."""
    return jax.vmap(functools.partial(ulysses_attention, **kw))(q, k, v)
