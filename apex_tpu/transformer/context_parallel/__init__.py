"""Context (sequence) parallelism for long sequences.

The reference has NO ring-attention/context-parallel support (SURVEY.md
§2.3 note: its only long-context mechanism is Megatron SP + activation
checkpointing). This package is the TPU-first long-context capability the
framework treats as first-class:

- :mod:`ring_attention` — ring self-attention over a ``cp`` mesh axis:
  K/V blocks rotate around the ring via ``lax.ppermute`` while each device
  keeps its Q shard, with online-softmax accumulation (blockwise attention,
  arXiv 2310.01889 "Ring Attention with Blockwise Transformers").
- :mod:`ulysses` — DeepSpeed-Ulysses-style all-to-all sequence
  parallelism (arXiv 2309.14509): heads scatter / sequence gathers on
  entry, inverse on exit, full attention runs locally on 1/cp of heads.

Both compose with the tp/dp/pp axes from
``parallel_state.initialize_model_parallel(context_parallel_size_=...)``.
"""

from apex_tpu.transformer.context_parallel.ring_attention import (
    ring_attention,
    ring_self_attention,
)
from apex_tpu.transformer.context_parallel.ulysses import (
    ulysses_attention,
    ulysses_self_attention,
    all_to_all_heads_to_seq,
    all_to_all_seq_to_heads,
)

__all__ = [
    "ring_attention",
    "ring_self_attention",
    "ulysses_attention",
    "ulysses_self_attention",
    "all_to_all_heads_to_seq",
    "all_to_all_seq_to_heads",
]
