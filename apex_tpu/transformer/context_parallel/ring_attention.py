"""Ring attention over a context-parallel mesh axis.

No reference equivalent (the reference's long-context story is Megatron SP
only — SURVEY.md §5 "Long-context"); this is the TPU-native capability:
sequence sharded over the ``cp`` axis, K/V blocks rotating by
``lax.ppermute`` while a blockwise online-softmax accumulates the local
Q-shard's output (arXiv 2310.01889). Communication rides ICI and overlaps
with the per-block attention matmuls thanks to XLA's latency-hiding
scheduler; each step's FLOPs are one [s_q, d] x [d, s_kv] and one
[s_q, s_kv] x [s_kv, d] MXU matmul.

Memory: the ring body is wrapped in ``jax.checkpoint`` so autodiff
recomputes per-step attention instead of stashing every rotated K/V block
— per-device activation memory stays O(s_local^2 / cp) per step.

Causal masking is applied per ring step from global positions (shards are
laid out contiguously in ring-rank order): the diagonal block gets the
triangular mask, fully-future blocks mask to -inf. Every step still runs
both matmuls — uniform shapes keep the scan body a single fused XLA
computation; masked-out FLOPs are the price of static control flow.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import CONTEXT_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import _axis_size


def _block_attention(q, k, v, m_prev, l_prev, o_prev, scale, mask):
    """One blockwise-attention accumulation step (online softmax).

    q: [s_q, h, d]; k, v: [s_kv, h, d]; mask: [s_q, s_kv] additive or None.
    m/l: [h, s_q] running max / normalizer; o: [s_q, h, d] unnormalized.
    """
    # scores: [h, s_q, s_kv]
    scores = jnp.einsum("qhd,khd->hqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = scores + mask[None, :, :]
    m_cur = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked rows (m_new = -inf): keep them neutral
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(scores - m_safe[:, :, None])
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_safe), 0.0)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1)
    pv = jnp.einsum("hqk,khd->qhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = alpha.transpose(1, 0)[:, :, None] * o_prev + pv
    return m_new, l_new, o_new


def ring_attention(q, k, v, *, causal=False, axis_name=CONTEXT_PARALLEL_AXIS,
                   scale=None):
    """Ring self-attention on sequence shards.

    Args:
      q, k, v: [s_local, num_heads, head_dim] — this device's sequence
        shard (call inside ``shard_map`` with the sequence dim split over
        ``axis_name``). A leading batch dim is supported via vmap in
        :func:`ring_self_attention`.
      causal: apply a causal mask consistent with the *global* sequence
        (shards are assumed laid out contiguously in ring-rank order).
      axis_name: the context-parallel mesh axis.
      scale: softmax scale; default 1/sqrt(head_dim).

    Returns [s_local, num_heads, head_dim] attention output for the local
    Q shard, numerically identical (up to fp assoc.) to full attention on
    the gathered sequence.
    """
    s_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    cp = _axis_size(axis_name)

    if cp == 1:
        mask = None
        if causal:
            mask = jnp.where(
                jnp.arange(s_local)[:, None] >= jnp.arange(s_local)[None, :],
                0.0, -jnp.inf)
        m0 = jnp.full((h, s_local), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((h, s_local), jnp.float32)
        o0 = jnp.zeros((s_local, h, d), jnp.float32)
        m, l, o = _block_attention(q, k, v, m0, l0, o0, scale, mask)
        return (o / jnp.maximum(l, 1e-30).transpose(1, 0)[:, :, None]).astype(q.dtype)

    rank = lax.axis_index(axis_name)
    # send each device's K/V to its ring successor every step
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    q_pos = rank * s_local + jnp.arange(s_local)

    @functools.partial(jax.checkpoint,
                       policy=jax.checkpoint_policies.nothing_saveable)
    def body(carry, step):
        m_prev, l_prev, o_prev, k_cur, v_cur = carry
        # K/V block currently held arrived from rank - step (mod cp)
        kv_rank = (rank - step) % cp
        kv_pos = kv_rank * s_local + jnp.arange(s_local)
        if causal:
            mask = jnp.where(q_pos[:, None] >= kv_pos[None, :], 0.0, -jnp.inf)
        else:
            mask = None
        m_new, l_new, o_new = _block_attention(
            q, k_cur, v_cur, m_prev, l_prev, o_prev, scale, mask)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, o_new, k_nxt, v_nxt), None

    m0 = jnp.full((h, s_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((h, s_local), jnp.float32)
    o0 = jnp.zeros((s_local, h, d), jnp.float32)
    (m, l, o, _, _), _ = lax.scan(
        body, (m0, l0, o0, k, v), jnp.arange(cp))
    out = o / jnp.maximum(l, 1e-30).transpose(1, 0)[:, :, None]
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, *, causal=False,
                        axis_name=CONTEXT_PARALLEL_AXIS, scale=None):
    """Batched ring attention: q/k/v [batch, s_local, heads, head_dim]."""
    fn = functools.partial(ring_attention, causal=causal,
                           axis_name=axis_name, scale=scale)
    return jax.vmap(fn)(q, k, v)
