"""Microbatch-count calculators for pipeline/data-parallel training.

Behavioral parity target: reference apex/transformer/microbatches.py
(constant count, and a linear global-batch-size ramp-up schedule keyed on
consumed samples). Re-derived here from the schedule definition:

  A *granule* is ``micro_batch_size * data_parallel_size`` samples — the
  smallest global-batch quantum a (DP, microbatch) layout can consume.
  The constant calculator fixes ``global_batch_size / granule`` microbatches
  forever.  The ramp-up calculator grows the effective global batch from
  ``start`` to ``final`` in increments of ``step``, spending an equal share
  of ``ramp_samples`` at each intermediate size, then stays at ``final``.
"""

from abc import ABC, abstractmethod


class NumMicroBatchesCalculator(ABC):
    """Interface: ``get()`` -> current microbatch count; ``update()`` advances
    the schedule from the number of globally consumed samples."""

    num_micro_batches = None
    current_global_batch_size = None

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    @abstractmethod
    def update(self, consumed_samples, consistency_check):
        ...


def _granule(micro_batch_size, data_parallel_size):
    g = micro_batch_size * data_parallel_size
    if g <= 0:
        raise ValueError(
            f"need positive micro_batch_size ({micro_batch_size}) and "
            f"data_parallel_size ({data_parallel_size})")
    return g


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """Fixed microbatch count: global batch must be a whole number of granules."""

    def __init__(self, global_batch_size, micro_batch_size, data_parallel_size):
        granule = _granule(micro_batch_size, data_parallel_size)
        if global_batch_size % granule != 0:
            raise AssertionError(
                f"global_batch_size={global_batch_size} must be a multiple of "
                f"micro_batch_size*data_parallel_size={granule}")
        self.num_micro_batches = global_batch_size // granule
        assert self.num_micro_batches >= 1
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check):
        # Nothing to advance — the count never changes.
        return None


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear global-batch-size warmup.

    The global batch starts at ``start_batch_size`` and increases by
    ``batch_size_increment`` every ``ramup_samples / num_increments``
    consumed samples until it reaches ``global_batch_size``; past
    ``ramup_samples`` it is pinned at the final size.
    """

    def __init__(self, start_batch_size, batch_size_increment, ramup_samples,
                 global_batch_size, micro_batch_size, data_parallel_size):
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = _granule(
            micro_batch_size, data_parallel_size)

        if start_batch_size <= 0 or global_batch_size <= 0:
            raise AssertionError("batch sizes must be positive")
        if batch_size_increment <= 0:
            raise AssertionError("batch_size_increment must be positive")
        span = global_batch_size - start_batch_size
        if span <= 0:
            raise AssertionError(
                f"start_batch_size={start_batch_size} must be strictly below "
                f"the final global_batch_size={global_batch_size}; use "
                "ConstantNumMicroBatches for a flat schedule")
        if span % batch_size_increment != 0:
            raise AssertionError(
                f"ramp span {span} (= {global_batch_size} - {start_batch_size}) "
                f"must be a multiple of the increment {batch_size_increment}")
        if ramup_samples <= 0:
            raise AssertionError(
                "ramup_samples must be positive for a ramp-up schedule")

        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.global_batch_size = global_batch_size
        self.ramup_samples = ramup_samples
        # Samples spent at each intermediate batch size before stepping up.
        self.rampup_samples_per_increment = (
            ramup_samples / (span // batch_size_increment))

        self.update(0, False)

    def update(self, consumed_samples, consistency_check):
        if consumed_samples > self.ramup_samples:
            gbs = self.global_batch_size
        else:
            steps_taken = int(consumed_samples / self.rampup_samples_per_increment)
            gbs = self.start_batch_size + steps_taken * self.batch_size_increment
            assert gbs <= self.global_batch_size
        if consistency_check and gbs % self.micro_batch_times_data_parallel_size:
            raise AssertionError(
                f"ramped global batch {gbs} is not a whole number of "
                f"micro_batch_size*data_parallel_size="
                f"{self.micro_batch_times_data_parallel_size} granules")
        self.current_global_batch_size = gbs
        self.num_micro_batches = gbs // self.micro_batch_times_data_parallel_size


def build_num_microbatches_calculator(rank, rampup_batch_size,
                                      global_batch_size, micro_batch_size,
                                      data_parallel_size):
    """Factory: constant schedule when ``rampup_batch_size`` is None, else a
    3-tuple ``(start, increment, ramp_samples)`` selects the ramp-up schedule."""
    if rampup_batch_size is None:
        calc = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
        if rank == 0:
            print(f"[apex_tpu] constant microbatch count: {calc.get()}")
        return calc

    if len(rampup_batch_size) != 3:
        raise AssertionError(
            "rampup_batch_size takes exactly (start, increment, ramp_samples)")
    start, increment, ramp_samples = (int(v) for v in rampup_batch_size)
    if rank == 0:
        print(f"[apex_tpu] ramping global batch {start} -> {global_batch_size} "
              f"in steps of {increment}, over {ramp_samples} samples")
    return RampupBatchsizeNumMicroBatches(
        start, increment, ramp_samples,
        global_batch_size, micro_batch_size, data_parallel_size)
