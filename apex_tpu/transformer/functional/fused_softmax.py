"""Fused scale + mask + softmax.

Parity: reference apex/transformer/functional/fused_softmax.py —
``FusedScaleMaskSoftmax`` (164-274) dispatching between
``scaled_upper_triang_masked_softmax_cuda`` (causal),
``scaled_masked_softmax_cuda``, ``scaled_softmax_cuda`` and a torch
fallback, with kernel-availability heuristics (222-246: 16 < sk <= 16384,
divisibility by 4 / batch-per-block), plus ``GenericFusedScaleMaskSoftmax``
(276).

TPU design: scale+mask+softmax is a pure VPU chain that XLA fuses into one
loop; the pure-jnp forms below are both the default lowering and the
parity oracle for the hand-written Pallas kernels in
:mod:`apex_tpu.kernels.softmax` (fused fwd + one-pass custom-VJP bwd,
causal mask derived in-kernel). Dispatch rides the kernel registry's
``softmax`` gate (:mod:`apex_tpu.kernels.registry`): gate off — the
default everywhere but TPU — reproduces today's jnp path bit-identically
*including autodiff gradients*; gate on routes through the kernels. The
availability heuristic is kept (``is_kernel_available``) for API parity
and returns True under the same shape conditions so callers exercising
the reference's dispatch logic behave identically. Numerics:
subtract-max in fp32, optionally compute in bf16 input dtype
(``attn_mask_type`` semantics preserved).
"""

import jax.numpy as jnp

from apex_tpu.kernels import softmax as _kernels
from apex_tpu.transformer.enums import AttnMaskType


def scaled_upper_triang_masked_softmax(x, scale):
    """Causal-masked scaled softmax over [b, sq, sk] or [b, np, sq, sk]
    (reference scaled_upper_triang_masked_softmax_cuda)."""
    if _kernels.usable(scale) and x.ndim == 3:
        _kernels.record("interpret" if _kernels.GATE.interpret
                        else "pallas")
        return _kernels.scaled_upper_triang_masked_softmax(x, float(scale))
    _kernels.record("oracle")
    xf = x.astype(jnp.float32) * scale
    sq, sk = x.shape[-2], x.shape[-1]
    causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
    xf = jnp.where(causal, xf, -10000.0)
    xf = xf - jnp.max(xf, axis=-1, keepdims=True)
    e = jnp.exp(xf)
    e = jnp.where(causal, e, 0.0)
    return (e / jnp.sum(e, axis=-1, keepdims=True)).astype(x.dtype)


def scaled_masked_softmax(x, mask, scale):
    """Arbitrary-mask scaled softmax; mask is 1/True where masked OUT
    (reference scaled_masked_softmax_cuda)."""
    if mask is None:
        return scaled_softmax(x, scale)
    if _kernels.usable(scale):
        _kernels.record("interpret" if _kernels.GATE.interpret
                        else "pallas")
        maskf = jnp.broadcast_to(mask.astype(bool), x.shape) \
            .astype(jnp.float32)
        return _kernels.scaled_masked_softmax(x, maskf, float(scale))
    _kernels.record("oracle")
    xf = x.astype(jnp.float32) * scale
    xf = jnp.where(mask.astype(bool), -10000.0, xf)
    xf = xf - jnp.max(xf, axis=-1, keepdims=True)
    e = jnp.exp(xf)
    e = jnp.where(mask.astype(bool), 0.0, e)
    return (e / jnp.sum(e, axis=-1, keepdims=True)).astype(x.dtype)


def scaled_softmax(x, scale):
    """No-mask scaled softmax (reference scaled_softmax_cuda)."""
    if _kernels.usable(scale):
        _kernels.record("interpret" if _kernels.GATE.interpret
                        else "pallas")
        return _kernels.scaled_softmax(x, float(scale))
    _kernels.record("oracle")
    xf = x.astype(jnp.float32) * scale
    xf = xf - jnp.max(xf, axis=-1, keepdims=True)
    e = jnp.exp(xf)
    return (e / jnp.sum(e, axis=-1, keepdims=True)).astype(x.dtype)


class FusedScaleMaskSoftmax:
    """Dispatching softmax front-end (reference fused_softmax.py:164-274).

    Args mirror the reference: input_in_fp16/bf16, attn_mask_type,
    scaled_masked_softmax_fusion, mask_func, softmax_in_fp32, scale.
    """

    def __init__(self, input_in_fp16, input_in_bf16, attn_mask_type,
                 scaled_masked_softmax_fusion, mask_func, softmax_in_fp32,
                 scale):
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        assert not (input_in_fp16 and input_in_bf16), (
            "both fp16 and bf16 flags cannot be active at the same time.")
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        assert self.scale is None or softmax_in_fp32, (
            "softmax should be in fp32 when scaled")

    def __call__(self, input, mask):
        assert input.ndim == 4  # [b, np, sq, sk]
        if self.is_kernel_available(mask, *input.shape):
            return self.forward_fused_softmax(input, mask)
        return self.forward_torch_softmax(input, mask)

    def is_kernel_available(self, mask, b, np_, sq, sk):
        """Same availability heuristic as the reference
        (fused_softmax.py:222-246); on TPU the fused path is always
        numerically available, but the predicate is preserved so dispatch
        behavior matches."""
        attn_batches = b * np_
        if (self.scaled_masked_softmax_fusion
                and self.input_in_float16
                and 16 < sk <= 16384
                and sq % 4 == 0
                and sk % 4 == 0
                and attn_batches % 4 == 0):
            if 0 <= sk <= 16384:
                batch_per_block = self.get_batch_per_block(sq, sk, b, np_)
                if self.attn_mask_type == AttnMaskType.causal:
                    if attn_batches % batch_per_block == 0:
                        return True
                else:
                    if sq % batch_per_block == 0:
                        return True
        return False

    def forward_fused_softmax(self, input, mask):
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            b, np_, sq, sk = input.shape
            assert sq == sk, "causal mask is only for self attention"
            out = scaled_upper_triang_masked_softmax(
                input.reshape(-1, sq, sk), scale)
            return out.reshape(b, np_, sq, sk)
        if mask is not None:
            return scaled_masked_softmax(input, mask, scale)
        return scaled_softmax(input, scale)

    def forward_torch_softmax(self, input, mask):
        """Unfused fallback (reference fused_softmax.py:248-268)."""
        orig_dtype = input.dtype
        if self.input_in_float16 and self.softmax_in_fp32:
            input = input.astype(jnp.float32)
        if self.scale is not None:
            input = input * self.scale
        mask_output = self.mask_func(input, mask) if mask is not None else input
        probs = jnp.exp(mask_output - jnp.max(mask_output, axis=-1, keepdims=True))
        probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
        if self.input_in_float16 and self.softmax_in_fp32:
            probs = probs.astype(orig_dtype)
        return probs

    @staticmethod
    def get_batch_per_block(sq, sk, b, np_):
        """Mirror of scaled_masked_softmax_cuda.get_batch_per_block
        (reference fused_softmax.py:271-274): pow2 batching heuristic."""
        pow2 = 1 << (sk - 1).bit_length()
        warp_size = pow2 if pow2 < 32 else 32
        batches_per_warp = 2 if pow2 <= 128 else 1
        warps_per_block = 4 * 32 // warp_size
        return warps_per_block * batches_per_warp


class GenericFusedScaleMaskSoftmax(FusedScaleMaskSoftmax):
    """Shape-generic variant (reference fused_softmax.py:276): no shape
    heuristics, always fused."""

    def __init__(self, input_in_fp16, input_in_bf16, mask_func,
                 softmax_in_fp32, scale):
        super().__init__(input_in_fp16, input_in_bf16, AttnMaskType.padding,
                         True, mask_func, softmax_in_fp32, scale)

    def is_kernel_available(self, mask, b, np_, sq, sk):
        return True
