"""Model-parallel topology over a ``jax.sharding.Mesh``.

Parity: reference apex/transformer/parallel_state.py:84-708 —
``initialize_model_parallel`` builds DP / TP / PP / model / embedding /
position-embedding / relative-position-embedding / amax process groups from
a (tp, pp) grid, tracks virtual-pipeline ranks and the encoder-decoder
split rank, and exposes ~40 getters.

TPU design: process groups become mesh axes. The world is
``len(devices) = pp * dp * tp`` laid out as ``Mesh(devices.reshape(pp, dp,
tp), ("pp", "dp", "tp"))`` — tp innermost so TP collectives ride the
fastest ICI links, matching the reference's rank-ordering convention
(parallel_state.py:140-167: "tensor ranks contiguous"). Rank getters return
``lax.axis_index`` when called inside ``shard_map`` (the only place a
per-device rank exists) and process-level values otherwise. Embedding /
amax "groups" are derivable subsets of the pp axis; helpers here expose the
membership logic the schedules need.
"""

from typing import Optional

import jax
import numpy as np
from jax import lax
from jax.sharding import Mesh

# Mesh axis names (the TPU analog of the 8 group types).
DATA_PARALLEL_AXIS = "dp"
TENSOR_PARALLEL_AXIS = "tp"
PIPELINE_PARALLEL_AXIS = "pp"
CONTEXT_PARALLEL_AXIS = "cp"  # long-context axis; no reference equivalent
EXPERT_PARALLEL_AXIS = "ep"  # MoE expert axis; no reference equivalent

_MESH: Optional[Mesh] = None
_CONTEXT_PARALLEL_WORLD_SIZE: Optional[int] = None
_EXPERT_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_TENSOR_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_DATA_PARALLEL_WORLD_SIZE: Optional[int] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK: Optional[int] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_PIPELINE_MODEL_PARALLEL_SPLIT_RANK: Optional[int] = None
# Host-level rank overrides used by eager helpers/tests.
_EXPLICIT_TP_RANK: Optional[int] = None
_EXPLICIT_PP_RANK: Optional[int] = None


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    context_parallel_size_: int = 1,
    expert_model_parallel_size_: int = 1,
    *,
    devices=None,
    default_backend: Optional[str] = None,
    p2p_backend: Optional[str] = None,
) -> Mesh:
    """Build the global mesh (reference parallel_state.py:84-331).

    ``default_backend``/``p2p_backend`` are accepted for API parity (the
    reference selects nccl/ucc; XLA picks ICI/DCN automatically).
    Returns the mesh; also installs it globally so the getters work.
    """
    global _MESH, _TENSOR_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_WORLD_SIZE, _DATA_PARALLEL_WORLD_SIZE
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK, _CONTEXT_PARALLEL_WORLD_SIZE
    global _EXPERT_MODEL_PARALLEL_WORLD_SIZE

    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    world_size = devices.size
    tp = tensor_model_parallel_size_
    pp = pipeline_model_parallel_size_
    cp = context_parallel_size_
    ep = expert_model_parallel_size_
    if world_size % (tp * pp * cp * ep) != 0:
        raise RuntimeError(
            f"world_size ({world_size}) is not divisible by "
            f"tensor_model_parallel_size ({tp}) x pipeline_model_parallel_size ({pp})"
            f" x context_parallel_size ({cp})"
            f" x expert_model_parallel_size ({ep})")
    dp = world_size // (tp * pp * cp * ep)

    if virtual_pipeline_model_parallel_size_ is not None:
        if pp < 2:
            raise RuntimeError(
                "pipeline-model-parallel size should be greater than 2 with "
                "interleaved schedule")
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = 0
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = (
            virtual_pipeline_model_parallel_size_)
    else:
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = pipeline_model_parallel_split_rank_

    # Axis order (outer to inner): pp, dp, ep, cp, tp. ep subdivides the
    # data-parallel block (Megatron-core's expert-data decomposition: the
    # dp*ep replicas of dense params are the dp replicas of each expert
    # shard); cp sits between dp and tp so sequence blocks ring on fast
    # links; tp innermost owns the fastest ICI hops. Size-1 ep/cp axes are
    # omitted so existing 3-axis callers see an unchanged mesh.
    dims = [(pp, PIPELINE_PARALLEL_AXIS), (dp, DATA_PARALLEL_AXIS)]
    if ep > 1:
        dims.append((ep, EXPERT_PARALLEL_AXIS))
    if cp > 1:
        dims.append((cp, CONTEXT_PARALLEL_AXIS))
    dims.append((tp, TENSOR_PARALLEL_AXIS))
    mesh_devices = devices.reshape(*[d for d, _ in dims])
    _MESH = Mesh(mesh_devices, tuple(name for _, name in dims))
    _TENSOR_MODEL_PARALLEL_WORLD_SIZE = tp
    _PIPELINE_MODEL_PARALLEL_WORLD_SIZE = pp
    _DATA_PARALLEL_WORLD_SIZE = dp
    _CONTEXT_PARALLEL_WORLD_SIZE = cp
    _EXPERT_MODEL_PARALLEL_WORLD_SIZE = ep
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("model parallel mesh is not initialized")
    return _MESH


def destroy_model_parallel():
    """Tear down global state (reference parallel_state.py:673-704)."""
    global _MESH, _TENSOR_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_WORLD_SIZE, _DATA_PARALLEL_WORLD_SIZE
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK, _EXPLICIT_TP_RANK, _EXPLICIT_PP_RANK
    global _CONTEXT_PARALLEL_WORLD_SIZE, _EXPERT_MODEL_PARALLEL_WORLD_SIZE
    _MESH = None
    _TENSOR_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _DATA_PARALLEL_WORLD_SIZE = None
    _CONTEXT_PARALLEL_WORLD_SIZE = None
    _EXPERT_MODEL_PARALLEL_WORLD_SIZE = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = None
    _EXPLICIT_TP_RANK = None
    _EXPLICIT_PP_RANK = None


# ---------------------------------------------------------------------------
# world sizes
# ---------------------------------------------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    if _TENSOR_MODEL_PARALLEL_WORLD_SIZE is None:
        return 1
    return _TENSOR_MODEL_PARALLEL_WORLD_SIZE


def get_pipeline_model_parallel_world_size() -> int:
    if _PIPELINE_MODEL_PARALLEL_WORLD_SIZE is None:
        return 1
    return _PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def get_data_parallel_world_size() -> int:
    if _DATA_PARALLEL_WORLD_SIZE is None:
        return 1
    return _DATA_PARALLEL_WORLD_SIZE


def get_context_parallel_world_size() -> int:
    if _CONTEXT_PARALLEL_WORLD_SIZE is None:
        return 1
    return _CONTEXT_PARALLEL_WORLD_SIZE


def get_context_parallel_rank():
    return _axis_rank(CONTEXT_PARALLEL_AXIS, None)


def get_expert_model_parallel_world_size() -> int:
    if _EXPERT_MODEL_PARALLEL_WORLD_SIZE is None:
        return 1
    return _EXPERT_MODEL_PARALLEL_WORLD_SIZE


def get_expert_model_parallel_rank():
    return _axis_rank(EXPERT_PARALLEL_AXIS, None)


def get_data_parallel_axes():
    """Mesh axes spanning the full data-parallel replica set for *dense*
    (non-expert) params. With expert parallelism the ep axis borrows
    devices from dp, so dense-grad sync must reduce over both; expert
    params replicate over dp alone (sync them over just 'dp')."""
    if get_expert_model_parallel_world_size() > 1:
        return (DATA_PARALLEL_AXIS, EXPERT_PARALLEL_AXIS)
    return (DATA_PARALLEL_AXIS,)


def get_model_parallel_world_size() -> int:
    return get_tensor_model_parallel_world_size() * get_pipeline_model_parallel_world_size()


# ---------------------------------------------------------------------------
# ranks — lax.axis_index inside shard_map, host override / 0 outside
# ---------------------------------------------------------------------------

def _axis_rank(axis_name: str, explicit: Optional[int]):
    if explicit is not None:
        return explicit
    try:
        return lax.axis_index(axis_name)
    except Exception:
        return 0


def set_tensor_model_parallel_rank(rank: Optional[int]):
    """Host-level override (used by eager tests; reference
    parallel_state.py set_tensor_model_parallel_rank)."""
    global _EXPLICIT_TP_RANK
    _EXPLICIT_TP_RANK = rank


def set_pipeline_model_parallel_rank(rank: Optional[int]):
    global _EXPLICIT_PP_RANK
    _EXPLICIT_PP_RANK = rank


def set_tensor_model_parallel_world_size(size: Optional[int]):
    global _TENSOR_MODEL_PARALLEL_WORLD_SIZE
    _TENSOR_MODEL_PARALLEL_WORLD_SIZE = size


def set_pipeline_model_parallel_world_size(size: Optional[int]):
    global _PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    _PIPELINE_MODEL_PARALLEL_WORLD_SIZE = size


def get_tensor_model_parallel_rank():
    return _axis_rank(TENSOR_PARALLEL_AXIS, _EXPLICIT_TP_RANK)


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPELINE_PARALLEL_AXIS, _EXPLICIT_PP_RANK)


def get_data_parallel_rank():
    return _axis_rank(DATA_PARALLEL_AXIS, None)


def get_tensor_model_parallel_src_rank():
    """Rank 0 of the local TP group (reference parallel_state.py:612-620).
    On a mesh this is simply tp-coordinate 0."""
    return 0


# ---------------------------------------------------------------------------
# pipeline-stage predicates (reference parallel_state.py:430-520)
# ---------------------------------------------------------------------------

def is_pipeline_first_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        if (_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE is not None
                and _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK != 0):
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        vws = _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
        if vws is not None and _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK != vws - 1:
            return False
    return get_pipeline_model_parallel_rank() == (
        get_pipeline_model_parallel_world_size() - 1)


def is_pipeline_stage_before_split(rank=None):
    """Encoder-decoder split support (reference parallel_state.py:469-486)."""
    if get_pipeline_model_parallel_world_size() == 1:
        return True
    if rank is None:
        rank = get_pipeline_model_parallel_rank()
    if _PIPELINE_MODEL_PARALLEL_SPLIT_RANK is None:
        return True
    return rank < _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def is_pipeline_stage_after_split(rank=None):
    if get_pipeline_model_parallel_world_size() == 1:
        return True
    if rank is None:
        rank = get_pipeline_model_parallel_rank()
    if _PIPELINE_MODEL_PARALLEL_SPLIT_RANK is None:
        return True
    return rank >= _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def is_pipeline_stage_at_split():
    rank = get_pipeline_model_parallel_rank()
    return is_pipeline_stage_before_split(rank) and is_pipeline_stage_after_split(rank + 1)


def get_pipeline_model_parallel_split_rank():
    return _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def set_pipeline_model_parallel_split_rank(rank):
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = rank


# virtual pipeline (interleaved schedule) bookkeeping -----------------------

def get_virtual_pipeline_model_parallel_rank():
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK


def set_virtual_pipeline_model_parallel_rank(rank):
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = rank


def get_virtual_pipeline_model_parallel_world_size():
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def set_virtual_pipeline_model_parallel_world_size(size):
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = size


# pipeline neighbours (reference parallel_state.py:622-646) -----------------

def get_pipeline_model_parallel_next_rank():
    rank = get_pipeline_model_parallel_rank()
    return (rank + 1) % get_pipeline_model_parallel_world_size()


def get_pipeline_model_parallel_prev_rank():
    rank = get_pipeline_model_parallel_rank()
    return (rank - 1) % get_pipeline_model_parallel_world_size()


# embedding-group membership (reference parallel_state.py:243-331) ----------

def is_rank_in_embedding_group(ignore_virtual: bool = False):
    """True on the first and last pipeline stages (tied-embedding grad sync)."""
    return bool(is_pipeline_first_stage(ignore_virtual)) or bool(
        is_pipeline_last_stage(ignore_virtual))


def is_rank_in_position_embedding_group():
    return bool(is_pipeline_first_stage(ignore_virtual=True))


def get_rank_info():
    """(dp, tp, pp, vpp) tuple for logging (reference apex/__init__.py:36-41)."""
    return (
        int(get_data_parallel_rank()) if _EXPLICIT_TP_RANK is None else 0,
        int(_EXPLICIT_TP_RANK or 0),
        int(_EXPLICIT_PP_RANK or 0),
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK,
    )


# amax-reduction groups (fp8 bookkeeping, reference parallel_state.py:204-216)

def get_amax_reduction_axes():
    """fp8 amax reductions span the full model-parallel block: tp x pp."""
    return (TENSOR_PARALLEL_AXIS, PIPELINE_PARALLEL_AXIS)
