"""TP data broadcast.

Parity: reference apex/transformer/tensor_parallel/data.py:80-122
``broadcast_data`` — broadcast a keyed dict of tensors from tp-rank-0
(sizes first, then one flattened payload).

TPU design: under SPMD the host feeds identical data to every device in a
tp group by construction (inputs are replicated over the tp mesh axis), so
broadcast is an assert-and-cast. Inside shard_map an explicit collective
variant is provided for parity with rank-divergent callers.
"""

import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS


def broadcast_data(keys, data, datatype, axis_name=TENSOR_PARALLEL_AXIS):
    """Broadcast ``{key: array}`` from tp-rank 0 to the tp group.

    Inside shard_map this psums the rank-0 copy (a true broadcast); outside
    it casts and returns (data is already replicated by the host feed).
    """
    out = {}
    for k in keys:
        v = jnp.asarray(data[k], datatype)
        try:
            rank = lax.axis_index(axis_name)
            masked = jnp.where(rank == 0, v, jnp.zeros_like(v))
            v = lax.psum(masked, axis_name)
        except Exception:
            pass
        out[k] = v
    return out
