"""Forward/backward-paired TP collectives.

Parity: reference apex/transformer/tensor_parallel/mappings.py:31-312 —
``_CopyToModelParallelRegion`` (identity fwd / allreduce bwd),
``_ReduceFromModelParallelRegion`` (allreduce fwd / identity bwd),
``_ScatterToModelParallelRegion`` / ``_GatherFromModelParallelRegion``
(last-dim split/gather) and the sequence-parallel first-dim variants
(213-268).

TPU design: each region op is a ``jax.custom_vjp`` over ``lax`` collectives
bound to the 'tp' mesh axis inside ``shard_map``. XLA lowers these to ICI
all-reduce / all-gather / reduce-scatter.
"""

import functools

import jax
from jax import lax

from apex_tpu.telemetry import comm as _telemetry_comm
from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS


# -- raw helpers (reference mappings.py:31-138) -----------------------------

def _axis_size(axis_name) -> int:
    """Size of the axis, or 1 when it is not bound (single-chip eager/jit
    use outside shard_map — the reference likewise no-ops when the TP group
    has world size 1, mappings.py:33-36)."""
    try:
        return lax.axis_size(axis_name)
    except Exception:
        return 1


# TP collectives are accounted like every DP collective
# (telemetry/comm.py record_collective, trace-time): full-width
# activation payloads (vmap batch axes included — traced_elements)
# tagged with the model axis name, so a 2-D
# (data, model) report separates compressed DP grad bytes from fp32
# TP psum volume per axis.

def _reduce(x, axis_name=TENSOR_PARALLEL_AXIS):
    if _axis_size(axis_name) == 1:
        return x
    _telemetry_comm.record_collective(
        "psum", elements=_telemetry_comm.traced_elements(x),
        dtype=x.dtype, axis_name=axis_name)
    return lax.psum(x, axis_name)


def _split(x, dim, axis_name=TENSOR_PARALLEL_AXIS):
    size = _axis_size(axis_name)
    if size == 1:
        return x
    rank = lax.axis_index(axis_name)
    shard = x.shape[dim] // size
    return lax.dynamic_slice_in_dim(x, rank * shard, shard, axis=dim)


def _gather(x, dim, axis_name=TENSOR_PARALLEL_AXIS):
    size = _axis_size(axis_name)
    if size == 1:
        return x
    _telemetry_comm.record_collective(
        "all_gather", elements=_telemetry_comm.traced_elements(x),
        dtype=x.dtype, axis_name=axis_name)
    return lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _reduce_scatter(x, dim, axis_name=TENSOR_PARALLEL_AXIS):
    size = _axis_size(axis_name)
    if size == 1:
        return x
    _telemetry_comm.record_collective(
        "psum_scatter", elements=_telemetry_comm.traced_elements(x),
        dtype=x.dtype, axis_name=axis_name)
    return lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


def _region_op(fwd_fn, bwd_fn):
    """Build a custom-vjp op from forward/backward transforms."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def op(x, axis_name=TENSOR_PARALLEL_AXIS):
        return fwd_fn(x, axis_name)

    def op_fwd(x, axis_name):
        return fwd_fn(x, axis_name), None

    def op_bwd(axis_name, _, g):
        return (bwd_fn(g, axis_name),)

    op.defvjp(op_fwd, op_bwd)
    return op


# -- region ops (reference mappings.py:141-268) -----------------------------

# identity fwd / allreduce bwd (mappings.py:141 _CopyToModelParallelRegion)
copy_to_tensor_model_parallel_region = _region_op(
    lambda x, ax: x, lambda g, ax: _reduce(g, ax))

# allreduce fwd / identity bwd (mappings.py:159 _ReduceFromModelParallelRegion)
reduce_from_tensor_model_parallel_region = _region_op(
    lambda x, ax: _reduce(x, ax), lambda g, ax: g)

# split last dim fwd / gather bwd (mappings.py:177 _ScatterToModelParallelRegion)
scatter_to_tensor_model_parallel_region = _region_op(
    lambda x, ax: _split(x, -1, ax), lambda g, ax: _gather(g, -1, ax))

# gather last dim fwd / split bwd (mappings.py:195 _GatherFromModelParallelRegion)
gather_from_tensor_model_parallel_region = _region_op(
    lambda x, ax: _gather(x, -1, ax), lambda g, ax: _split(g, -1, ax))

# SP: split first dim fwd / gather bwd (mappings.py:213 _ScatterToSequenceParallelRegion)
scatter_to_sequence_parallel_region = _region_op(
    lambda x, ax: _split(x, 0, ax), lambda g, ax: _gather(g, 0, ax))

# SP: reduce-scatter first dim fwd / gather bwd
# (mappings.py:253 _ReduceScatterToSequenceParallelRegion)
reduce_scatter_to_sequence_parallel_region = _region_op(
    lambda x, ax: _reduce_scatter(x, 0, ax), lambda g, ax: _gather(g, 0, ax))


# SP gather needs the tensor_parallel_output_grad switch
# (mappings.py:231 _GatherFromSequenceParallelRegion).

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(x, tensor_parallel_output_grad=True,
                                         axis_name=TENSOR_PARALLEL_AXIS):
    return _gather(x, 0, axis_name)


def _gfspr_fwd(x, tensor_parallel_output_grad, axis_name):
    return _gather(x, 0, axis_name), None


def _gfspr_bwd(tensor_parallel_output_grad, axis_name, _, g):
    if tensor_parallel_output_grad:
        return (_reduce_scatter(g, 0, axis_name),)
    return (_split(g, 0, axis_name),)


gather_from_sequence_parallel_region.defvjp(_gfspr_fwd, _gfspr_bwd)
