"""TP RNG discipline + activation checkpointing.

Parity: reference apex/transformer/tensor_parallel/random.py —
``CudaRNGStatesTracker`` (124-196), ``model_parallel_cuda_manual_seed``
(204: tp seed = seed + 2718 + tp_rank), ``checkpoint`` with RNG restore
(237-311).

TPU design: JAX RNG is functional, so "states" are keys. The tracker maps
names to keys; ``fork`` yields a fresh per-use key split from the named
stream — the same duplicated-vs-partitioned discipline without mutable
device state. Activation checkpointing is ``jax.checkpoint``
(rematerialization), which replays RNG correctly by construction — the
manual state save/restore of the reference is unnecessary.
"""

import contextlib

import jax

from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


def model_parallel_rng_tracker_name():
    return _MODEL_PARALLEL_RNG_TRACKER_NAME


class RNGStatesTracker:
    """Named RNG streams (reference CudaRNGStatesTracker, random.py:124-196)."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed_or_key):
        if name in self.states_:
            raise Exception("RNG state {} already exists".format(name))
        if isinstance(seed_or_key, int):
            key = jax.random.PRNGKey(seed_or_key)
        else:
            key = seed_or_key
        self.states_[name] = key

    @contextlib.contextmanager
    def fork(self, name=_MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Yield a fresh key from the named stream and advance it."""
        if name not in self.states_:
            raise Exception("RNG state {} is not added".format(name))
        key, next_key = jax.random.split(self.states_[name])
        self.states_[name] = next_key
        yield key


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_xla_manual_seed(seed: int):
    """Seed the duplicated and tp-partitioned streams.

    Parity: reference random.py:204 — default stream gets ``seed``;
    the model-parallel stream gets ``seed + 2718 + tp_rank``. The rank is
    folded in at *use* time (inside shard_map) via ``fold_in`` so one host
    call serves all devices.
    """
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add("default", seed)
    _RNG_STATE_TRACKER.add(_MODEL_PARALLEL_RNG_TRACKER_NAME, seed + 2718)


# Name kept for drop-in parity.
model_parallel_cuda_manual_seed = model_parallel_xla_manual_seed


def fold_in_tp_rank(key, axis_name=TENSOR_PARALLEL_AXIS):
    """Per-device partitioned key: fold the tp rank into a base key."""
    try:
        rank = jax.lax.axis_index(axis_name)
    except Exception:
        rank = 0
    return jax.random.fold_in(key, rank)


def checkpoint(function, distribute_saved_activations=False, *args, **kwargs):
    """Activation checkpointing (recompute).

    Parity: reference random.py:237-311 ``CheckpointFunction``. Maps to
    ``jax.checkpoint``; ``distribute_saved_activations`` (sharding the
    stashed input across TP ranks) is subsumed by XLA's SPMD partitioner —
    saved residuals inside shard_map are already per-device shards.
    """
    return jax.checkpoint(function)(*args, **kwargs)
