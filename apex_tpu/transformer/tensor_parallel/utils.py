"""TP utility helpers.

Parity: reference apex/transformer/tensor_parallel/utils.py
(``split_tensor_along_last_dim``, ``divide``, ``VocabUtility``).
"""

import jax.numpy as jnp


def ensure_divisibility(numerator, denominator):
    assert numerator % denominator == 0, "{} is not divisible by {}".format(
        numerator, denominator)


def divide(numerator, denominator):
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(tensor, num_partitions, contiguous_split_chunks=False):
    # contiguous_split_chunks is accepted for API parity; jnp.split output
    # is always contiguous (no torch-style views on TPU).
    ensure_divisibility(tensor.shape[-1], num_partitions)
    return jnp.split(tensor, num_partitions, axis=-1)


class VocabUtility:
    """Vocab range math (reference tensor_parallel/utils.py VocabUtility)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(per_partition_vocab_size, rank, world_size):
        index_f = rank * per_partition_vocab_size
        index_l = index_f + per_partition_vocab_size
        return index_f, index_l

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size, rank, world_size):
        per_partition_vocab_size = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size, rank, world_size)
