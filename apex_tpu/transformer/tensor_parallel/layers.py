"""Megatron-style tensor-parallel layers.

Parity: reference apex/transformer/tensor_parallel/layers.py:174-813 —
``VocabParallelEmbedding`` (masked local lookup + allreduce),
``ColumnParallelLinear`` (460), ``RowParallelLinear`` (645),
``LinearWithGradAccumulationAndAsyncCommunication`` (279-438: async grad
allreduce, sequence-parallel all-gather fwd + reduce-scatter bwd, fused
wgrad accumulation), and the param partition-attribute helpers (70-107).

TPU design: layers are flax modules holding the *local shard* of each
weight; they run inside ``shard_map`` over the 'tp' mesh axis. The
forward/backward collective pairing is expressed through the custom-vjp
region ops in :mod:`mappings`; XLA's async collectives + latency-hiding
scheduler provide the comm/compute overlap the reference hand-schedules.
The fused wgrad-accum GEMM (fused_weight_gradient_mlp_cuda,
layers.py:415-429) is unnecessary: XLA accumulates the weight-grad einsum
directly into the gradient buffer with buffer donation.

Partitioned-vs-duplicated init parity (reference random.py:204-236): weight
shards are initialized from a per-rank key folded with the tp rank, so
TP=n layers statistically match a TP=1 layer sliced n ways.
"""

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import (
    TENSOR_PARALLEL_AXIS,
    get_tensor_model_parallel_world_size,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import (  # noqa: F401
    # VocabUtility re-exported for reference-apex layers API parity
    VocabUtility,
    divide,
)

_MODEL_PARALLEL_ATTRIBUTE_DEFAULTS = {
    "tensor_model_parallel": False,
    "partition_dim": -1,
    "partition_stride": 1,
}


# -- param attribute helpers (reference layers.py:70-107) -------------------
# JAX arrays are immutable values without attributes; partition metadata
# lives in a side dict pytree produced by ``Module.param_attributes``.

def set_tensor_model_parallel_attributes(attrs: dict, is_parallel: bool,
                                         dim: int, stride: int) -> dict:
    attrs.update({"tensor_model_parallel": is_parallel, "partition_dim": dim,
                  "partition_stride": stride})
    return attrs


def set_defaults_if_not_set_tensor_model_parallel_attributes(attrs: dict) -> dict:
    for k, v in _MODEL_PARALLEL_ATTRIBUTE_DEFAULTS.items():
        attrs.setdefault(k, v)
    return attrs


def copy_tensor_model_parallel_attributes(dst: dict, src: dict) -> dict:
    for k in _MODEL_PARALLEL_ATTRIBUTE_DEFAULTS:
        if k in src:
            dst[k] = src[k]
    return dst


def _tp_rank_key(key):
    """Fold the tp rank into an RNG key for partitioned init (the TPU analog
    of CudaRNGStatesTracker's tp-offset seed, reference random.py:204)."""
    try:
        rank = lax.axis_index(TENSOR_PARALLEL_AXIS)
    except Exception:
        rank = 0
    return jax.random.fold_in(key, rank)


def _partitioned_init(init_fn):
    def wrapped(key, shape, dtype):
        return init_fn(_tp_rank_key(key), shape, dtype)
    return wrapped


def linear_with_grad_accumulation_and_async_allreduce(
        input, weight, bias=None, gradient_accumulation_fusion=False,
        async_grad_allreduce=True, sequence_parallel_enabled=False,
        axis_name=TENSOR_PARALLEL_AXIS):
    """Functional core of ColumnParallelLinear
    (reference layers.py:279-438).

    - sequence_parallel_enabled: all-gather the seq-sharded input on entry
      (fwd) / reduce-scatter the input grad on exit (bwd).
    - else async_grad_allreduce: identity fwd / allreduce of input grad bwd.
    The flags select collectives; accumulation fusion is XLA's job.
    """
    if sequence_parallel_enabled:
        total_input = gather_from_sequence_parallel_region(input, True, axis_name)
    elif async_grad_allreduce:
        total_input = copy_to_tensor_model_parallel_region(input, axis_name)
    else:
        total_input = input
    out = jnp.matmul(total_input, weight, preferred_element_type=jnp.float32)
    out = out.astype(input.dtype)
    if bias is not None:
        out = out + bias
    return out


class ColumnParallelLinear(nn.Module):
    """Linear with output-dim partitioning: Y = XA + b, A = [A_1 .. A_p]
    (reference layers.py:460). Holds the local shard A_i of shape
    [input_size, output_size / tp]."""

    input_size: int
    output_size: int
    bias: bool = True
    gather_output: bool = True
    init_method: Callable = nn.initializers.lecun_normal()
    stride: int = 1
    keep_master_weight_for_test: bool = False
    skip_bias_add: bool = False
    no_async_tensor_model_parallel_allreduce: bool = False
    params_dtype: Any = jnp.float32
    use_cpu_initialization: bool = False
    gradient_accumulation_fusion: bool = False
    sequence_parallel_enabled: bool = False
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    def __call__(self, input_):
        world = get_tensor_model_parallel_world_size()
        out_per_partition = divide(self.output_size, world)
        weight = self.param(
            "weight", _partitioned_init(self.init_method),
            (self.input_size, out_per_partition), self.params_dtype)
        b = (self.param("bias", nn.initializers.zeros, (out_per_partition,),
                        self.params_dtype) if self.bias else None)
        bias_for_matmul = None if self.skip_bias_add else b
        out_parallel = linear_with_grad_accumulation_and_async_allreduce(
            input_, weight, bias_for_matmul,
            gradient_accumulation_fusion=self.gradient_accumulation_fusion,
            async_grad_allreduce=not self.no_async_tensor_model_parallel_allreduce,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            axis_name=self.axis_name)
        if self.gather_output:
            assert not self.sequence_parallel_enabled
            output = gather_from_tensor_model_parallel_region(
                out_parallel, self.axis_name)
        else:
            output = out_parallel
        if self.skip_bias_add:
            return output, b
        return output


class RowParallelLinear(nn.Module):
    """Linear with input-dim partitioning: Y = XA, A = [A_1; ..; A_p]
    (reference layers.py:645). Holds the local shard of shape
    [input_size / tp, output_size]; output is allreduced (or
    reduce-scattered under sequence parallelism)."""

    input_size: int
    output_size: int
    bias: bool = True
    input_is_parallel: bool = False
    init_method: Callable = nn.initializers.lecun_normal()
    stride: int = 1
    keep_master_weight_for_test: bool = False
    skip_bias_add: bool = False
    params_dtype: Any = jnp.float32
    use_cpu_initialization: bool = False
    gradient_accumulation_fusion: bool = False
    sequence_parallel_enabled: bool = False
    axis_name: str = TENSOR_PARALLEL_AXIS

    @nn.compact
    def __call__(self, input_):
        world = get_tensor_model_parallel_world_size()
        in_per_partition = divide(self.input_size, world)
        weight = self.param(
            "weight", _partitioned_init(self.init_method),
            (in_per_partition, self.output_size), self.params_dtype)
        b = (self.param("bias", nn.initializers.zeros, (self.output_size,),
                        self.params_dtype) if self.bias else None)
        if self.input_is_parallel:
            input_parallel = input_
        else:
            assert not self.sequence_parallel_enabled
            input_parallel = scatter_to_tensor_model_parallel_region(
                input_, self.axis_name)
        out_parallel = jnp.matmul(input_parallel, weight,
                                  preferred_element_type=jnp.float32)
        out_parallel = out_parallel.astype(input_.dtype)
        if self.sequence_parallel_enabled:
            output_ = reduce_scatter_to_sequence_parallel_region(
                out_parallel, self.axis_name)
        else:
            output_ = reduce_from_tensor_model_parallel_region(
                out_parallel, self.axis_name)
        if self.skip_bias_add:
            return output_, b
        if b is not None:
            output_ = output_ + b
        return output_


class VocabParallelEmbedding(nn.Module):
    """Embedding with vocab-dim partitioning (reference layers.py:174-276):
    masked local lookup followed by an allreduce over the tp axis.
    ``attend`` projects hidden states back onto the vocab shard — the
    tied LM head (reference parallel_lm_logits uses the embedding table).
    """

    num_embeddings: int
    embedding_dim: int
    init_method: Callable = nn.initializers.normal(stddev=0.02)
    params_dtype: Any = jnp.float32
    use_cpu_initialization: bool = False
    axis_name: str = TENSOR_PARALLEL_AXIS

    def setup(self):
        world = get_tensor_model_parallel_world_size()
        per_partition = divide(self.num_embeddings, world)
        self.weight = self.param(
            "weight", _partitioned_init(self.init_method),
            (per_partition, self.embedding_dim), self.params_dtype)

    def attend(self, h):
        """[..., hidden] @ table.T -> vocab-parallel logits
        [..., vocab/tp] (fp32 accumulation)."""
        return jnp.einsum("...h,vh->...v", h, self.weight.astype(h.dtype),
                          preferred_element_type=jnp.float32)

    def __call__(self, input_):
        world = get_tensor_model_parallel_world_size()
        per_partition = divide(self.num_embeddings, world)
        weight = self.weight
        if world > 1:
            try:
                rank = lax.axis_index(self.axis_name)
            except Exception:
                rank = 0
            start = rank * per_partition
            masked = input_ - start
            in_range = (input_ >= start) & (input_ < start + per_partition)
            masked = jnp.where(in_range, masked, 0)
            out = weight[masked]
            out = jnp.where(in_range[..., None], out, 0.0)
            out = reduce_from_tensor_model_parallel_region(out, self.axis_name)
        else:
            out = weight[input_]
        return out


# -- sequence-parallel gradient sync ----------------------------------------
# The reference tags tp-replicated params with ``sequence_parallel_enabled``
# and allreduces their grads over the TP group (layers.py sequence_parallel
# attr + transformer/layers/layer_norm.py:26-99). JAX param pytrees carry no
# attributes, so the tagging is a path predicate: True for params whose
# forward consumed only the local sequence shard (layernorms, position
# embeddings, row-parallel biases added after the reduce-scatter) and whose
# grads are therefore partial sums over the tp axis.

def allreduce_sequence_parallel_grads(grads, is_sequence_parallel_param,
                                      axis_name=TENSOR_PARALLEL_AXIS):
    """psum the grads of seq-partial params over the tp axis.

    ``is_sequence_parallel_param(path: str) -> bool`` receives the
    '/'-joined param path. Call inside shard_map when
    ``sequence_parallel_enabled`` models train with tp > 1.
    """

    def fix(path, g):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if is_sequence_parallel_param(name):
            return lax.psum(g, axis_name)
        return g

    return jax.tree_util.tree_map_with_path(fix, grads)
