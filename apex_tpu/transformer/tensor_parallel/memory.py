"""Preallocated memory buffers for activation checkpointing.

Parity: reference apex/transformer/tensor_parallel/memory.py —
``MemoryBuffer`` (37-133): one preallocated flat tensor handed out as
zero-copy views; ``RingMemBuffer`` (135-151): a rotating ring of them.

TPU design note: XLA owns device allocation, so these buffers manage
*host-side* staging storage (numpy) — useful for checkpoint IO and the
data path. On-device "preallocation" is expressed with buffer donation in
jit, not with manual pools; the classes keep the reference API for code
that expects it.
"""

import numpy as np


class MemoryBuffer:
    """A contiguous preallocated buffer that hands out shaped views
    (reference memory.py:37-133)."""

    def __init__(self, name, numel, dtype, track_usage=False):
        self.name = name
        self.numel = int(numel)
        self.dtype = np.dtype(dtype)
        self.data = np.zeros(self.numel, dtype=self.dtype)
        # usage tracking (reference memory.py:60-70)
        self.track_usage = track_usage
        if track_usage:
            self.in_use_value = 0.0
            self.total_value = 0.0
        self._start = 0

    def reset(self):
        self._start = 0

    def is_in_use(self):
        return self._start > 0

    def add(self, shape):
        """Allocate a zero-copy view of ``shape`` (reference ``add``)."""
        numel = int(np.prod(shape))
        new_start = self._start + numel
        if new_start > self.numel:
            raise MemoryError(
                f"MemoryBuffer {self.name}: out of space "
                f"({new_start} > {self.numel} elements)")
        view = self.data[self._start:new_start].reshape(shape)
        if self.track_usage:
            self.in_use_value = float(new_start)
            self.total_value = max(self.total_value, float(new_start))
        self._start = new_start
        return view

    def get_data(self):
        return self.data

    def print_average_usage(self):
        if not self.track_usage:
            return
        if self.total_value == 0:
            print(f"> memory buffer {self.name}: unused")
            return
        print(f"> memory buffer {self.name}: peak usage "
              f"{100.0 * self.total_value / self.numel:.1f}%")


class RingMemBuffer:
    """Ring of ``num_buffers`` MemoryBuffers handed out round-robin
    (reference memory.py:135-151)."""

    def __init__(self, name, num_buffers, numel, dtype, track_usage=False):
        self.num_buffers = num_buffers
        self.buffers = [
            MemoryBuffer(f"{name} {i}", numel, dtype, track_usage)
            for i in range(num_buffers)]
        self._index = -1

    def get_next_buffer(self):
        self._index = (self._index + 1) % self.num_buffers
        buf = self.buffers[self._index]
        if buf.is_in_use():
            raise RuntimeError("buffer is already in use")
        return buf
