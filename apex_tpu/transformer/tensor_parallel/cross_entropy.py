"""Vocab-parallel cross entropy.

Parity: reference apex/transformer/tensor_parallel/cross_entropy.py:23-132 —
max-allreduce over the tp axis, masked local logit lookup, sum-allreduce of
exp, optional label smoothing.

TPU design: a plain differentiable jnp composition using ``lax.pmax`` /
``lax.psum`` on the tp axis — jax autodiff reproduces the reference's
hand-written backward (softmax minus one-hot) and XLA fuses it.
"""

import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.parallel_state import TENSOR_PARALLEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    reduce_from_tensor_model_parallel_region as _allreduce,
)


def vocab_parallel_cross_entropy(vocab_parallel_logits, target,
                                 label_smoothing=0.0,
                                 axis_name=TENSOR_PARALLEL_AXIS):
    """Cross entropy over vocab-sharded logits.

    Args:
      vocab_parallel_logits: [..., vocab/tp] local logit shard.
      target: [...] int labels in the *global* vocab.
    Returns per-token loss [...].
    """
    try:
        world = lax.axis_size(axis_name)
        rank = lax.axis_index(axis_name)
    except Exception:
        world, rank = 1, 0

    logits = vocab_parallel_logits.astype(jnp.float32)
    local_max = jnp.max(lax.stop_gradient(logits), axis=-1)
    if world > 1:
        global_max = lax.pmax(local_max, axis_name)
    else:
        global_max = local_max
    # The max shift is for numerical stability only; it must not contribute
    # to the gradient (and lax.pmax has no transpose rule).
    logits = logits - lax.stop_gradient(global_max)[..., None]

    partition_vocab_size = logits.shape[-1]
    start = rank * partition_vocab_size
    masked_target = target - start
    in_range = (target >= start) & (target < start + partition_vocab_size)
    masked_target = jnp.where(in_range, masked_target, 0)
    predicted = jnp.take_along_axis(logits, masked_target[..., None], axis=-1)[..., 0]
    predicted = jnp.where(in_range, predicted, 0.0)

    exp_sum = jnp.sum(jnp.exp(logits), axis=-1)
    if world > 1:
        # Allreduce with *identity backward* (Megatron convention: every tp
        # rank re-derives the loss from the reduced value and backprops its
        # own shard exactly once — reference cross_entropy.py:58-66 uses
        # torch.distributed.all_reduce whose autograd is identity).
        predicted = _allreduce(predicted, axis_name)
        exp_sum = _allreduce(exp_sum, axis_name)
    loss = jnp.log(exp_sum) - predicted

    if label_smoothing > 0:
        # Smoothed loss (reference cross_entropy.py:92-113): mix in the mean
        # log-prob over the full vocab.
        vocab_size = partition_vocab_size * world
        smoothing = label_smoothing * vocab_size / (vocab_size - 1)
        log_probs_sum = jnp.sum(logits - jnp.log(exp_sum)[..., None], axis=-1)
        if world > 1:
            log_probs_sum = _allreduce(log_probs_sum, axis_name)
        mean_log_probs = log_probs_sum / vocab_size
        loss = (1.0 - smoothing) * loss - smoothing * mean_log_probs
    return loss
