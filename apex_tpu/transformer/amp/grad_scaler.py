"""Model-parallel grad scaler.

Parity: reference apex/transformer/amp/grad_scaler.py:21-125 — a GradScaler
whose found_inf is all-reduced across the *model-parallel* group (tp x pp)
before the optimizer step and scale update, so all model-parallel ranks
skip (or step) together.
"""

import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.transformer.parallel_state import (
    PIPELINE_PARALLEL_AXIS,
    TENSOR_PARALLEL_AXIS,
)


class GradScaler(LossScaler):
    """LossScaler whose overflow flag is maxed over the model-parallel axes
    (reference grad_scaler.py:48-51 all_reduce(found_inf, MAX, mp_group))."""

    def __init__(self, init_scale=2.0 ** 16, growth_factor=2.0,
                 backoff_factor=0.5, growth_interval=2000, enabled=True,
                 axis_names=(TENSOR_PARALLEL_AXIS, PIPELINE_PARALLEL_AXIS)):
        super().__init__("dynamic", init_scale=init_scale,
                         scale_factor=growth_factor,
                         scale_window=growth_interval)
        self._backoff_factor = backoff_factor
        self.axis_names = axis_names
        self.enabled = enabled

    def all_reduce_found_inf(self, found_inf):
        for ax in self.axis_names:
            try:
                found_inf = lax.pmax(found_inf, ax)
            except Exception:
                pass  # axis not bound (single-device / host-level call)
        return found_inf

    def unscale_grads(self, grads, state=None):
        grads, found_inf = super().unscale_grads(grads, state)
        return grads, self.all_reduce_found_inf(found_inf)

    def update(self, state, found_inf):
        found_inf = self.all_reduce_found_inf(found_inf)
        overflow = found_inf > 0
        new_scale = jnp.where(
            overflow, state.loss_scale * self._backoff_factor,
            jnp.where(state.unskipped + 1 >= self._scale_window,
                      state.loss_scale * self._scale_factor, state.loss_scale))
        new_unskipped = jnp.where(
            overflow | (state.unskipped + 1 >= self._scale_window),
            0, state.unskipped + 1).astype(jnp.int32)
        from apex_tpu.amp.scaler import ScalerState

        return ScalerState(new_scale, new_unskipped)
