"""Data-parallel pretraining batch samplers.

Behavioral parity target: reference apex/transformer/_data/_batchsampler.py
(sequential resume-able sampler, and an epoch-seeded shuffling sampler).
Re-derived from the contract:

  The global sample stream is consumed in *granules* of
  ``micro_batch_size * data_parallel_size`` indices; each DP rank owns one
  contiguous ``micro_batch_size`` slice of every granule.  Both samplers are
  framework-agnostic index iterators (work as a torch ``batch_sampler=`` or
  with any indexable source) and support mid-epoch resume via
  ``consumed_samples``.
"""

import numpy as np


def _check_layout(total_samples, micro_batch_size, data_parallel_rank,
                  data_parallel_size):
    if total_samples <= 0:
        raise AssertionError(f"empty dataset (total_samples={total_samples})")
    if micro_batch_size <= 0 or data_parallel_size <= 0:
        raise AssertionError("micro_batch_size and data_parallel_size must be "
                             "positive")
    if not 0 <= data_parallel_rank < data_parallel_size:
        raise AssertionError(
            f"rank {data_parallel_rank} outside data-parallel group of size "
            f"{data_parallel_size}")


class MegatronPretrainingSampler:
    """Deterministic in-order sampler: rank r of each granule."""

    def __init__(self, total_samples, consumed_samples, micro_batch_size,
                 data_parallel_rank, data_parallel_size, drop_last=True):
        _check_layout(total_samples, micro_batch_size, data_parallel_rank,
                      data_parallel_size)
        if consumed_samples >= total_samples:
            raise AssertionError(
                f"resume point {consumed_samples} is at/past the end of the "
                f"dataset ({total_samples})")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        self.drop_last = drop_last

    def __len__(self):
        return self.total_samples

    def get_start_end_idx(self):
        lo = self.data_parallel_rank * self.micro_batch_size
        return lo, lo + self.micro_batch_size

    def __iter__(self):
        granule = self.micro_batch_times_data_parallel_size
        lo, hi = self.get_start_end_idx()
        cursor = self.consumed_samples
        while cursor < self.total_samples:
            chunk = list(range(cursor, min(cursor + granule,
                                           self.total_samples)))
            cursor += granule
            if len(chunk) == granule:
                yield chunk[lo:hi]
            elif not self.drop_last:
                # ragged tail: emit whatever of this rank's slice exists
                yield chunk[lo:hi]


class MegatronPretrainingRandomSampler:
    """Epoch-shuffled sampler: each rank owns a fixed contiguous index bucket;
    the bucket is permuted with a seed derived from (seed, epoch), and resume
    skips the already-consumed prefix of the current epoch's permutation."""

    def __init__(self, total_samples, consumed_samples, micro_batch_size,
                 data_parallel_rank, data_parallel_size, seed=1234):
        _check_layout(total_samples, micro_batch_size, data_parallel_rank,
                      data_parallel_size)
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        if total_samples < self.micro_batch_times_data_parallel_size:
            raise AssertionError(
                f"dataset of {total_samples} samples is smaller than one "
                f"granule ({self.micro_batch_times_data_parallel_size}); "
                "shrink micro_batch_size or data_parallel_size")
        # The ragged tail (if any) is never sampled; an epoch is the
        # whole-granule portion of the dataset.
        self.last_batch_size = (
            total_samples % self.micro_batch_times_data_parallel_size)
        self.seed = seed

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        granule = self.micro_batch_times_data_parallel_size
        epoch_samples = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // epoch_samples
        into_epoch = self.consumed_samples % epoch_samples
        if into_epoch % granule:
            raise AssertionError(
                f"resume point {self.consumed_samples} is not granule-aligned "
                f"(granule={granule})")

        per_rank = (self.total_samples // granule) * self.micro_batch_size
        bucket_start = self.data_parallel_rank * per_rank
        skip = into_epoch // self.data_parallel_size

        order = np.random.RandomState(self.seed + self.epoch).permutation(
            per_rank)
        pending = []
        for off in order[skip:]:
            pending.append(int(bucket_start + off))
            if len(pending) == self.micro_batch_size:
                self.consumed_samples += granule
                yield pending
                pending = []
