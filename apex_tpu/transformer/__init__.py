"""apex_tpu.transformer — Megatron-style model parallelism over a device mesh.

Parity: reference apex/transformer/__init__.py (parallel_state,
tensor_parallel, pipeline_parallel, amp, functional, layers, enums,
microbatches, testing).
"""

from apex_tpu.transformer import parallel_state  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer import pipeline_parallel  # noqa: F401
from apex_tpu.transformer import functional  # noqa: F401
from apex_tpu.transformer import layers  # noqa: F401
from apex_tpu.transformer.enums import AttnMaskType, AttnType, LayerType, ModelType  # noqa: F401
from apex_tpu.transformer.microbatches import build_num_microbatches_calculator  # noqa: F401
from apex_tpu.transformer import amp  # noqa: F401
from apex_tpu.transformer import context_parallel  # noqa: F401
from apex_tpu.transformer import moe  # noqa: F401
