"""Shared expert-parallel (dp x ep x tp) MoE-GPT training harness.

One full training step — MoE GPT forward with router aux losses, grads,
the split data-parallel sync rule (dense params pmean over dp x ep, expert
shards over dp alone — parallel_state.get_data_parallel_axes), fused
optimizer — shard_mapped over the global mesh. Used by the driver entry
(``__graft_entry__.dryrun_multichip``) and tests/L0/test_moe sibling
end-to-end runs, like gpt_3d.py is for the pipelined dense path.
"""

import functools

import jax
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import GPTModel, gpt_loss_fn
from apex_tpu.parallel.distributed import all_reduce_gradients
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import is_expert_param, moe_loss_from_variables


def build_gpt_moe_harness(cfg, mesh, opt):
    """Return ``(init_params_and_opt, step)`` for an ep-parallel GPT.

    ``tokens``/``labels`` are [global_batch, seq] with global_batch a
    multiple of dp*ep; each of the dp x ep cells trains on its own shard
    (expert parallelism borrows the replica axis for expert placement).
    Model params come back stacked over a leading ep*tp axis; the step is
    jitted and returns (params, opt_state, mean_loss).
    """
    assert not cfg.sequence_parallel, (
        "this harness covers the dp x ep x tp plane; SP lives in gpt_3d")
    model = GPTModel(cfg)
    dense_axes = parallel_state.get_data_parallel_axes()  # ("dp","ep")
    model_axes = tuple(a for a in ("ep", "tp") if a in mesh.shape)
    batch_axes = tuple(a for a in ("dp", "ep") if a in mesh.shape)

    def sync_grads(grads):
        # The production DDP rule: dense params average over the full
        # dp x ep replica set, expert shards over dp alone.
        return all_reduce_gradients(
            grads, axis_name=dense_axes,
            expert_param_predicate=is_expert_param, expert_axis_name="dp")

    def train_step(params, opt_state, tokens, labels):
        def loss_fn(p):
            logits, mut = model.apply({"params": p}, tokens,
                                      mutable=["moe_losses"])
            return gpt_loss_fn(logits, labels) + moe_loss_from_variables(
                mut, cfg.moe_aux_loss_coeff, cfg.moe_z_loss_coeff)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = sync_grads(grads)
        new_params, new_opt_state = opt.step(grads, opt_state, params)
        return new_params, new_opt_state, jax.lax.pmean(loss, mesh.axis_names)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(model_axes), P(model_axes), P(batch_axes),
                  P(batch_axes)),
        out_specs=(P(model_axes), P(model_axes), P()),
        check_vma=False)
    def sharded_step(stacked_params, stacked_opt, tok, lab):
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        opt_state = jax.tree_util.tree_map(lambda a: a[0], stacked_opt)
        p, o, l = train_step(params, opt_state, tok, lab)
        stack = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)  # noqa: E731
        return stack(p), stack(o), l

    # Init under shard_map so TP/expert param inits see their local ranks.
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(P(), P(batch_axes)),
                       out_specs=P(model_axes), check_vma=False)
    def init_params(key, tok):
        variables = model.init(key, tok)
        return jax.tree_util.tree_map(lambda a: a[None], variables["params"])

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P(model_axes),
                       out_specs=P(model_axes), check_vma=False)
    def init_opt(stacked_params):
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        return jax.tree_util.tree_map(lambda a: a[None], opt.init(params))

    def init_state(key, tokens, stacked_params=None):
        """``stacked_params``: pre-loaded per-rank params (e.g. from
        ``models.reshard.load_moe_checkpoint_for_ep``) instead of a
        fresh init; optimizer state is built for them either way."""
        if stacked_params is None:
            stacked_params = init_params(key, tokens)
        return stacked_params, init_opt(stacked_params)

    return init_state, jax.jit(sharded_step)
