"""Standalone T5-style encoder-decoder for pipeline split-rank tests.

Parity: the reference exercises its ``ModelType.encoder_and_decoder``
pipeline path with Megatron T5-style models (dual p2p shapes from
``decoder_seq_length`` in
apex/transformer/pipeline_parallel/schedules/fwd_bwd_pipelining_without_interleaving.py:29-86;
split-rank group placement in apex/transformer/parallel_state.py:243-331).
This is the TPU build's equivalent test vehicle: a small but genuine
encoder-decoder — pre-RMSNorm blocks, multi-head self-attention, causal
decoder self-attention, cross-attention over the encoder memory, GeGLU-free
relu FFN — written as *pure jnp stage functions over explicit param dicts*
so the same blocks run (a) per-stage inside the SPMD pipeline tick machine
and (b) end-to-end on one device as the grad-parity oracle.

Simplifications vs real T5 (documented, irrelevant to the pipeline
mechanics under test): learned absolute position embeddings instead of
relative position bias, no dropout, one block per pipeline stage.

Layout: every pp rank holds a *uniform* params pytree
``{"enc": {...}, "dec": {...}}`` (its own stage's weights; the slots a
rank never touches — e.g. the encoder embedding off rank 0, the vocab
head off the last rank — simply receive zero grads). Stage placement:
ranks < split run ``encoder_block``; ranks >= split run
``decoder_block`` with the forwarded encoder memory.
"""

import jax
import jax.numpy as jnp
import numpy as np


def t5_test_config(hidden=16, heads=2, ffn=32, vocab=32,
                   enc_seq=6, dec_seq=5):
    return dict(hidden=hidden, heads=heads, ffn=ffn, vocab=vocab,
                enc_seq=enc_seq, dec_seq=dec_seq)


def _rms_norm(x, g, eps=1e-6):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + eps) * g


def _attention(q_w, k_w, v_w, o_w, x_q, x_kv, heads, causal=False):
    """Multi-head attention between query stream x_q [s_q, b, h] and
    key/value stream x_kv [s_kv, b, h]."""
    sq, b, h = x_q.shape
    skv = x_kv.shape[0]
    d = h // heads
    q = (x_q @ q_w).reshape(sq, b, heads, d)
    k = (x_kv @ k_w).reshape(skv, b, heads, d)
    v = (x_kv @ v_w).reshape(skv, b, heads, d)
    scores = jnp.einsum("qbnd,kbnd->bnqk", q, k) / np.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((sq, skv), bool))
        scores = jnp.where(mask, scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bnqk,kbnd->qbnd", probs, v).reshape(sq, b, h)
    return ctx @ o_w


def _ffn(w1, w2, x):
    return jax.nn.relu(x @ w1) @ w2


def init_stage_params(rng, cfg, scale=0.15):
    """One pp rank's uniform param pytree (both sides present)."""
    h, f, v = cfg["hidden"], cfg["ffn"], cfg["vocab"]

    def mat(*shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * scale)

    attn = lambda: {"q": mat(h, h), "k": mat(h, h), "v": mat(h, h),
                    "o": mat(h, h)}
    return {
        "enc": {
            "embed": mat(v, h), "pos": mat(cfg["enc_seq"], h),
            "ln1": jnp.ones((h,)), "attn": attn(),
            "ln2": jnp.ones((h,)), "ffn": {"w1": mat(h, f), "w2": mat(f, h)},
        },
        "dec": {
            "embed": mat(v, h), "pos": mat(cfg["dec_seq"], h),
            "ln1": jnp.ones((h,)), "self_attn": attn(),
            "ln2": jnp.ones((h,)), "cross_attn": attn(),
            "ln3": jnp.ones((h,)), "ffn": {"w1": mat(h, f), "w2": mat(f, h)},
            "ln_out": jnp.ones((h,)), "head": mat(h, v),
        },
    }


def encoder_block(p, h, mb, is_first, *, cfg):
    """Pipeline encoder stage: embed on the first stage, then one
    pre-RMSNorm self-attention + FFN block. h: [enc_seq, b, hidden]."""
    e = p["enc"]
    # tokens mb["enc_tokens"]: [b, enc_seq] -> [enc_seq, b, hidden]
    embedded = (e["embed"][mb["enc_tokens"]]
                + e["pos"][None, :, :]).swapaxes(0, 1)
    h = jnp.where(is_first, embedded, h)
    a = _attention(e["attn"]["q"], e["attn"]["k"], e["attn"]["v"],
                   e["attn"]["o"], _rms_norm(h, e["ln1"]),
                   _rms_norm(h, e["ln1"]), cfg["heads"])
    h = h + a
    h = h + _ffn(e["ffn"]["w1"], e["ffn"]["w2"], _rms_norm(h, e["ln2"]))
    return h


def decoder_block(p, h, memory, mb, is_split, *, cfg):
    """Pipeline decoder stage: embed decoder tokens on the split stage,
    then causal self-attention + cross-attention over the encoder memory +
    FFN. h: [dec_seq, b, hidden], memory: [enc_seq, b, hidden]."""
    d = p["dec"]
    embedded = (d["embed"][mb["dec_tokens"]]
                + d["pos"][None, :, :]).swapaxes(0, 1)
    h = jnp.where(is_split, embedded, h)
    sa = d["self_attn"]
    h = h + _attention(sa["q"], sa["k"], sa["v"], sa["o"],
                       _rms_norm(h, d["ln1"]), _rms_norm(h, d["ln1"]),
                       cfg["heads"], causal=True)
    ca = d["cross_attn"]
    h = h + _attention(ca["q"], ca["k"], ca["v"], ca["o"],
                       _rms_norm(h, d["ln2"]), memory, cfg["heads"])
    h = h + _ffn(d["ffn"]["w1"], d["ffn"]["w2"], _rms_norm(h, d["ln3"]))
    return h


def t5_loss(p, h, mb):
    """Vocab head + mean token cross-entropy on the decoder stream."""
    d = p["dec"]
    logits = _rms_norm(h, d["ln_out"]) @ d["head"]  # [dec_seq, b, v]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = mb["dec_targets"].swapaxes(0, 1)  # [dec_seq, b]
    return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], -1))


def t5_reference_loss(stage_params_list, mb, split, *, cfg):
    """Single-device oracle: run every encoder stage then every decoder
    stage sequentially with the same blocks the pipeline runs.
    ``stage_params_list[r]`` is rank r's uniform pytree."""
    P = len(stage_params_list)
    b = mb["enc_tokens"].shape[0]
    h = jnp.zeros((cfg["enc_seq"], b, cfg["hidden"]), jnp.float32)
    for r in range(split):
        h = encoder_block(stage_params_list[r], h, mb,
                          jnp.asarray(r == 0), cfg=cfg)
    memory = h
    h = jnp.zeros((cfg["dec_seq"], b, cfg["hidden"]), jnp.float32)
    for r in range(split, P):
        h = decoder_block(stage_params_list[r], h, memory, mb,
                          jnp.asarray(r == split), cfg=cfg)
    return t5_loss(stage_params_list[P - 1], h, mb)
