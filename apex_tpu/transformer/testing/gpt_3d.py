"""Shared 3D-parallel (pp x dp x tp) GPT training harness.

One full training step — 1F1B pipeline schedule, DP grad pmean, sequence-
parallel grad allreduce, model-parallel GradScaler, fused optimizer —
shard_mapped over the global mesh. Used by both the driver entry
(``__graft_entry__.dryrun_multichip``) and the minimal end-to-end test
(tests/L0/test_gpt_minimal.py), mirroring how the reference ships its
integration-test harness inside the package
(apex/transformer/testing/standalone_gpt.py + commons.py).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt_stage import GPTStage
from apex_tpu.models.transformer_lm import is_sequence_parallel_param
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_pipelining_with_interleaving,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    allreduce_sequence_parallel_grads,
)


def boundary_tensor_shape(cfg, mesh, seq, microbatch):
    """Per-device activation shape crossing pipeline-stage boundaries:
    [s(/tp under SP), mb, h]. Under sequence parallelism the ppermute
    payload is the *seq shard*, i.e. 1/tp of the full activation — the
    layout-level realization of the reference's p2p scatter-gather
    compression (p2p_communication.py:117-400 splits the tensor over the
    TP group before isend and all-gathers after irecv; sharding makes
    that the resting state, no extra collectives)."""
    seq_shard = seq // mesh.shape.get("tp", 1) if cfg.sequence_parallel \
        else seq
    return (seq_shard, microbatch, cfg.hidden_size)


def build_gpt_3d_harness(cfg, mesh, opt, scaler, *, pp, seq, microbatch,
                         num_microbatches, vpp=None):
    """Return ``(init_state, step)`` for a pipelined GPT training loop.

    ``init_state(key, tokens, labels)`` builds per-stage stacked params,
    optimizer state, and scaler state. ``step(stacked_params, stacked_opt,
    scaler_state, tokens, labels)`` is jitted and returns the new state
    plus the per-(pp, dp)-cell loss grid; only the last pipeline stage's
    rows are nonzero.

    ``tokens``/``labels`` are [global_batch, seq] with
    global_batch = microbatch * num_microbatches * dp.

    ``vpp``: virtual-pipeline chunks per rank (interleaved 1F1B). Rank r
    holds chunks c with global stage c*pp + r; per-rank param leaves get
    a leading [vpp] axis and the step runs
    ``forward_backward_pipelining_with_interleaving`` (reference
    build_model virtual-chunk support, common.py:30-151).
    """
    moe = cfg.num_moe_experts is not None
    if "ep" in mesh.shape and mesh.shape["ep"] > 1:
        # This harness pmeans every grad over 'dp' alone; with an ep>1
        # axis, dense params replicated across ep need the dense-over-
        # (dp, ep) / expert-over-dp split sync (moe/layer.py:14-17,
        # testing/gpt_moe.py) — replicas would silently diverge here.
        raise ValueError(
            "the pipelined harness does not support expert parallelism "
            "(ep > 1); use transformer.testing.gpt_moe (dp x ep x tp)")
    if moe and cfg.moe_layer_freq != 1:
        # Stage-local layer numbering: each stage numbers its layers
        # 0..layers_per_stage-1, so a global every-Nth-layer MoE pattern
        # would silently shift per stage. A uniform stack (every layer
        # MoE) is placement-invariant and composes; refuse the rest.
        raise ValueError(
            "MoE under the pipelined harness needs moe_layer_freq == 1 "
            "(uniform stack); for sparse placement use "
            "transformer.testing.gpt_moe (dp x ep x tp)")
    V = vpp or 1
    if cfg.num_layers % (pp * V):
        raise ValueError(
            f"num_layers ({cfg.num_layers}) must be a multiple of "
            f"pp*vpp ({pp * V})")
    stage = GPTStage(cfg, cfg.num_layers // (pp * V))
    MB, M = microbatch, num_microbatches
    tensor_shape = boundary_tensor_shape(cfg, mesh, seq, microbatch)

    if moe:
        from apex_tpu.transformer.moe import moe_loss_from_variables

        def stage_fn(params, h, mb, is_first):
            # router aux/z losses are per-stage; the schedule's aux_loss
            # contract backprops them from each stage's own backward unit
            y, mut = stage.apply({"params": params}, mb["tokens"], h,
                                 is_first, mutable=["moe_losses"])
            return y, moe_loss_from_variables(
                mut, cfg.moe_aux_loss_coeff, cfg.moe_z_loss_coeff)
    else:
        def stage_fn(params, h, mb, is_first):
            return stage.apply({"params": params}, mb["tokens"], h,
                               is_first)

    def loss_fn(params, y, mb):
        return stage.apply({"params": params}, y, mb["labels"],
                           method=GPTStage.loss)

    def train_step(params, opt_state, scaler_state, tokens, labels):
        mbs = {"tokens": tokens.reshape(M, MB, seq),
               "labels": labels.reshape(M, MB, seq)}
        # scale the loss up by the live scale; unscale_grads divides it
        # back out (and pmaxes found_inf over tp x pp)
        # V=1 falls through to the non-interleaved schedule inside
        losses, grads = forward_backward_pipelining_with_interleaving(
            stage_fn, loss_fn, params, mbs, num_microbatches=M,
            tensor_shape=tensor_shape, dtype=cfg.compute_dtype,
            grad_scale=scaler_state.loss_scale, pp_size=pp,
            num_model_chunks=V, aux_loss=moe)
        # DP gradient sync (DDP semantics: average over the dp axis).
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, "dp"), grads)
        # SP: grads of tp-replicated params (layernorms, position
        # embeddings, row-parallel biases) are partial over seq shards —
        # allreduce them over tp (reference layer_norm.py:26-99 tagging).
        if cfg.sequence_parallel:
            grads = allreduce_sequence_parallel_grads(
                grads, is_sequence_parallel_param)
        grads, found_inf = scaler.unscale_grads(grads, scaler_state)
        new_params, new_opt_state = opt.step(
            grads, opt_state, params, found_inf=found_inf)
        new_scaler_state = scaler.update(scaler_state, found_inf)
        return new_params, new_opt_state, new_scaler_state, jnp.sum(losses)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P("pp"), P("pp"), P(), P("dp"), P("dp")),
        out_specs=(P("pp"), P("pp"), P(), P(("pp", "dp"))),
        check_vma=False)
    def sharded_step(stacked_params, stacked_opt, scaler_state, tok, lab):
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        opt_state = jax.tree_util.tree_map(lambda a: a[0], stacked_opt)
        p, o, s, l = train_step(params, opt_state, scaler_state,
                                tok.reshape(-1, seq), lab.reshape(-1, seq))
        stack = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)  # noqa: E731
        return stack(p), stack(o), s, l.reshape(1, 1)

    # Per-stage params: init under shard_map so TP layers see local shards.
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(), P(), P()), out_specs=P("pp"),
                       check_vma=False)
    def init_params(key, tok, lab):
        rank = jax.lax.axis_index("pp")
        h0 = jnp.zeros(tensor_shape, cfg.compute_dtype)

        def init_chunk(c):
            # chunk c on rank r is global stage c*pp + r
            k = jax.random.fold_in(key, c * pp + rank)
            return stage.init(k, tok[:MB], h0, jnp.asarray(False),
                              lab[:MB], method=GPTStage.full)["params"]

        if V > 1:
            chunks = [init_chunk(c) for c in range(V)]
            params = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *chunks)
        else:
            params = init_chunk(0)
        return jax.tree_util.tree_map(lambda a: a[None], params)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P("pp"),
                       out_specs=P("pp"), check_vma=False)
    def init_opt(stacked_params):
        params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        return jax.tree_util.tree_map(lambda a: a[None], opt.init(params))

    def init_state(key, tokens, labels, stacked_params=None):
        """``stacked_params``: pre-loaded per-rank params (e.g. from
        ``models.reshard.load_checkpoint_for_3d``) instead of a fresh
        init; optimizer/scaler state is built for them either way."""
        if stacked_params is None:
            stacked_params = init_params(key, tokens[:MB], labels[:MB])
        return stacked_params, init_opt(stacked_params), scaler.init_state()

    return init_state, jax.jit(sharded_step)
