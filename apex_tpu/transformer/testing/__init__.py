"""In-package testing harness.

Parity: reference apex/transformer/testing/ — standalone GPT/BERT model
providers for integration tests, the Megatron-style argument parser,
process-global state (args/timers/microbatch calculator), and shared
helpers. The standalone models live in :mod:`apex_tpu.models`; this
package wires them to the reference harness API.
"""

from apex_tpu.transformer.testing.arguments import parse_args  # noqa: F401
from apex_tpu.transformer.testing.global_vars import (  # noqa: F401
    get_args,
    get_timers,
    set_global_variables,
)
from apex_tpu.transformer.testing.standalone_gpt import (  # noqa: F401
    gpt_model_provider,
)
from apex_tpu.transformer.testing.standalone_bert import (  # noqa: F401
    bert_model_provider,
)
