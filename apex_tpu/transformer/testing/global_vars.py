"""Process-global harness state.

Parity: reference apex/transformer/testing/global_vars.py — singletons for
args, the microbatch calculator, tensorboard writer, autoresume hook, and
timers, with ensure-initialized/ensure-not-initialized guards.
"""

from apex_tpu.transformer.microbatches import (
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.pipeline_parallel._timers import _Timers as Timers

_GLOBAL_ARGS = None
_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_ADLR_AUTORESUME = None
_GLOBAL_TIMERS = None


def _ensure_var_is_initialized(var, name):
    if var is None:
        raise RuntimeError(f"{name} is not initialized.")


def _ensure_var_is_not_initialized(var, name):
    if var is not None:
        raise RuntimeError(f"{name} is already initialized.")


def get_args():
    _ensure_var_is_initialized(_GLOBAL_ARGS, "args")
    return _GLOBAL_ARGS


def get_num_microbatches():
    _ensure_var_is_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                               "num microbatches calculator")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size():
    _ensure_var_is_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                               "num microbatches calculator")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True):
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples,
                                               consistency_check)


def get_tensorboard_writer():
    return _GLOBAL_TENSORBOARD_WRITER


def get_adlr_autoresume():
    return _GLOBAL_ADLR_AUTORESUME


def get_timers():
    _ensure_var_is_initialized(_GLOBAL_TIMERS, "timers")
    return _GLOBAL_TIMERS


def set_global_variables(args):
    """Install args + derived singletons (reference set_global_variables)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_TIMERS
    _ensure_var_is_not_initialized(_GLOBAL_ARGS, "args")
    _GLOBAL_ARGS = args
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank=0,
        rampup_batch_size=getattr(args, "rampup_batch_size", None),
        global_batch_size=args.global_batch_size,
        micro_batch_size=args.micro_batch_size,
        data_parallel_size=args.data_parallel_size,
    )
    _GLOBAL_TIMERS = Timers()


def destroy_global_vars():
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_TIMERS
    _GLOBAL_ARGS = None
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
    _GLOBAL_TIMERS = None
