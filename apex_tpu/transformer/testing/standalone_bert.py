"""Standalone BERT for integration tests.

Parity: reference apex/transformer/testing/standalone_bert.py:
``bert_model_provider(pre_process, post_process, cpu_offload)``. The TPU
model is :class:`apex_tpu.models.BertModel` (padding-mask attention, MLM +
NSP heads, vocab-parallel logits).
"""

import jax.numpy as jnp

from apex_tpu.models import BertModel, TransformerConfig
from apex_tpu.models.bert import bert_loss_fn  # noqa: F401
from apex_tpu.transformer.enums import AttnMaskType


def bert_model_provider(pre_process=True, post_process=True, *,
                        config=None, num_tokentypes=2, **kwargs):
    """Build a BERT model from harness args (reference signature parity)."""
    if config is None:
        from apex_tpu.transformer.testing.global_vars import get_args

        args = get_args()
        config = TransformerConfig(
            hidden_size=args.hidden_size,
            num_layers=args.num_layers,
            num_attention_heads=args.num_attention_heads,
            vocab_size=args.padded_vocab_size or args.vocab_size,
            max_position_embeddings=args.max_position_embeddings,
            sequence_parallel=args.sequence_parallel,
            params_dtype=jnp.float32,
            compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
            attn_mask_type=AttnMaskType.padding,
        )
    return BertModel(config, num_tokentypes=num_tokentypes, **kwargs)
