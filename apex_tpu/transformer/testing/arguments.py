"""Megatron-style argument parser for the testing harness.

Parity: reference apex/transformer/testing/arguments.py (977 LoC, ~170
flags). External Megatron/NeMo-style launch commands should parse
unchanged: every reference flag is accepted here under its original
spelling, including the vision / retriever / BERT-pretraining tails that
the TPU harness itself never reads (they exist so a ported launch script
does not die on argparse). Structure is our own: flags live in grouped
tables, then one derivation pass computes the dependent values
(world-size splits, padded vocab, virtual-pipeline geometry) and
validates cross-flag constraints.
"""

import argparse
import os


def _model_flags(parser):
    g = parser.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=2)
    g.add_argument("--hidden-size", type=int, default=64)
    g.add_argument("--num-attention-heads", type=int, default=4)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=128)
    g.add_argument("--seq-length", type=int, default=64)
    g.add_argument("--encoder-seq-length", type=int, default=None)
    g.add_argument("--decoder-seq-length", type=int, default=None)
    g.add_argument("--retriever-seq-length", type=int, default=256)
    g.add_argument("--vocab-size", type=int, default=1024)
    g.add_argument("--padded-vocab-size", type=int, default=None)
    g.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    g.add_argument("--vocab-extra-ids", type=int, default=0)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--num-experts", type=int, default=None)
    g.add_argument("--apply-residual-connection-post-layernorm",
                   action="store_true")
    g.add_argument("--openai-gelu", action="store_true")
    g.add_argument("--onnx-safe", type=bool, default=None)
    g.add_argument("--bert-no-binary-head", action="store_false",
                   dest="bert_binary_head")


def _parallelism_flags(parser):
    g = parser.add_argument_group("parallelism")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--model-parallel-size", type=int, default=None,
                   help="deprecated alias for "
                        "--tensor-model-parallel-size")
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--num-layers-per-virtual-pipeline-stage", type=int,
                   default=None)
    g.add_argument("--pipeline-model-parallel-split-rank", type=int,
                   default=None)
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--standalone-embedding-stage", action="store_true")
    g.add_argument("--distributed-backend", default="xla",
                   choices=["xla", "nccl", "gloo", "ucc"])
    g.add_argument("--lazy-mpu-init", type=bool, default=None)
    g.add_argument("--use-cpu-initialization", type=bool, default=None)
    g.add_argument("--empty-unused-memory-level", type=int, default=0,
                   choices=[0, 1, 2])
    g.add_argument("--no-async-tensor-model-parallel-allreduce",
                   action="store_false",
                   dest="async_tensor_model_parallel_allreduce")
    g.add_argument("--no-scatter-gather-tensors-in-pipeline",
                   action="store_false",
                   dest="scatter_gather_tensors_in_pipeline")
    g.add_argument("--no-contiguous-buffers-in-local-ddp",
                   action="store_false",
                   dest="contiguous_buffers_in_local_ddp")
    g.add_argument("--inference-batch-times-seqlen-threshold", type=int,
                   default=512)
    g.add_argument("--cpu-offload", action="store_true")


def _batching_flags(parser):
    g = parser.add_argument_group("batching")
    g.add_argument("--micro-batch-size", type=int, default=2)
    g.add_argument("--batch-size", type=int, default=None,
                   help="deprecated alias for --micro-batch-size")
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)


def _precision_flags(parser):
    g = parser.add_argument_group("precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")
    g.add_argument("--params-dtype", default="float32")
    g.add_argument("--fp32-residual-connection", action="store_true")
    g.add_argument("--fp16-lm-cross-entropy", action="store_true")
    g.add_argument("--attention-softmax-in-fp32", action="store_true")
    g.add_argument("--no-query-key-layer-scaling", action="store_false",
                   dest="apply_query_key_layer_scaling")
    g.add_argument("--no-masked-softmax-fusion", action="store_false",
                   dest="masked_softmax_fusion")
    g.add_argument("--no-bias-gelu-fusion", action="store_false",
                   dest="bias_gelu_fusion")
    g.add_argument("--no-bias-dropout-fusion", action="store_false",
                   dest="bias_dropout_fusion")
    g.add_argument("--no-persist-layer-norm", action="store_false",
                   dest="persist_layer_norm")
    g.add_argument("--no-gradient-accumulation-fusion",
                   action="store_false",
                   dest="gradient_accumulation_fusion")


def _training_flags(parser):
    g = parser.add_argument_group("training")
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--min-lr", type=float, default=0.0)
    g.add_argument("--lr-decay-style", default="linear",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--lr-decay-iters", type=int, default=None)
    g.add_argument("--lr-decay-samples", type=int, default=None)
    g.add_argument("--lr-warmup-fraction", type=float, default=None)
    g.add_argument("--lr-warmup-iters", type=int, default=0)
    g.add_argument("--lr-warmup-samples", type=int, default=0)
    g.add_argument("--warmup", type=float, default=None,
                   help="removed; use --lr-warmup-fraction")
    g.add_argument("--override-lr-scheduler", action="store_true")
    g.add_argument("--use-checkpoint-lr-scheduler", action="store_true")
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--start-weight-decay", type=float, default=None)
    g.add_argument("--end-weight-decay", type=float, default=None)
    g.add_argument("--weight-decay-incr-style", default="constant",
                   choices=["constant", "linear", "cosine"])
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)
    g.add_argument("--sgd-momentum", type=float, default=0.9)
    g.add_argument("--optimizer", default="adam",
                   choices=["adam", "sgd", "lamb"])
    g.add_argument("--train-iters", type=int, default=10)
    g.add_argument("--train-samples", type=int, default=None)
    g.add_argument("--exit-interval", type=int, default=None)
    g.add_argument("--exit-duration-in-mins", type=int, default=None)
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--init-method-std", type=float, default=0.02)
    g.add_argument("--init-method-xavier-uniform", action="store_true")
    g.add_argument("--finetune", action="store_true")
    g.add_argument("--head-lr-mult", type=float, default=1.0)


def _checkpoint_flags(parser):
    g = parser.add_argument_group("checkpointing")
    g.add_argument("--save", default=None)
    g.add_argument("--load", default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--no-save-optim", action="store_true", default=None)
    g.add_argument("--no-save-rng", action="store_true", default=None)
    g.add_argument("--no-load-optim", action="store_true", default=None)
    g.add_argument("--no-load-rng", action="store_true", default=None)
    g.add_argument("--bert-load", default=None)
    g.add_argument("--adlr-autoresume", action="store_true")
    g.add_argument("--adlr-autoresume-interval", type=int, default=1000)
    # recompute family: the reference carries both the legacy
    # checkpoint-activations spelling and the newer recompute-* one
    g.add_argument("--checkpoint-activations", action="store_true")
    g.add_argument("--recompute-activations", action="store_true")
    g.add_argument("--recompute-granularity", default=None,
                   choices=[None, "full", "selective"])
    g.add_argument("--recompute-method", default=None,
                   choices=[None, "uniform", "block"])
    g.add_argument("--recompute-num-layers", type=int, default=1)
    g.add_argument("--activations-checkpoint-method", default=None,
                   choices=[None, "uniform", "block"])
    g.add_argument("--activations-checkpoint-num-layers", type=int,
                   default=1)
    g.add_argument("--distribute-saved-activations", action="store_true")


def _logging_flags(parser):
    g = parser.add_argument_group("logging")
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--log-params-norm", action="store_true")
    g.add_argument("--log-num-zeros-in-grad", action="store_true")
    g.add_argument("--timing-log-level", type=int, default=0,
                   choices=[0, 1, 2])
    g.add_argument("--tensorboard-dir", default=None)
    g.add_argument("--tensorboard-log-interval", type=int, default=1)
    g.add_argument("--tensorboard-queue-size", type=int, default=1000)
    g.add_argument("--log-timers-to-tensorboard", action="store_true")
    g.add_argument("--log-batch-size-to-tensorboard", action="store_true")
    g.add_argument("--no-log-learnig-rate-to-tensorboard",
                   action="store_false",
                   dest="log_learning_rate_to_tensorboard")
    g.add_argument("--no-log-loss-scale-to-tensorboard",
                   action="store_false",
                   dest="log_loss_scale_to_tensorboard")
    g.add_argument("--log-validation-ppl-to-tensorboard",
                   action="store_true")
    g.add_argument("--log-memory-to-tensorboard", action="store_true")
    g.add_argument("--log-world-size-to-tensorboard", action="store_true")
    g.add_argument("--eval-interval", type=int, default=1000)
    g.add_argument("--eval-iters", type=int, default=100)


def _data_flags(parser):
    g = parser.add_argument_group("data")
    g.add_argument("--data-path", nargs="*", default=None)
    g.add_argument("--split", default="969, 30, 1")
    g.add_argument("--vocab-file", default=None)
    g.add_argument("--merge-file", default=None)
    g.add_argument("--tokenizer-type", default=None)
    g.add_argument("--data-impl", default="infer",
                   choices=["lazy", "cached", "mmap", "infer"])
    g.add_argument("--mmap-warmup", action="store_true")
    g.add_argument("--num-workers", type=int, default=2)
    g.add_argument("--dataloader-type", default=None,
                   choices=[None, "single", "cyclic"])
    g.add_argument("--no-data-sharding", action="store_false",
                   dest="data_sharding")
    g.add_argument("--mask-prob", type=float, default=0.15)
    g.add_argument("--short-seq-prob", type=float, default=0.1)
    g.add_argument("--sample-rate", type=float, default=1.0)
    g.add_argument("--reset-position-ids", action="store_true")
    g.add_argument("--reset-attention-mask", action="store_true")
    g.add_argument("--eod-mask-loss", action="store_true")
    g.add_argument("--use-one-sent-docs", type=bool, default=False)


def _vision_flags(parser):
    # vision/DINO tail — parsed for launch-command parity only
    g = parser.add_argument_group("vision")
    g.add_argument("--num-classes", type=int, default=1000)
    g.add_argument("--img-h", type=int, default=224)
    g.add_argument("--img-w", type=int, default=224)
    g.add_argument("--num-channels", type=int, default=3)
    g.add_argument("--patch-dim", type=int, default=16)
    g.add_argument("--classes-fraction", type=float, default=1.0)
    g.add_argument("--data-per-class-fraction", type=float, default=1.0)
    g.add_argument("--vision-pretraining", action="store_true")
    g.add_argument("--vision-pretraining-type", default="classify",
                   choices=["classify", "inpaint", "dino"])
    g.add_argument("--vision-backbone-type", default="vit",
                   choices=["vit", "mit", "swin"])
    g.add_argument("--swin-backbone-type", default="tiny",
                   choices=["tiny", "base", "h3"])
    g.add_argument("--mask-type", default="random",
                   choices=["random", "row"])
    g.add_argument("--mask-factor", type=float, default=1.0)
    g.add_argument("--iter-per-epoch", type=int, default=1250)
    g.add_argument("--dino-local-img-size", type=int, default=96)
    g.add_argument("--dino-local-crops-number", type=int, default=10)
    g.add_argument("--dino-head-hidden-size", type=int, default=2048)
    g.add_argument("--dino-bottleneck-size", type=int, default=256)
    g.add_argument("--dino-freeze-last-layer", type=float, default=1)
    g.add_argument("--dino-norm-last-layer", action="store_true")
    g.add_argument("--dino-warmup-teacher-temp", type=float, default=0.04)
    g.add_argument("--dino-teacher-temp", type=float, default=0.07)
    g.add_argument("--dino-warmup-teacher-temp-epochs", type=int,
                   default=30)


def _retriever_flags(parser):
    # REALM/ICT/biencoder tail — parsed for launch-command parity only
    g = parser.add_argument_group("retriever")
    g.add_argument("--ict-head-size", type=int, default=None)
    g.add_argument("--biencoder-projection-dim", type=int, default=0)
    g.add_argument("--biencoder-shared-query-context-model",
                   action="store_true")
    g.add_argument("--ict-load", default=None)
    g.add_argument("--titles-data-path", default=None)
    g.add_argument("--query-in-block-prob", type=float, default=0.1)
    g.add_argument("--block-data-path", default=None)
    g.add_argument("--embedding-path", default=None)
    g.add_argument("--evidence-data-path", default=None)
    g.add_argument("--indexer-batch-size", type=int, default=128)
    g.add_argument("--indexer-log-interval", type=int, default=1000)
    g.add_argument("--retriever-report-topk-accuracies", nargs="+",
                   type=int, default=[])
    g.add_argument("--retriever-score-scaling", action="store_true")


def parse_args(extra_args_provider=None, defaults=None,
               ignore_unknown_args=True, args=None):
    """Parse harness arguments (reference arguments.py:parse_args)."""
    parser = argparse.ArgumentParser(
        description="apex_tpu testing harness arguments",
        allow_abbrev=False)
    for add in (_model_flags, _parallelism_flags, _batching_flags,
                _precision_flags, _training_flags, _checkpoint_flags,
                _logging_flags, _data_flags, _vision_flags,
                _retriever_flags):
        add(parser)

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)

    if defaults:
        for k, v in defaults.items():
            if getattr(parsed, k, None) is None:
                setattr(parsed, k, v)

    return _derive_and_validate(parsed)


def _derive_and_validate(parsed):
    """Dependent-value derivation + cross-flag validation (the
    reference's validate_args)."""
    # deprecated aliases fold into their modern spellings
    if parsed.model_parallel_size is not None:
        parsed.tensor_model_parallel_size = parsed.model_parallel_size
    if parsed.batch_size is not None:
        parsed.micro_batch_size = parsed.batch_size
    if parsed.warmup is not None:
        # the reference refuses this flag outright (arguments.py:109) —
        # its historical int/fraction ambiguity makes silent folding
        # dangerous
        raise ValueError(
            "--warmup was removed; use --lr-warmup-fraction instead")
    if parsed.checkpoint_activations and not parsed.recompute_granularity:
        parsed.recompute_granularity = "full"
        parsed.recompute_method = (parsed.activations_checkpoint_method
                                   or "uniform")
    if parsed.recompute_activations and not parsed.recompute_granularity:
        parsed.recompute_granularity = "selective"

    parsed.world_size = int(os.environ.get("WORLD_SIZE", "0")) or None
    if parsed.world_size is None:
        import jax

        parsed.world_size = len(jax.devices())
    mp = (parsed.tensor_model_parallel_size
          * parsed.pipeline_model_parallel_size
          * parsed.context_parallel_size)
    if parsed.world_size % mp != 0:
        raise ValueError(
            f"world size ({parsed.world_size}) is not divisible by "
            f"tp*pp*cp ({mp})")
    parsed.data_parallel_size = parsed.world_size // mp

    if parsed.global_batch_size is None:
        parsed.global_batch_size = (parsed.micro_batch_size
                                    * parsed.data_parallel_size)
    if parsed.ffn_hidden_size is None:
        parsed.ffn_hidden_size = 4 * parsed.hidden_size
    if parsed.kv_channels is None:
        parsed.kv_channels = (parsed.hidden_size
                              // parsed.num_attention_heads)
    if parsed.encoder_seq_length is None:
        parsed.encoder_seq_length = parsed.seq_length
    if parsed.padded_vocab_size is None:
        mult = (parsed.make_vocab_size_divisible_by
                * parsed.tensor_model_parallel_size)
        parsed.padded_vocab_size = (
            (parsed.vocab_size + parsed.vocab_extra_ids + mult - 1)
            // mult * mult)

    # virtual pipeline geometry: either give the chunk count directly or
    # derive it from layers-per-virtual-stage
    if (parsed.num_layers_per_virtual_pipeline_stage is not None
            and parsed.virtual_pipeline_model_parallel_size is None):
        if parsed.num_layers % parsed.pipeline_model_parallel_size:
            raise ValueError(
                f"--num-layers ({parsed.num_layers}) must be divisible "
                f"by the pipeline size "
                f"({parsed.pipeline_model_parallel_size}) to derive "
                f"virtual-pipeline geometry")
        per_stage = (parsed.num_layers
                     // parsed.pipeline_model_parallel_size)
        if per_stage % parsed.num_layers_per_virtual_pipeline_stage:
            raise ValueError(
                f"layers per pipeline stage ({per_stage}) must divide "
                f"evenly into virtual stages of "
                f"{parsed.num_layers_per_virtual_pipeline_stage}")
        parsed.virtual_pipeline_model_parallel_size = (
            per_stage // parsed.num_layers_per_virtual_pipeline_stage)

    split = parsed.pipeline_model_parallel_split_rank
    if split is not None and not (
            0 <= split <= parsed.pipeline_model_parallel_size):
        raise ValueError(
            f"pipeline split rank {split} outside the "
            f"{parsed.pipeline_model_parallel_size}-stage pipeline")

    if parsed.fp16 and parsed.bf16:
        raise ValueError("--fp16 and --bf16 are mutually exclusive")
    if parsed.train_samples is not None:
        # sample-based bound wins over the iteration default: convert at
        # the (possibly ramped-up) global batch size floor
        parsed.train_iters = max(
            1, parsed.train_samples // parsed.global_batch_size)
    if parsed.lr_decay_iters is not None and parsed.lr_decay_samples \
            is not None:
        raise ValueError(
            "--lr-decay-iters and --lr-decay-samples are mutually "
            "exclusive")
    if parsed.start_weight_decay is not None:
        if parsed.start_weight_decay < 0:
            raise ValueError("--start-weight-decay must be >= 0")
        if parsed.end_weight_decay is None \
                or parsed.end_weight_decay < parsed.start_weight_decay:
            raise ValueError(
                "--end-weight-decay must be set >= --start-weight-decay")
    if parsed.sequence_parallel and parsed.tensor_model_parallel_size == 1:
        parsed.sequence_parallel = False
    if parsed.standalone_embedding_stage \
            and parsed.pipeline_model_parallel_size == 1:
        raise ValueError(
            "--standalone-embedding-stage needs a multi-stage pipeline")
    return parsed
