"""Megatron-style argument parser for the testing harness.

Parity: reference apex/transformer/testing/arguments.py (977 LoC, ~180
flags). This carries the subset the harness and tests actually consume —
model geometry, parallelism degrees, batching, precision, checkpointing,
logging — with the same flag names and defaulting/validation behavior
(world-size divisibility, global-batch derivation) so Megatron-style
launch commands work unchanged.
"""

import argparse
import os


def parse_args(extra_args_provider=None, defaults=None,
               ignore_unknown_args=True, args=None):
    """Parse harness arguments (reference arguments.py:parse_args)."""
    parser = argparse.ArgumentParser(
        description="apex_tpu testing harness arguments",
        allow_abbrev=False)

    g = parser.add_argument_group("model")
    g.add_argument("--num-layers", type=int, default=2)
    g.add_argument("--hidden-size", type=int, default=64)
    g.add_argument("--num-attention-heads", type=int, default=4)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=128)
    g.add_argument("--seq-length", type=int, default=64)
    g.add_argument("--vocab-size", type=int, default=1024)
    g.add_argument("--padded-vocab-size", type=int, default=None)
    g.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--attention-dropout", type=float, default=0.1)

    g = parser.add_argument_group("parallelism")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--pipeline-model-parallel-split-rank", type=int,
                   default=None)
    g.add_argument("--context-parallel-size", type=int, default=1)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--distributed-backend", default="xla",
                   choices=["xla", "nccl", "gloo", "ucc"])

    g = parser.add_argument_group("batching")
    g.add_argument("--micro-batch-size", type=int, default=2)
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)

    g = parser.add_argument_group("precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    g.add_argument("--min-loss-scale", type=float, default=1.0)
    g.add_argument("--loss-scale-window", type=float, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--accumulate-allreduce-grads-in-fp32",
                   action="store_true")
    g.add_argument("--params-dtype", default="float32")

    g = parser.add_argument_group("training")
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--train-iters", type=int, default=10)
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--init-method-std", type=float, default=0.02)
    g.add_argument("--optimizer", default="adam",
                   choices=["adam", "sgd", "lamb"])

    g = parser.add_argument_group("checkpointing")
    g.add_argument("--save", default=None)
    g.add_argument("--load", default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--activations-checkpoint-method", default=None,
                   choices=[None, "uniform", "block"])
    g.add_argument("--activations-checkpoint-num-layers", type=int,
                   default=1)
    g.add_argument("--distribute-saved-activations", action="store_true")

    g = parser.add_argument_group("logging")
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--tensorboard-dir", default=None)
    g.add_argument("--timing-log-level", type=int, default=0)

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)

    if defaults:
        for k, v in defaults.items():
            if getattr(parsed, k, None) is None:
                setattr(parsed, k, v)

    # -- derivations/validation (reference arguments.py validate_args) ----
    parsed.world_size = int(os.environ.get("WORLD_SIZE", "0")) or None
    if parsed.world_size is None:
        import jax

        parsed.world_size = len(jax.devices())
    mp = (parsed.tensor_model_parallel_size
          * parsed.pipeline_model_parallel_size
          * parsed.context_parallel_size)
    if parsed.world_size % mp != 0:
        raise ValueError(
            f"world size ({parsed.world_size}) is not divisible by "
            f"tp*pp*cp ({mp})")
    parsed.data_parallel_size = parsed.world_size // mp
    if parsed.global_batch_size is None:
        parsed.global_batch_size = (parsed.micro_batch_size
                                    * parsed.data_parallel_size)
    if parsed.ffn_hidden_size is None:
        parsed.ffn_hidden_size = 4 * parsed.hidden_size
    if parsed.kv_channels is None:
        parsed.kv_channels = (parsed.hidden_size
                              // parsed.num_attention_heads)
    if parsed.padded_vocab_size is None:
        mult = (parsed.make_vocab_size_divisible_by
                * parsed.tensor_model_parallel_size)
        parsed.padded_vocab_size = (
            (parsed.vocab_size + mult - 1) // mult * mult)
    if parsed.fp16 and parsed.bf16:
        raise ValueError("--fp16 and --bf16 are mutually exclusive")
    if parsed.sequence_parallel and parsed.tensor_model_parallel_size == 1:
        parsed.sequence_parallel = False
    return parsed
