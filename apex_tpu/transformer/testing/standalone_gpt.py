"""Standalone GPT for integration tests.

Parity: reference apex/transformer/testing/standalone_gpt.py:
``gpt_model_provider(pre_process, post_process, cpu_offload)`` returning a
Megatron GPT built from the parallel transformer stack. The TPU model
itself is :class:`apex_tpu.models.GPTModel` (tensor/sequence-parallel
layers over the mesh, vocab-parallel loss).
"""

import jax.numpy as jnp

from apex_tpu.models import GPTModel, TransformerConfig
from apex_tpu.models.gpt import gpt_loss_fn  # noqa: F401


def gpt_model_provider(pre_process=True, post_process=True, *,
                       config=None, **kwargs):
    """Build a GPT model from harness args (reference signature parity;
    pre/post_process select pipeline-stage roles)."""
    if config is None:
        from apex_tpu.transformer.testing.global_vars import get_args

        args = get_args()
        config = TransformerConfig(
            hidden_size=args.hidden_size,
            num_layers=args.num_layers,
            num_attention_heads=args.num_attention_heads,
            vocab_size=args.padded_vocab_size or args.vocab_size,
            max_position_embeddings=args.max_position_embeddings,
            sequence_parallel=args.sequence_parallel,
            # honor an explicit --kv-channels (cfg normalizes the
            # derived-value case back to None)
            head_dim=args.kv_channels,
            params_dtype=jnp.float32,
            compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        )
    return GPTModel(config, pre_process=pre_process,
                    post_process=post_process, **kwargs)
