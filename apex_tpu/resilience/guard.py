"""Non-finite step guard: skip poisoned optimizer steps in-graph.

Why: one NaN microbatch — an fp16 overflow the scaler missed, a bad
input record, a transient ICI bit flip — poisons every parameter
forever once the optimizer commits it, and a multi-day run only finds
out when the loss curve flatlines. The reference's answer is amp's
host-synced overflow check (apex/amp/scaler.py:200 D2H-syncs the
overflow flag every step); the TPU-native answer must stay inside the
compiled step: no host sync, no callback, nothing XLA can't schedule.

:func:`guarded_update` is that answer. It derives a single
found-non-finite flag from the (pre-update) gradients, ORs it across
the data-parallel replica set with one scalar ``psum`` (every replica
must agree to skip, or params diverge), computes the candidate update
anyway, and commits it with ``jnp.where`` — the skip costs one select
per leaf, not a branch, and composes with donation. The skip decision
also:

- **does not commit dependent state**: whatever pytree the caller
  passes as ``state`` is reverted wholesale on a skipped step. Put the
  ``compress="int8"`` error-feedback residual in there — a residual
  computed from NaN gradients must not feed back into the next step.
- **still drives the loss scaler**: ``scaler.update`` *wants* to see
  the overflow (that is how dynamic scaling backs off), so when a
  ``scaler``/``scaler_state`` pair is supplied its update always
  commits, fed with the global flag.
- carries a consecutive-skip counter in :class:`GuardState` so the
  host can distinguish "one bad batch" (skip and move on) from "the
  run is diverging" (:func:`check_guard` raises
  :class:`NonFiniteError` after K consecutive skips).

Escalation and telemetry are host-side by design: :func:`check_guard`
fetches the three-scalar ``GuardState`` (the only sync, amortizable to
every N steps), lands the ``guard/steps_skipped`` counter and
``guard/consecutive_skips`` gauge in the registry, and raises once the
skip streak crosses the threshold. The compiled step stays clean — the
chaos suite asserts no ``callback`` custom-calls in the lowered HLO.

OOM joins NaN as a post-mortem-producing failure (telemetry/memory.py):
a non-finite step is skippable in-graph, but HBM exhaustion kills the
dispatch itself — the runtime raises RESOURCE_EXHAUSTED before any
flag could be computed. :func:`guarded_call` is the host-side
companion: wrap the step *dispatch* (plus its completion barrier) and
an OOM writes ``memory-postmortem-rank<N>.json`` (live-buffer census +
headroom trend) before re-raising as
:class:`~apex_tpu.telemetry.memory.HBMExhaustedError` — the same
"die with attribution, not a bare traceback" contract
:func:`check_guard` gives NaN escalation.

Numerics attribution (telemetry/numerics.py + telemetry/recorder.py):
pass a :class:`~apex_tpu.telemetry.recorder.FlightRecorder` (plus its
carry state) to :func:`guarded_update` and every step's per-module
stats land in the device-side ring — recorded OUTSIDE the skip revert,
so the poisoned step's stats survive their own skip. On a skipped step
(and on escalation) :func:`check_guard` fetches the ring once and
dumps ``numerics-postmortem-rank<N>.json`` naming the first module
prefix whose non-finite count is > 0 — the "which layer, which step,
how did it trend" answer a bare ``NonFiniteError`` was missing.
"""

import os
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.telemetry import trace as _telemetry_trace
from apex_tpu.telemetry.registry import get_registry

ENV_MAX_SKIPS = "APEX_TPU_GUARD_MAX_SKIPS"
DEFAULT_MAX_CONSECUTIVE_SKIPS = 3


class NonFiniteError(RuntimeError):
    """Raised host-side when non-finite gradients persist past the
    consecutive-skip budget (or eagerly by
    ``clip_grad_norm_(..., error_if_nonfinite=True)``)."""


class GuardState(NamedTuple):
    """Skip accounting carried through the jitted step (three i32
    scalars — donate it with the rest of the training state)."""

    consecutive_skips: jnp.ndarray  # i32: current skip streak
    total_skips: jnp.ndarray        # i32: lifetime skipped steps
    last_skipped: jnp.ndarray       # i32: 1 iff the latest step skipped


def init_guard_state() -> GuardState:
    return GuardState(
        consecutive_skips=jnp.zeros((), jnp.int32),
        total_skips=jnp.zeros((), jnp.int32),
        last_skipped=jnp.zeros((), jnp.int32),
    )


def nonfinite_flag(tree) -> jnp.ndarray:
    """f32 scalar: 1.0 iff any inexact leaf of ``tree`` holds a
    non-finite value. One fused reduction per leaf — cheap against the
    backward pass that produced the leaves."""
    leaves = [jnp.asarray(l) for l in jax.tree_util.tree_leaves(tree)]
    bad = [jnp.any(~jnp.isfinite(l)) for l in leaves
           if jnp.issubdtype(l.dtype, jnp.inexact)]
    if not bad:
        return jnp.zeros((), jnp.float32)
    flag = bad[0]
    for b in bad[1:]:
        flag = flag | b
    return flag.astype(jnp.float32)


def guarded_update(grads, opt_update: Callable[[Any, Any], Any], state,
                   guard_state: GuardState, *, axis_name=None,
                   flag=None, found_inf=None, scaler=None,
                   scaler_state=None, recorder=None, recorder_state=None,
                   stats=None, step=None):
    """Commit ``opt_update(grads, state)`` only when the gradients are
    globally finite; otherwise keep ``state`` bit-identical.

    jit-native: the non-finite flag is derived in-graph
    (:func:`nonfinite_flag`), all-reduced over ``axis_name`` with one
    scalar psum (``parallel.distributed.all_reduce_flag`` — every
    replica takes the same branch), and the commit is a ``jnp.where``
    select per leaf. No host sync, no callback.

    Args:
      grads: gradient pytree the flag is derived from. Check the
        *local pre-compression* gradients: an int8-quantized psum can
        launder a replica's NaN into finite garbage on the wire, so
        the flag — not the payload — is what crosses replicas.
      opt_update: ``(grads, state) -> new_state`` computing the
        candidate (optimizer step, EF-residual commit, step counter —
        anything that must NOT advance on a poisoned step). Must
        return the same tree structure as ``state``.
      state: the pytree to protect.
      guard_state: :class:`GuardState` from the previous step
        (:func:`init_guard_state` on step 0).
      axis_name: mesh axis (or tuple) to OR the flag over; ``None``
        for single-replica.
      flag: optionally override the derived flag (f32, >0 = skip) —
        e.g. when the caller already computed it pre-sync.
      found_inf: optional extra overflow flag ORed in (the f32 count
        ``LossScaler.unscale_grads`` returns).
      scaler / scaler_state: when both given, ``scaler.update`` runs
        on the *global* flag and its new state is returned third —
        committed even on skipped steps, because backing the loss
        scale off IS the reaction to the overflow.
      recorder / recorder_state: when both given, this step's
        per-module stats land in the
        :class:`~apex_tpu.telemetry.recorder.FlightRecorder` ring and
        the new ring state is returned LAST. Recording commits
        unconditionally — the poisoned step's stats are the
        post-mortem evidence and are never reverted with the state.
      stats: optional precomputed ``tree_stats(grads, ...)`` dict (the
        DDP ``numerics=`` knob returns one computed on the local
        pre-compression grads — prefer it; deriving here sees only
        what the caller passed as ``grads``).
      step: optional i32 step number stamped into the ring rows
        (defaults to the ring's lifetime record count).

    Returns ``(new_state, new_guard_state)`` — plus
    ``new_scaler_state`` when a scaler was supplied, plus
    ``new_recorder_state`` (always last) when a recorder was supplied.
    """
    with _telemetry_trace.span("guard/update", axis=str(axis_name),
                               scaled=scaler is not None):
        local = nonfinite_flag(grads) if flag is None \
            else jnp.asarray(flag, jnp.float32)
        if found_inf is not None:
            local = jnp.maximum(
                local, (jnp.asarray(found_inf, jnp.float32) > 0)
                .astype(jnp.float32))
        if axis_name is not None:
            from apex_tpu.parallel.distributed import all_reduce_flag

            global_flag = all_reduce_flag(local, axis_name)
        else:
            global_flag = local
        skip = global_flag > 0

        candidate = opt_update(grads, state)
        if (jax.tree_util.tree_structure(candidate)
                != jax.tree_util.tree_structure(state)):
            raise ValueError(
                "guarded_update: opt_update returned a different tree "
                "structure than state — the skip path could not revert "
                f"it ({jax.tree_util.tree_structure(candidate)} vs "
                f"{jax.tree_util.tree_structure(state)})")
        new_state = jax.tree_util.tree_map(
            lambda old, new: jnp.where(skip, old, new), state, candidate)

        skip_i = skip.astype(jnp.int32)
        new_guard = GuardState(
            consecutive_skips=jnp.where(
                skip, guard_state.consecutive_skips + 1, 0)
            .astype(jnp.int32),
            total_skips=(guard_state.total_skips + skip_i)
            .astype(jnp.int32),
            last_skipped=skip_i,
        )
        outs = (new_state, new_guard)
        if scaler is not None:
            if scaler_state is None:
                raise ValueError("guarded_update: scaler given without "
                                 "scaler_state")
            outs = outs + (scaler.update(scaler_state, global_flag),)
        if recorder is not None:
            if recorder_state is None:
                raise ValueError("guarded_update: recorder given without "
                                 "recorder_state")
            if stats is None:
                from apex_tpu.telemetry import numerics as _numerics

                stats = _numerics.tree_stats(
                    grads, prefix_depth=recorder.prefix_depth)
            # unconditional: the ring keeps the poisoned step's stats
            # whether or not the state commit was reverted above
            outs = outs + (recorder.record(
                recorder_state,
                recorder_state.cursor if step is None else step,
                stats),)
        return outs


def guarded_call(fn, *args, oom_dir=None, registry=None, labels=None,
                 **kwargs):
    """Dispatch one (jitted) step under the OOM post-mortem handler.

    ``fn(*args, **kwargs)`` with RESOURCE_EXHAUSTED — the real XLA
    runtime error, or ``faults.inject_alloc_failure``'s synthetic one —
    caught by :func:`telemetry.memory.oom_guard`: the handler writes an
    atomic ``memory-postmortem-rank<N>.json`` (live-buffer census, last
    ``step_memory`` report, headroom trend) into ``oom_dir`` (default
    ``$APEX_TPU_MEMORY_DIR`` -> telemetry dir -> CWD) and re-raises as
    :class:`~apex_tpu.telemetry.memory.HBMExhaustedError`. ``labels``
    (``{"params": params, ...}``) attribute census rows to the caller's
    pytrees. Every other exception passes through untouched; the happy
    path costs one ``try`` — nothing enters the compiled program."""
    from apex_tpu.telemetry import memory as _memory

    with _memory.oom_guard(oom_dir, registry=registry, labels=labels):
        return fn(*args, **kwargs)


def check_guard(guard_state: GuardState,
                max_consecutive_skips: Optional[int] = None, *,
                registry=None, recorder=None, recorder_state=None,
                postmortem_dir=None) -> int:
    """Host-side escalation + telemetry poll for the guard.

    Fetches the three GuardState scalars (the only host sync in the
    guard story — call it every step or every N, it is three i32s),
    reconciles the ``guard/steps_skipped`` counter and
    ``guard/consecutive_skips`` gauge with the device truth, and raises
    :class:`NonFiniteError` once the consecutive-skip streak reaches
    ``max_consecutive_skips`` (default ``$APEX_TPU_GUARD_MAX_SKIPS`` or
    3) — skipping forever just burns a pod on a diverged run.

    When a ``recorder`` + ``recorder_state`` pair (the flight-recorder
    ring this run's ``guarded_update`` has been feeding) is supplied,
    a skipped step fetches the ring ONCE and dumps
    ``numerics-postmortem-rank<N>.json`` into ``postmortem_dir``
    (default ``$APEX_TPU_NUMERICS_DIR``, else the telemetry JSONL dir,
    else the CWD), and the escalation error names the first module
    prefix whose non-finite count went positive — attribution instead
    of a blind death. The dump costs one device->host transfer of the
    small ring, and only ever happens on a step that was already
    skipped.

    Returns the current consecutive-skip count.
    """
    if max_consecutive_skips is None:
        max_consecutive_skips = int(
            os.environ.get(ENV_MAX_SKIPS, str(DEFAULT_MAX_CONSECUTIVE_SKIPS)))
    consecutive = int(guard_state.consecutive_skips)
    total = int(guard_state.total_skips)
    last = int(guard_state.last_skipped)
    reg = registry or get_registry()
    if reg.enabled:
        counter = reg.counter("guard/steps_skipped")
        # counters only go up; reconcile to the device-side total so
        # check_guard may be called every N steps without undercounting
        delta = total - counter.value
        if delta > 0:
            counter.inc(delta)
        reg.gauge("guard/consecutive_skips").set(consecutive)
        if last:
            reg.event("guard", "step_skipped", consecutive=consecutive,
                      total=total)
    escalate = consecutive >= max_consecutive_skips > 0
    postmortem = None
    if recorder is not None and recorder_state is not None \
            and (last or escalate):
        postmortem = recorder.dump_postmortem(
            recorder_state, postmortem_dir,
            reason="escalation" if escalate else "step_skipped",
            registry=reg,
            extra={"consecutive_skips": consecutive,
                   "total_skips": total})
    if escalate:
        if reg.enabled:
            reg.event("guard", "escalate", consecutive=consecutive,
                      total=total, limit=max_consecutive_skips)
        culprit = ""
        if postmortem is not None:
            prefix = postmortem.get("first_nonfinite_prefix")
            if prefix:
                culprit = (
                    f" Flight record: first non-finite stats in module "
                    f"prefix '{prefix}' at step "
                    f"{postmortem.get('first_nonfinite_step')} "
                    f"(post-mortem: {postmortem.get('path')}).")
            elif postmortem.get("path"):
                culprit = (f" Flight record dumped to "
                           f"{postmortem['path']}.")
        raise NonFiniteError(
            f"{consecutive} consecutive optimizer steps skipped on "
            f"non-finite gradients (limit {max_consecutive_skips}; "
            f"{total} skipped in total) — the run is diverging, not "
            f"hitting one bad batch. Inspect the data pipeline / loss "
            f"scale; restore from the last verified checkpoint."
            + culprit)
    return consecutive
