"""Non-finite step guard: skip poisoned optimizer steps in-graph.

Why: one NaN microbatch — an fp16 overflow the scaler missed, a bad
input record, a transient ICI bit flip — poisons every parameter
forever once the optimizer commits it, and a multi-day run only finds
out when the loss curve flatlines. The reference's answer is amp's
host-synced overflow check (apex/amp/scaler.py:200 D2H-syncs the
overflow flag every step); the TPU-native answer must stay inside the
compiled step: no host sync, no callback, nothing XLA can't schedule.

:func:`guarded_update` is that answer. It derives a single
found-non-finite flag from the (pre-update) gradients, ORs it across
the data-parallel replica set with one scalar ``psum`` (every replica
must agree to skip, or params diverge), computes the candidate update
anyway, and commits it with ``jnp.where`` — the skip costs one select
per leaf, not a branch, and composes with donation. The skip decision
also:

- **does not commit dependent state**: whatever pytree the caller
  passes as ``state`` is reverted wholesale on a skipped step. Put the
  ``compress="int8"`` error-feedback residual in there — a residual
  computed from NaN gradients must not feed back into the next step.
- **still drives the loss scaler**: ``scaler.update`` *wants* to see
  the overflow (that is how dynamic scaling backs off), so when a
  ``scaler``/``scaler_state`` pair is supplied its update always
  commits, fed with the global flag.
- carries a consecutive-skip counter in :class:`GuardState` so the
  host can distinguish "one bad batch" (skip and move on) from "the
  run is diverging" (:func:`check_guard` raises
  :class:`NonFiniteError` after K consecutive skips).

Escalation and telemetry are host-side by design: :func:`check_guard`
fetches the three-scalar ``GuardState`` (the only sync, amortizable to
every N steps), lands the ``guard/steps_skipped`` counter and
``guard/consecutive_skips`` gauge in the registry, and raises once the
skip streak crosses the threshold. The compiled step stays clean — the
chaos suite asserts no ``callback`` custom-calls in the lowered HLO.
"""

import os
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.telemetry import trace as _telemetry_trace
from apex_tpu.telemetry.registry import get_registry

ENV_MAX_SKIPS = "APEX_TPU_GUARD_MAX_SKIPS"
DEFAULT_MAX_CONSECUTIVE_SKIPS = 3


class NonFiniteError(RuntimeError):
    """Raised host-side when non-finite gradients persist past the
    consecutive-skip budget (or eagerly by
    ``clip_grad_norm_(..., error_if_nonfinite=True)``)."""


class GuardState(NamedTuple):
    """Skip accounting carried through the jitted step (three i32
    scalars — donate it with the rest of the training state)."""

    consecutive_skips: jnp.ndarray  # i32: current skip streak
    total_skips: jnp.ndarray        # i32: lifetime skipped steps
    last_skipped: jnp.ndarray       # i32: 1 iff the latest step skipped


def init_guard_state() -> GuardState:
    return GuardState(
        consecutive_skips=jnp.zeros((), jnp.int32),
        total_skips=jnp.zeros((), jnp.int32),
        last_skipped=jnp.zeros((), jnp.int32),
    )


def nonfinite_flag(tree) -> jnp.ndarray:
    """f32 scalar: 1.0 iff any inexact leaf of ``tree`` holds a
    non-finite value. One fused reduction per leaf — cheap against the
    backward pass that produced the leaves."""
    leaves = [jnp.asarray(l) for l in jax.tree_util.tree_leaves(tree)]
    bad = [jnp.any(~jnp.isfinite(l)) for l in leaves
           if jnp.issubdtype(l.dtype, jnp.inexact)]
    if not bad:
        return jnp.zeros((), jnp.float32)
    flag = bad[0]
    for b in bad[1:]:
        flag = flag | b
    return flag.astype(jnp.float32)


def guarded_update(grads, opt_update: Callable[[Any, Any], Any], state,
                   guard_state: GuardState, *, axis_name=None,
                   flag=None, found_inf=None, scaler=None,
                   scaler_state=None):
    """Commit ``opt_update(grads, state)`` only when the gradients are
    globally finite; otherwise keep ``state`` bit-identical.

    jit-native: the non-finite flag is derived in-graph
    (:func:`nonfinite_flag`), all-reduced over ``axis_name`` with one
    scalar psum (``parallel.distributed.all_reduce_flag`` — every
    replica takes the same branch), and the commit is a ``jnp.where``
    select per leaf. No host sync, no callback.

    Args:
      grads: gradient pytree the flag is derived from. Check the
        *local pre-compression* gradients: an int8-quantized psum can
        launder a replica's NaN into finite garbage on the wire, so
        the flag — not the payload — is what crosses replicas.
      opt_update: ``(grads, state) -> new_state`` computing the
        candidate (optimizer step, EF-residual commit, step counter —
        anything that must NOT advance on a poisoned step). Must
        return the same tree structure as ``state``.
      state: the pytree to protect.
      guard_state: :class:`GuardState` from the previous step
        (:func:`init_guard_state` on step 0).
      axis_name: mesh axis (or tuple) to OR the flag over; ``None``
        for single-replica.
      flag: optionally override the derived flag (f32, >0 = skip) —
        e.g. when the caller already computed it pre-sync.
      found_inf: optional extra overflow flag ORed in (the f32 count
        ``LossScaler.unscale_grads`` returns).
      scaler / scaler_state: when both given, ``scaler.update`` runs
        on the *global* flag and its new state is returned third —
        committed even on skipped steps, because backing the loss
        scale off IS the reaction to the overflow.

    Returns ``(new_state, new_guard_state)`` — plus
    ``new_scaler_state`` when a scaler was supplied.
    """
    with _telemetry_trace.span("guard/update", axis=str(axis_name),
                               scaled=scaler is not None):
        local = nonfinite_flag(grads) if flag is None \
            else jnp.asarray(flag, jnp.float32)
        if found_inf is not None:
            local = jnp.maximum(
                local, (jnp.asarray(found_inf, jnp.float32) > 0)
                .astype(jnp.float32))
        if axis_name is not None:
            from apex_tpu.parallel.distributed import all_reduce_flag

            global_flag = all_reduce_flag(local, axis_name)
        else:
            global_flag = local
        skip = global_flag > 0

        candidate = opt_update(grads, state)
        if (jax.tree_util.tree_structure(candidate)
                != jax.tree_util.tree_structure(state)):
            raise ValueError(
                "guarded_update: opt_update returned a different tree "
                "structure than state — the skip path could not revert "
                f"it ({jax.tree_util.tree_structure(candidate)} vs "
                f"{jax.tree_util.tree_structure(state)})")
        new_state = jax.tree_util.tree_map(
            lambda old, new: jnp.where(skip, old, new), state, candidate)

        skip_i = skip.astype(jnp.int32)
        new_guard = GuardState(
            consecutive_skips=jnp.where(
                skip, guard_state.consecutive_skips + 1, 0)
            .astype(jnp.int32),
            total_skips=(guard_state.total_skips + skip_i)
            .astype(jnp.int32),
            last_skipped=skip_i,
        )
        if scaler is not None:
            if scaler_state is None:
                raise ValueError("guarded_update: scaler given without "
                                 "scaler_state")
            new_scaler_state = scaler.update(scaler_state, global_flag)
            return new_state, new_guard, new_scaler_state
        return new_state, new_guard


def check_guard(guard_state: GuardState,
                max_consecutive_skips: Optional[int] = None, *,
                registry=None) -> int:
    """Host-side escalation + telemetry poll for the guard.

    Fetches the three GuardState scalars (the only host sync in the
    guard story — call it every step or every N, it is three i32s),
    reconciles the ``guard/steps_skipped`` counter and
    ``guard/consecutive_skips`` gauge with the device truth, and raises
    :class:`NonFiniteError` once the consecutive-skip streak reaches
    ``max_consecutive_skips`` (default ``$APEX_TPU_GUARD_MAX_SKIPS`` or
    3) — skipping forever just burns a pod on a diverged run.

    Returns the current consecutive-skip count.
    """
    if max_consecutive_skips is None:
        max_consecutive_skips = int(
            os.environ.get(ENV_MAX_SKIPS, str(DEFAULT_MAX_CONSECUTIVE_SKIPS)))
    consecutive = int(guard_state.consecutive_skips)
    total = int(guard_state.total_skips)
    last = int(guard_state.last_skipped)
    reg = registry or get_registry()
    if reg.enabled:
        counter = reg.counter("guard/steps_skipped")
        # counters only go up; reconcile to the device-side total so
        # check_guard may be called every N steps without undercounting
        delta = total - counter.value
        if delta > 0:
            counter.inc(delta)
        reg.gauge("guard/consecutive_skips").set(consecutive)
        if last:
            reg.event("guard", "step_skipped", consecutive=consecutive,
                      total=total)
    if consecutive >= max_consecutive_skips > 0:
        if reg.enabled:
            reg.event("guard", "escalate", consecutive=consecutive,
                      total=total, limit=max_consecutive_skips)
        raise NonFiniteError(
            f"{consecutive} consecutive optimizer steps skipped on "
            f"non-finite gradients (limit {max_consecutive_skips}; "
            f"{total} skipped in total) — the run is diverging, not "
            f"hitting one bad batch. Inspect the data pipeline / loss "
            f"scale; restore from the last verified checkpoint.")
    return consecutive
