"""apex_tpu.resilience — surviving the failures a long run will see.

Five pillars (docs/resilience.md has the operational tour):

- :mod:`supervisor` — the recovery loop on top of everything below:
  :class:`Supervisor` wraps a user step function, classifies each
  failure (:func:`classify_failure`) and applies a per-class
  :class:`RecoveryPolicy` — hot-snapshot revert + loss-scale backoff
  for numerics, checkpoint-fallback restore for corruption, one final
  save + clean exit for preemption, a mesh-shrink restart (elastic
  ZeRO re-sharding) for device loss — with bounded restarts, capped
  backoff, and a step-monotonic :class:`StepLedger` proving no step
  was silently lost or double-applied.

- :mod:`guard`      — jit-native non-finite step guard:
  :func:`guarded_update` skips poisoned optimizer steps in-graph (one
  all-reduced scalar flag, ``jnp.where`` commit, no host sync) and
  :func:`check_guard` escalates to :class:`NonFiniteError` after K
  consecutive skips. With a
  :class:`~apex_tpu.telemetry.recorder.FlightRecorder` attached, the
  skip/escalation also dumps a ``numerics-postmortem-rank<N>.json``
  naming the first module prefix that went non-finite
  (telemetry/numerics.py — per-layer stats, still zero host
  callbacks).
- ``checkpoint``    — durability lives in :mod:`apex_tpu.checkpoint`:
  every save writes a ``manifest.json`` (per-leaf shapes/dtypes/crc32 +
  per-file sha256), writes retry with exponential backoff + jitter,
  ``restore`` verifies and walks back through older steps on
  corruption (:class:`~apex_tpu.checkpoint.CheckpointCorruptError`),
  and ``keep_last_n`` prunes only after the new step verified.
- :mod:`preemption` — :class:`PreemptionGuard` turns SIGTERM/SIGINT
  into a pollable checkpoint-now flag plus one final synchronous save.
- :mod:`faults`     — deterministic, env/API-gated injectors (NaN at
  step N, synthetic RESOURCE_EXHAUSTED at step N, partial/torn
  checkpoint writes, byte corruption, simulated SIGTERM) powering the
  tests/L0/test_resilience.py chaos suite.

OOM joins NaN as a post-mortem-producing failure: wrap the step
dispatch in :func:`guarded_call` (or ``telemetry.memory.oom_guard``)
and a RESOURCE_EXHAUSTED writes ``memory-postmortem-rank<N>.json``
(live-buffer census + headroom trend — telemetry/memory.py) before
re-raising as :class:`HBMExhaustedError`, the way :func:`check_guard`
turns persistent NaN skips into an attributed :class:`NonFiniteError`.
"""

from apex_tpu.resilience import faults  # noqa: F401
from apex_tpu.resilience import preemption  # noqa: F401
from apex_tpu.resilience import supervisor  # noqa: F401
from apex_tpu.resilience.faults import DeviceLostError  # noqa: F401
from apex_tpu.resilience.guard import (  # noqa: F401
    GuardState,
    NonFiniteError,
    check_guard,
    guarded_call,
    guarded_update,
    init_guard_state,
    nonfinite_flag,
)
from apex_tpu.resilience.preemption import PreemptionGuard  # noqa: F401
from apex_tpu.resilience.supervisor import (  # noqa: F401
    FailureClass,
    LedgerError,
    RecoveryExhaustedError,
    RecoveryPolicy,
    StepLedger,
    Supervisor,
    classify_failure,
    default_policies,
    loss_scale_backoff,
)
from apex_tpu.telemetry.memory import HBMExhaustedError  # noqa: F401
