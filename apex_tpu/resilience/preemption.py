"""Preemption handling: turn SIGTERM into one final verified checkpoint.

TPU pods are preempted with a SIGTERM and a grace window; a training
loop that ignores it loses everything since the last periodic
checkpoint. :class:`PreemptionGuard` installs SIGTERM/SIGINT handlers
for the duration of a ``with`` block, flips a flag the loop can poll
between steps (``should_checkpoint()``), and — when the block exits
with the flag up and nothing saved yet — runs one final *synchronous*
``save_training_state`` so the state lands inside the grace window.
Prior handlers are restored on exit, whatever happens inside.

Two usage shapes::

    # polled: the loop decides where a step boundary is
    with PreemptionGuard() as guard:
        for step in range(n):
            state = train_step(state)
            if guard.should_checkpoint():
                checkpoint.save_training_state(d, step, **state)
                guard.mark_saved()
                break

    # callback: the guard itself runs the last save on exit
    with PreemptionGuard(final_save=lambda: checkpoint.save_training_state(
            d, current_step(), **snapshot())):
        train()

Signal handlers are a main-thread-only facility in CPython; off the
main thread the guard degrades to poll-only mode (``trigger()`` still
works — the fault injector uses it) with a warning rather than
refusing to run.
"""

import signal
import threading
import warnings
from typing import Callable, Optional

from apex_tpu.telemetry.registry import get_registry


class PreemptionGuard:
    """Context manager bridging SIGTERM/SIGINT to a pollable
    checkpoint-now flag (see module docstring)."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT), *,
                 final_save: Optional[Callable[[], object]] = None):
        self._signals = tuple(signals)
        self._final_save = final_save
        self._prev_handlers = {}
        self._event = threading.Event()
        self._received = None
        self._saved = False
        self._installed = False
        self._counted = False

    # -- signal plumbing ----------------------------------------------------

    def _handler(self, signum, frame):
        # async-signal context: just record; telemetry/saving happen on
        # the training thread at the next poll / on exit
        self._received = signum
        self._event.set()

    def __enter__(self):
        try:
            for sig in self._signals:
                self._prev_handlers[sig] = signal.signal(sig, self._handler)
            self._installed = True
        except ValueError:  # not the main thread
            self._prev_handlers.clear()
            warnings.warn(
                "PreemptionGuard: cannot install signal handlers off the "
                "main thread; running in poll-only mode (trigger() still "
                "flips the flag)")
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            if (exc_type is None and self.preempted and not self._saved
                    and self._final_save is not None):
                self.save_now()
        finally:
            if self._installed:
                for sig, prev in self._prev_handlers.items():
                    signal.signal(sig, prev)
                self._prev_handlers.clear()
                self._installed = False
        return False

    # -- the loop-facing surface --------------------------------------------

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    @property
    def signum(self):
        """The signal that triggered, or None."""
        return self._received

    def trigger(self, signum=signal.SIGTERM):
        """Flip the flag programmatically (fault injection / tests /
        cluster agents that learn of preemption out-of-band)."""
        self._handler(signum, None)

    def should_checkpoint(self) -> bool:
        """True once preempted and the final checkpoint has not been
        written yet — the per-step poll."""
        if not self._event.is_set():
            return False
        if not self._counted:  # first poll after the signal: record it
            self._counted = True
            reg = get_registry()
            if reg.enabled:
                reg.counter("preemption/signals").inc()
                reg.event("preemption", "signal", signum=self._received)
        return not self._saved

    def mark_saved(self):
        """Tell the guard the final checkpoint landed (suppresses the
        exit-time ``final_save``)."""
        self._saved = True
        self._record("saved")

    def save_now(self):
        """Run the ``final_save`` callable synchronously, once."""
        if self._final_save is None:
            raise ValueError("PreemptionGuard: no final_save callable given")
        if self._saved:
            return
        self._record("final_save")
        self._final_save()
        self._saved = True

    def wait(self, timeout=None) -> bool:
        """Block until preempted (tests / driver threads)."""
        return self._event.wait(timeout)

    def _record(self, what):
        reg = get_registry()
        if reg.enabled:
            reg.event("preemption", what, signum=self._received)
