"""Training supervisor: automatic recovery, not just detection.

The rest of the resilience subsystem is a *detection* stack — the step
guard skips poisoned steps and escalates ``NonFiniteError``, the OOM
guard dies with an attributed ``HBMExhaustedError``, restore rejects
corrupt checkpoints — but every escalation still kills the run, and a
kill loses every step since the last disk checkpoint. The
:class:`Supervisor` closes the loop: it wraps a user step function and
composes the existing primitives into a policy-driven recovery loop,
so the failure classes a long run WILL see each cost a bounded number
of replayed steps instead of the run.

Per failure class (:func:`classify_failure`), a
:class:`RecoveryPolicy` names the reaction:

- ``numerics`` (``NonFiniteError`` escalation) — revert to the latest
  **hot snapshot** and optionally back the loss scale off
  (:func:`loss_scale_backoff`); the poisoned streak is replayed from
  known-good state.
- ``oom`` (``HBMExhaustedError`` / RESOURCE_EXHAUSTED) — revert to the
  hot snapshot (transient fragmentation / shape spike) with an
  ``adjust`` hook for callers that want to shrink the batch.
- ``checkpoint_corrupt`` (``CheckpointCorruptError``, e.g. a torn
  write caught by post-save verification) — restore from the last
  *good* step via ``checkpoint.restore``'s existing fallback chain,
  auditing what was actually loaded through the restore metadata.
- ``preemption`` (polled from a
  :class:`~apex_tpu.resilience.preemption.PreemptionGuard`) — one
  final synchronous checkpoint, then a clean exit the driver can
  resume from (:meth:`Supervisor.restore_from_checkpoint`).
- ``device_loss`` (:class:`~apex_tpu.resilience.faults.DeviceLostError`
  / a PJRT ``DEVICE_LOST``) — a **mesh-shrink restart**: the caller's
  ``rebuild(world, host_state, step)`` hook reconstructs the step
  function on the surviving mesh, re-partitioning ZeRO shards via
  ``DistributedFusedAdam.load_state_dict_resharded``.

Two mechanisms make recovery cheap and provable:

1. **Hot snapshots** (:class:`HotSnapshots`): every ``snapshot_every``
   steps the full training state — params, optimizer state including
   the int8 EF residual, RNG, ``GuardState``, flight-recorder ring,
   whatever the caller put in the state pytree — is copied to host RAM
   (one ``jax.device_get``). A snapshot restore is a host-memory
   assignment: milliseconds, no disk, and it survives device loss
   because the copy lives on the host. Disk checkpoints remain the
   durable tier below (``checkpoint_every``); the snapshot cadence
   bounds MTTR in steps, the checkpoint cadence bounds loss on a full
   process death.
2. **The step ledger** (:class:`StepLedger`): every applied step and
   every rollback is recorded, with apply order enforced at record
   time — a step applied out of order (silently lost or double-applied
   after a botched restore) raises :class:`LedgerError` immediately,
   and :meth:`StepLedger.verify` replays the whole event log as the
   end-of-run proof that the surviving lineage is exactly
   ``start..final`` with each step applied once.

Restarts are bounded (per-class ``max_restarts`` + a global
``max_restarts_total``) with capped exponential backoff between
attempts; exhaustion raises :class:`RecoveryExhaustedError` chaining
the final failure. Telemetry: ``recovery/restarts`` /
``snapshot_restores`` / ``checkpoint_restores`` / ``mesh_shrinks`` /
``steps_lost`` counters, a per-class ``recovery/cause/<class>``
histogram-by-counter, the ``recovery/mttr_steps`` gauge, and
``recovery`` JSONL events (``failure`` / ``recovered`` / ``gave_up`` /
``snapshot`` / ``preempted_exit``) that ``tools/telemetry_report.py``
rolls up. ``tools/chaos_run.py`` sweeps the fault injectors over a
guarded DDP+ZeRO run and asserts the per-class invariants;
docs/resilience.md ("Supervised training") has the operational tour.
"""

import time
import warnings
from typing import Any, Callable, Dict, Optional

import jax

from apex_tpu.resilience.faults import DeviceLostError
from apex_tpu.resilience.guard import NonFiniteError
from apex_tpu.telemetry.registry import get_registry
from apex_tpu.telemetry.trace import span, trace_context

# -- failure classes ---------------------------------------------------------


class FailureClass:
    """The failure taxonomy the supervisor routes on (plain strings so
    policies/telemetry/JSON stay trivially serializable)."""

    NUMERICS = "numerics"
    OOM = "oom"
    CHECKPOINT = "checkpoint_corrupt"
    PREEMPTION = "preemption"
    DEVICE_LOSS = "device_loss"
    UNKNOWN = "unknown"

    ALL = (NUMERICS, OOM, CHECKPOINT, PREEMPTION, DEVICE_LOSS, UNKNOWN)


def classify_failure(exc: BaseException) -> str:
    """Map an exception from the supervised step (or the supervisor's
    own checkpoint I/O) to a :class:`FailureClass` constant. Typed
    errors from the resilience/telemetry stack classify exactly;
    untyped runtime errors fall back to the markers the runtimes put in
    their messages (``DEVICE_LOST``, ``RESOURCE_EXHAUSTED``)."""
    from apex_tpu.checkpoint import CheckpointCorruptError
    from apex_tpu.telemetry.memory import HBMExhaustedError, is_oom_error

    if isinstance(exc, NonFiniteError):
        return FailureClass.NUMERICS
    if isinstance(exc, DeviceLostError):
        return FailureClass.DEVICE_LOSS
    if isinstance(exc, CheckpointCorruptError):
        return FailureClass.CHECKPOINT
    if isinstance(exc, HBMExhaustedError) or is_oom_error(exc):
        return FailureClass.OOM
    if "DEVICE_LOST" in str(exc):
        return FailureClass.DEVICE_LOSS
    return FailureClass.UNKNOWN


class RecoveryExhaustedError(RuntimeError):
    """The restart budget (per-class or total) ran out; the original
    failure is chained as ``__cause__``. At this point a human (or the
    cluster scheduler) owns the run again."""


class LedgerError(RuntimeError):
    """The step ledger caught a non-monotonic apply — a step silently
    lost or double-applied. This is a supervisor bug surfacing, never
    something to recover from."""


# -- the step ledger ---------------------------------------------------------


class StepLedger:
    """Append-only audit log proving step-application integrity.

    Invariant, enforced at record time: in the *surviving lineage*
    (applies minus rolled-back suffixes), step ``s`` is applied exactly
    when the previous applied step was ``s - 1``. A rollback names the
    step the timeline truncates back to; replayed steps then re-apply
    in order. :meth:`verify` independently replays the event log so the
    proof does not rest on the same counter that enforced it.
    """

    def __init__(self, start_step: int = 0):
        self.start_step = int(start_step)
        self._next = int(start_step)
        self.events = [("start", int(start_step), None)]
        self.applies = 0
        self.rollbacks = 0

    @property
    def next_step(self) -> int:
        """The only step the lineage can legally apply next."""
        return self._next

    def record_apply(self, step: int) -> None:
        step = int(step)
        if step != self._next:
            what = "double-applied" if step < self._next else "lost"
            raise LedgerError(
                f"step {step} applied out of order — the lineage "
                f"expected {self._next} (a step was silently {what})")
        self.events.append(("apply", step, None))
        self._next = step + 1
        self.applies += 1

    def record_rollback(self, to_step: int, cause: Optional[str] = None
                        ) -> int:
        """Truncate the lineage back to ``to_step`` (the next step to
        apply). Returns the number of applied steps rolled back."""
        to_step = int(to_step)
        if not self.start_step <= to_step <= self._next:
            raise LedgerError(
                f"rollback to step {to_step} is outside the lineage "
                f"[{self.start_step}, {self._next}]")
        lost = self._next - to_step
        self.events.append(("rollback", to_step, cause))
        self._next = to_step
        self.rollbacks += 1
        return lost

    def verify(self, expect_next: Optional[int] = None) -> Dict[str, Any]:
        """Replay the event log and prove the lineage: applies strictly
        monotonic, each rollback inside the lineage, final next-step
        equal to ``expect_next`` when given. Raises :class:`LedgerError`
        on any violation; returns the summary dict."""
        cur = None
        for kind, step, _ in self.events:
            if kind == "start":
                cur = step
            elif kind == "apply":
                if step != cur:
                    raise LedgerError(
                        f"ledger replay: apply({step}) where {cur} was "
                        f"expected")
                cur = step + 1
            elif kind == "rollback":
                if not self.start_step <= step <= cur:
                    raise LedgerError(
                        f"ledger replay: rollback({step}) outside "
                        f"[{self.start_step}, {cur}]")
                cur = step
        if cur != self._next:
            raise LedgerError(
                f"ledger replay ended at {cur}, counter says {self._next}")
        if expect_next is not None and cur != int(expect_next):
            raise LedgerError(
                f"lineage ends at step {cur}, expected {int(expect_next)}"
                " — steps were lost")
        return {"monotonic": True, "start_step": self.start_step,
                "next_step": cur, "applies": self.applies,
                "rollbacks": self.rollbacks, "events": len(self.events)}


# -- policies ---------------------------------------------------------------


class RecoveryPolicy:
    """What to do when a failure of one class lands.

    ``action``: ``"snapshot_restore"`` (revert to the latest hot
    snapshot; falls back to ``checkpoint_restore`` when no snapshot
    exists yet), ``"checkpoint_restore"`` (the disk fallback chain),
    ``"mesh_shrink"`` (rebuild on a smaller world via the supervisor's
    ``rebuild`` hook), or ``"reraise"`` (no recovery — the class is
    fatal by policy).

    ``max_restarts`` bounds recoveries of this class per run;
    ``backoff_base_s``/``backoff_cap_s`` shape the capped exponential
    wait before re-dispatch. ``adjust`` (``(host_state, exc) ->
    host_state``) edits the restored state before replay — the
    loss-scale backoff hook for numerics, a batch-shrink hook for OOM.
    ``shrink_to`` pins the post-loss world size for ``mesh_shrink``
    (default: the error's own ``shrink_to``, else ``world // 2``)."""

    ACTIONS = ("snapshot_restore", "checkpoint_restore", "mesh_shrink",
               "reraise")

    def __init__(self, action: str, *, max_restarts: int = 3,
                 backoff_base_s: float = 0.05, backoff_cap_s: float = 2.0,
                 adjust: Optional[Callable[[Any, BaseException], Any]] = None,
                 shrink_to: Optional[int] = None):
        if action not in self.ACTIONS:
            raise ValueError(f"RecoveryPolicy: unknown action {action!r} "
                             f"(want one of {self.ACTIONS})")
        self.action = action
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.adjust = adjust
        self.shrink_to = shrink_to

    def backoff(self, attempt: int) -> float:
        """Capped exponential backoff for the ``attempt``-th recovery
        of this class (1-based)."""
        return min(self.backoff_cap_s,
                   self.backoff_base_s * (2 ** max(attempt - 1, 0)))

    def __repr__(self):
        return (f"RecoveryPolicy({self.action!r}, "
                f"max_restarts={self.max_restarts})")


def loss_scale_backoff(key: str = "loss_scale", factor: float = 0.5,
                       min_scale: float = 1.0):
    """An ``adjust`` hook for the numerics policy: multiply the state's
    ``key`` leaf (when present) by ``factor``, flooring at
    ``min_scale`` — replaying the poisoned stretch at a lower loss
    scale is the reaction that actually removes an overflow cause,
    where a bare replay would re-diverge."""
    import numpy as np

    def adjust(host_state, exc):
        if isinstance(host_state, dict) and key in host_state:
            cur = np.asarray(host_state[key], np.float32)
            host_state = dict(host_state)
            host_state[key] = np.maximum(cur * factor,
                                         np.float32(min_scale))
        return host_state

    return adjust


def default_policies() -> Dict[str, RecoveryPolicy]:
    """The per-class defaults the ISSUE's failure matrix names. Callers
    override per class by passing ``policies={cls: RecoveryPolicy(...)}``
    to :class:`Supervisor` (missing classes keep these)."""
    return {
        FailureClass.NUMERICS: RecoveryPolicy(
            "snapshot_restore", max_restarts=3,
            adjust=loss_scale_backoff()),
        FailureClass.OOM: RecoveryPolicy("snapshot_restore",
                                         max_restarts=3),
        FailureClass.CHECKPOINT: RecoveryPolicy("checkpoint_restore",
                                                max_restarts=3),
        FailureClass.DEVICE_LOSS: RecoveryPolicy("mesh_shrink",
                                                 max_restarts=2),
        FailureClass.UNKNOWN: RecoveryPolicy("reraise", max_restarts=0),
    }


# -- hot snapshots -----------------------------------------------------------


class Snapshot:
    """One host-RAM copy of the full training state, taken *entering*
    ``step`` (restoring it means the next step to run is ``step``)."""

    __slots__ = ("step", "state", "world")

    def __init__(self, step, state, world=None):
        self.step = int(step)
        self.state = state
        self.world = world


class HotSnapshots:
    """A bounded stack of host-RAM state copies — the fast recovery
    tier above disk checkpoints. ``take`` is one ``jax.device_get``
    (synchronous D2H, donation-safe for the step that follows);
    ``latest``/``restore`` cost a container copy, no device transfer —
    the arrays go back to the device lazily on the next dispatch."""

    def __init__(self, keep: int = 2):
        if keep < 1:
            raise ValueError(f"HotSnapshots: keep must be >= 1, got {keep}")
        self.keep = int(keep)
        self._snaps = []

    def take(self, step: int, state, world=None) -> Snapshot:
        snap = Snapshot(step, jax.device_get(state), world)
        self._snaps.append(snap)
        del self._snaps[:-self.keep]
        return snap

    def latest(self) -> Optional[Snapshot]:
        return self._snaps[-1] if self._snaps else None

    def clear(self) -> None:
        self._snaps.clear()

    def __len__(self):
        return len(self._snaps)

    @staticmethod
    def copy_state(snap: Snapshot):
        """A fresh container tree over the snapshot's (immutable host)
        arrays, so an ``adjust`` hook editing the restored state never
        mutates the snapshot itself."""
        return jax.tree_util.tree_map(lambda x: x, snap.state)


# -- world-size helpers (2-D (data, model) meshes carry tuple worlds) -------


def _canon_world(world):
    """An int dp world stays an int; a ``(dp, tp)`` (or deeper) mesh
    shape becomes a tuple of ints — the form the ``rebuild`` hook and
    the elastic 2-D ZeRO reshard consume."""
    if isinstance(world, (tuple, list)):
        return tuple(int(w) for w in world)
    return int(world)


def _world_size(world):
    """Total replica count of an int or tuple world (telemetry gauge)."""
    if isinstance(world, (tuple, list)):
        n = 1
        for w in world:
            n *= int(w)
        return n
    return int(world)


def _half_world(world):
    """The default shrink target when neither the error nor the policy
    pins one: halve an int world; on a tuple world halve the LAST axis
    whose size exceeds 1, falling back leftward. Axis order in the
    tuple therefore IS the give-up policy: ``(data, model)`` halves
    the model axis first (``(2, 4) -> (2, 2)``); a 3-D
    ``(data, model, pipe)`` world gives up the pipe axis first
    (``(2, 2, 2) -> (2, 2, 1)``), the model axis second — pipeline
    bubbles are the cheapest capability to lose, and the elastic 3-D
    ZeRO table (:func:`~apex_tpu.contrib.optimizers.
    distributed_fused_adam.reshard_zero_state_3d`) restores onto the
    shrunk topology bit-identically."""
    if isinstance(world, (tuple, list)):
        axes = [int(w) for w in world]
        for i in reversed(range(len(axes))):
            if axes[i] > 1:
                axes[i] = max(1, axes[i] // 2)
                return tuple(axes)
        return tuple(axes)
    return max(1, (world or 2) // 2)


def _world_json(world):
    """Tuples -> lists so the topology record stays JSON-serializable."""
    if isinstance(world, (tuple, list)):
        return [int(w) for w in world]
    return int(world)


# -- the supervisor ----------------------------------------------------------


class Supervisor:
    """Run ``step_fn`` under automatic failure recovery.

    ``step_fn(state, step) -> new_state`` is the user's whole training
    step — dispatch, ``check_guard`` escalation poll, anything that can
    raise. ``state`` is one pytree holding EVERYTHING a restore must
    bring back (params, optimizer state incl. the EF residual, RNG,
    ``GuardState``, flight-recorder ring): the supervisor snapshots,
    checkpoints, and restores it as a unit.

    Knobs: ``snapshot_every`` / ``snapshot_keep`` (hot-snapshot tier),
    ``checkpoint_dir`` / ``checkpoint_every`` / ``keep_last_n``
    (durable tier; also the preemption exit target), ``policies``
    (per-class overrides merged over :func:`default_policies`),
    ``max_restarts_total`` (global cap over all classes),
    ``preemption_guard`` (a
    :class:`~apex_tpu.resilience.preemption.PreemptionGuard` polled at
    every step boundary), ``rebuild(world, host_state, step) ->
    (step_fn, state)`` (the mesh-shrink hook — re-partition ZeRO
    shards with ``load_state_dict_resharded`` in there), ``topology``
    (recorded in every checkpoint so an elastic restore knows the
    writing world size), ``sleep`` (injectable backoff clock for
    tests).

    :meth:`run` returns the report dict (exit reason, restart/cause
    accounting, MTTR, goodput ratio, the verified ledger summary);
    the live state stays at :attr:`state`.
    """

    def __init__(self, step_fn: Callable[[Any, int], Any], state, *,
                 policies: Optional[Dict[str, RecoveryPolicy]] = None,
                 snapshot_every: int = 10, snapshot_keep: int = 2,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: Optional[int] = None,
                 keep_last_n: int = 3,
                 max_restarts_total: int = 16,
                 preemption_guard=None,
                 rebuild: Optional[Callable[[int, Any, int], Any]] = None,
                 world: Optional[int] = None,
                 topology: Optional[Dict[str, Any]] = None,
                 start_step: int = 0,
                 registry=None,
                 snapshot_ok: Optional[Callable[[Any], bool]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._step_fn = step_fn
        self.state = state
        self.policies = dict(default_policies())
        self.policies.update(policies or {})
        self.snapshot_every = int(snapshot_every)
        self.snapshots = HotSnapshots(keep=snapshot_keep)
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = (int(checkpoint_every)
                                 if checkpoint_every else None)
        self.keep_last_n = keep_last_n
        self.max_restarts_total = int(max_restarts_total)
        self.preemption = preemption_guard
        self.rebuild = rebuild
        # int for a 1-D dp mesh, a (data, model) tuple for a 2-D one —
        # the shrink default halves the model axis first (_half_world)
        self.world = None if world is None else _canon_world(world)
        self.topology = dict(topology) if topology else None
        self.step = int(start_step)
        self.ledger = StepLedger(start_step)
        self._registry = registry
        # "don't snapshot a state you wouldn't want to restore": e.g.
        # reject states whose GuardState shows a live skip streak — a
        # snapshot taken mid-streak freezes the skipped (uncommitted)
        # steps out of the lineage, so the post-recovery replay could
        # never match the clean run
        self.snapshot_ok = snapshot_ok
        self._sleep = sleep
        # accounting
        self.restarts = 0
        self.restarts_by_class = {c: 0 for c in FailureClass.ALL}
        self.causes = {}
        self.snapshot_restores = 0
        self.checkpoint_restores = 0
        self.mesh_shrinks = 0
        self.steps_lost = 0
        self.dispatches = 0
        self.last_restore_meta = None

    # -- telemetry ------------------------------------------------------

    def _reg(self):
        return self._registry or get_registry()

    def _event(self, name, **fields):
        reg = self._reg()
        if reg.enabled:
            reg.event("recovery", name, **fields)

    def _count(self, name, amount=1):
        reg = self._reg()
        if reg.enabled:
            reg.counter(name).inc(amount)

    # -- durable tier ---------------------------------------------------

    def save_checkpoint(self) -> str:
        """One verified disk checkpoint of the current state at the
        current step (the durable tier; also the preemption exit).
        Raises ``CheckpointCorruptError`` when the landed bytes fail
        verification (e.g. a torn write) — which :meth:`run` treats as
        a recoverable ``checkpoint_corrupt`` failure."""
        from apex_tpu import checkpoint as _ckpt

        if self.checkpoint_dir is None:
            raise ValueError("Supervisor: no checkpoint_dir configured")
        host = jax.device_get(self.state)
        payload = {"state": host,
                   "supervisor": {"step": self.step,
                                  "topology": self.topology or {}}}
        path = _ckpt.save(self.checkpoint_dir, self.step, payload,
                          use_orbax=False)
        _ckpt.verify_checkpoint(path)  # a torn write dies HERE, loudly
        if self.keep_last_n:
            _ckpt._prune_old_steps(self.checkpoint_dir, self.keep_last_n)
        self._event("checkpoint_saved", step=self.step, path=path)
        return path

    def restore_from_checkpoint(self):
        """Load the newest *good* checkpoint through the fallback chain,
        reset the run to its step, and return the restore metadata
        (settled step, rejected steps) for the audit trail. Used both
        for in-run corruption recovery and for resuming a fresh
        process after a preemption exit."""
        from apex_tpu import checkpoint as _ckpt

        if self.checkpoint_dir is None:
            raise ValueError("Supervisor: no checkpoint_dir configured")
        payload, meta = _ckpt.restore(self.checkpoint_dir,
                                      with_metadata=True)
        step = int(payload.get("supervisor", {}).get(
            "step", meta["settled_step"]))
        self.state = payload["state"]
        if step <= self.ledger.next_step:
            lost = self.ledger.record_rollback(step,
                                               cause="checkpoint_restore")
        else:
            # a fresh process resuming a previous run's checkpoint: the
            # lineage restarts at the restored step
            self.ledger = StepLedger(step)
            lost = 0
        self.step = step
        self.steps_lost += lost
        self._last_restore_lost = lost
        self.last_restore_meta = meta
        saved_topo = payload.get("supervisor", {}).get("topology") or None
        if saved_topo and self.topology and saved_topo != self.topology:
            warnings.warn(
                f"Supervisor: checkpoint topology {saved_topo} differs "
                f"from the run's {self.topology} — an elastic "
                "(re-sharded) restore is required; make sure the "
                "rebuild/restore path re-partitioned the shards")
        return meta

    # -- the loop -------------------------------------------------------

    def run(self, num_steps: int) -> Dict[str, Any]:
        """Supervise ``num_steps`` steps (absolute: the loop ends when
        the lineage reaches step ``num_steps``). Returns the report."""
        exit_reason = "completed"
        while self.step < num_steps:
            if self.preemption is not None \
                    and self.preemption.should_checkpoint():
                if self.checkpoint_dir is not None:
                    self.save_checkpoint()
                    self.preemption.mark_saved()
                self.causes[FailureClass.PREEMPTION] = \
                    self.causes.get(FailureClass.PREEMPTION, 0) + 1
                self._count("recovery/cause/preemption")
                self._event("preempted_exit", step=self.step,
                            saved=self.checkpoint_dir is not None)
                exit_reason = "preempted"
                break
            try:
                if self.snapshot_every and \
                        self.step % self.snapshot_every == 0 \
                        and (self.snapshot_ok is None
                             or self.snapshot_ok(self.state)):
                    self.snapshots.take(self.step, self.state, self.world)
                    self._event("snapshot", step=self.step,
                                kept=len(self.snapshots))
                if self.checkpoint_dir is not None and self.checkpoint_every \
                        and self.step % self.checkpoint_every == 0:
                    self.save_checkpoint()
                self.dispatches += 1
                # one trace per dispatched step: phase spans the step
                # function opens at trace time (overlap psum buckets,
                # 1F1B microbatch ticks, ZeRO reduce/gather, ddp/sync)
                # join this context, so the compiling call's timeline
                # is a causal tree under train/step. Telemetry off:
                # trace_context yields None, span records nothing —
                # the compiled program never sees any of this.
                with trace_context(registry=self._reg()), \
                        span("train/step", registry=self._reg(),
                             step=self.step):
                    new_state = self._step_fn(self.state, self.step)
            except (KeyboardInterrupt, LedgerError,
                    RecoveryExhaustedError):
                raise
            except Exception as e:  # noqa: BLE001 — the classify point
                self._recover(e)
                continue
            self.state = new_state
            self.ledger.record_apply(self.step)
            self.step += 1
            reg = self._reg()
            if reg.enabled and self.dispatches:
                # keep the goodput ratio live (not just end-of-run) so
                # the monitor's goodput-drop rule sees it in-window
                reg.gauge("recovery/goodput_step_ratio").set(
                    (self.step - self.ledger.start_step)
                    / self.dispatches)
        report = self._report(exit_reason)
        reg = self._reg()
        if reg.enabled:
            reg.gauge("recovery/mttr_steps").set(report["mttr_steps"])
            reg.gauge("recovery/goodput_step_ratio").set(
                report["goodput_step_ratio"])
        self._event("run_done", **{k: report[k] for k in (
            "exit", "final_step", "restarts", "snapshot_restores",
            "checkpoint_restores", "mesh_shrinks", "steps_lost",
            "mttr_steps", "goodput_step_ratio")})
        return report

    def _report(self, exit_reason):
        recoveries = max(self.restarts, 1)
        applied = self.step - self.ledger.start_step
        return {
            "exit": exit_reason,
            "final_step": self.step,
            "world": self.world,
            "restarts": self.restarts,
            "causes": dict(self.causes),
            "snapshot_restores": self.snapshot_restores,
            "checkpoint_restores": self.checkpoint_restores,
            "mesh_shrinks": self.mesh_shrinks,
            "steps_lost": self.steps_lost,
            "mttr_steps": (self.steps_lost / recoveries
                           if self.restarts else 0.0),
            "dispatches": self.dispatches,
            "goodput_step_ratio": (applied / self.dispatches
                                   if self.dispatches else 1.0),
            "ledger": self.ledger.verify(expect_next=self.step),
        }

    # -- recovery -------------------------------------------------------

    def _recover(self, exc: BaseException) -> None:
        cls = classify_failure(exc)
        self.causes[cls] = self.causes.get(cls, 0) + 1
        policy = self.policies.get(cls) or \
            self.policies[FailureClass.UNKNOWN]
        self._count("recovery/restarts")
        self._count(f"recovery/cause/{cls}")
        reg = self._reg()
        if reg.enabled:
            # live-monitor feed: 1 from failure until the recovery
            # lands (a gave_up raise leaves it raised — correctly: the
            # run is down). telemetry.monitor escalates the failure
            # event to an alert and resolves it off this gauge.
            reg.gauge("recovery/in_recovery").set(1)
        self._event("failure", cls=cls, step=self.step,
                    action=policy.action,
                    error=f"{type(exc).__name__}: {str(exc)[:300]}")
        self.restarts += 1
        self.restarts_by_class[cls] = attempt = \
            self.restarts_by_class.get(cls, 0) + 1
        if policy.action == "reraise":
            self._event("gave_up", cls=cls, step=self.step,
                        reason="policy_reraise")
            raise exc
        if attempt > policy.max_restarts \
                or self.restarts > self.max_restarts_total:
            self._event("gave_up", cls=cls, step=self.step,
                        reason="budget_exhausted", attempts=attempt,
                        total=self.restarts)
            raise RecoveryExhaustedError(
                f"{cls} failure at step {self.step} exhausted the "
                f"restart budget (class attempt {attempt}/"
                f"{policy.max_restarts}, total {self.restarts}/"
                f"{self.max_restarts_total})") from exc
        wait = policy.backoff(attempt)
        if wait > 0:
            self._sleep(wait)
        action = policy.action
        if action == "snapshot_restore" and self.snapshots.latest() is None:
            # nothing hot yet: degrade to the durable tier if it exists
            action = ("checkpoint_restore" if self.checkpoint_dir
                      else "snapshot_restore")
        if action == "snapshot_restore":
            snap = self.snapshots.latest()
            if snap is None:
                self._event("gave_up", cls=cls, step=self.step,
                            reason="no_restore_tier")
                raise RecoveryExhaustedError(
                    f"{cls} failure at step {self.step} but no hot "
                    "snapshot and no checkpoint_dir to restore from"
                ) from exc
            state = HotSnapshots.copy_state(snap)
            if policy.adjust is not None:
                state = policy.adjust(state, exc)
            lost = self.ledger.record_rollback(snap.step, cause=cls)
            self.state = state
            self.step = snap.step
            self.steps_lost += lost
            self.snapshot_restores += 1
            self._count("recovery/snapshot_restores")
            self._count("recovery/steps_lost", lost)
            self._event("recovered", cls=cls, action="snapshot_restore",
                        resume_step=snap.step, steps_lost=lost,
                        attempt=attempt)
        elif action == "checkpoint_restore":
            try:
                meta = self.restore_from_checkpoint()
            except Exception as restore_exc:
                self._event("gave_up", cls=cls, step=self.step,
                            reason="restore_failed",
                            error=str(restore_exc)[:300])
                raise RecoveryExhaustedError(
                    f"{cls} failure at step {self.step} and the "
                    f"checkpoint restore failed too "
                    f"({type(restore_exc).__name__}: {restore_exc})"
                ) from exc
            if policy.adjust is not None:
                self.state = policy.adjust(self.state, exc)
            self.checkpoint_restores += 1
            self._count("recovery/checkpoint_restores")
            self._count("recovery/steps_lost",
                        getattr(self, "_last_restore_lost", 0))
            self._event("recovered", cls=cls, action="checkpoint_restore",
                        resume_step=self.step,
                        steps_lost=getattr(self, "_last_restore_lost", 0),
                        settled_step=meta["settled_step"],
                        rejected_steps=[r["step"]
                                        for r in meta["rejected"]],
                        attempt=attempt)
        elif action == "mesh_shrink":
            if self.rebuild is None:
                self._event("gave_up", cls=cls, step=self.step,
                            reason="no_rebuild_hook")
                raise RecoveryExhaustedError(
                    f"{cls} failure at step {self.step} wants a "
                    "mesh-shrink restart but no rebuild hook was given"
                ) from exc
            snap = self.snapshots.latest()
            if snap is None:
                self._event("gave_up", cls=cls, step=self.step,
                            reason="no_snapshot_for_shrink")
                raise RecoveryExhaustedError(
                    f"{cls} failure at step {self.step} but no hot "
                    "snapshot to rebuild from") from exc
            new_world = _canon_world(
                getattr(exc, "shrink_to", None)
                or policy.shrink_to
                or _half_world(self.world))
            host_state = HotSnapshots.copy_state(snap)
            if policy.adjust is not None:
                host_state = policy.adjust(host_state, exc)
            self._step_fn, self.state = self.rebuild(
                new_world, host_state, snap.step)
            lost = self.ledger.record_rollback(snap.step, cause=cls)
            self.step = snap.step
            self.steps_lost += lost
            self.world = new_world
            if self.topology is not None:
                self.topology = dict(self.topology,
                                     world=_world_json(new_world))
            self.snapshots.clear()  # old-world layouts must not restore
            self.mesh_shrinks += 1
            self._count("recovery/mesh_shrinks")
            self._count("recovery/steps_lost", lost)
            reg = self._reg()
            if reg.enabled:
                reg.gauge("recovery/world").set(_world_size(new_world))
            self._event("recovered", cls=cls, action="mesh_shrink",
                        resume_step=snap.step, steps_lost=lost,
                        world=_world_json(new_world), attempt=attempt)
        reg = self._reg()
        if reg.enabled:
            reg.gauge("recovery/in_recovery").set(0)
