"""Deterministic fault injection for the resilience chaos suite.

Every injector is explicit and deterministic — a fault fires at the
step / call you named, never randomly — so a chaos test is a regular
regression test. Gating is API-first (call the injector) with env
escape hatches for end-to-end drills from the bench/capture drivers:

- ``APEX_TPU_FAULT_NAN_STEP=<n>`` — :func:`nan_step_from_env`, read by
  ``bench.bench_ddp_resilience`` and anything else calling
  :func:`inject_nan` with ``nan_step=None``.
- ``APEX_TPU_FAULT_CKPT_WRITE_FAILURES=<n>`` — default failure count
  for :func:`failing_checkpoint_writes`.
- ``APEX_TPU_FAULT_ALLOC_STEP=<n>`` — :func:`alloc_step_from_env`,
  read by ``bench.bench_ddp_memwatch`` and anything else calling
  :func:`inject_alloc_failure` with ``alloc_step=None``.

Injector catalogue:

- :func:`inject_nan` — jit-native NaN poisoning of a grad pytree at
  one chosen step (a ``jnp.where`` on the step counter; compiles into
  the step, costs one select when armed, is the identity when not).
- :func:`inject_alloc_failure` — host-side synthetic
  ``RESOURCE_EXHAUSTED`` at one chosen step (a real HBM exhaustion is
  raised by the runtime at dispatch, so the injector fires on the host
  just before it), making the OOM post-mortem path
  (``telemetry.memory.oom_guard`` / ``resilience.guarded_call``)
  testable on CPU — the allocation sibling of :func:`inject_nan`.
- :func:`failing_checkpoint_writes` — the next N checkpoint writes die
  after flushing a few real payload bytes into the temp location
  (transient disk/FS failure; nothing lands, exercising the retry path
  and ``AsyncCheckpointer`` error surfacing).
- :func:`torn_checkpoint_write` — the next checkpoint write LANDS, but
  with a truncated ``state.pkl`` behind a manifest describing the full
  intended bytes (a crash/power-cut that lost the file tail):
  ``restore`` must reject the step and fall back.
- :func:`corrupt_checkpoint` — flip bytes in a landed checkpoint's
  payload in place (bit rot / torn sector).
- :func:`simulate_preemption` — raise a real SIGTERM in-process, which
  a :class:`~apex_tpu.resilience.preemption.PreemptionGuard` fields.
"""

import contextlib
import os
import pickle
import signal

import jax.numpy as jnp
from jax import tree_util

ENV_NAN_STEP = "APEX_TPU_FAULT_NAN_STEP"
ENV_CKPT_WRITE_FAILURES = "APEX_TPU_FAULT_CKPT_WRITE_FAILURES"
ENV_ALLOC_STEP = "APEX_TPU_FAULT_ALLOC_STEP"


class FaultInjected(OSError):
    """The error raised by injected I/O faults — distinguishable from a
    real failure in test assertions."""


class SyntheticResourceExhausted(FaultInjected):
    """Injected allocation failure. The message carries the literal
    ``RESOURCE_EXHAUSTED`` marker so ``telemetry.memory.is_oom_error``
    treats it exactly like the XLA runtime error it stands in for."""


def nan_step_from_env():
    """The step to poison per ``$APEX_TPU_FAULT_NAN_STEP``, or None."""
    v = os.environ.get(ENV_NAN_STEP)
    return int(v) if v not in (None, "") else None


def alloc_step_from_env():
    """The step to OOM per ``$APEX_TPU_FAULT_ALLOC_STEP``, or None."""
    v = os.environ.get(ENV_ALLOC_STEP)
    return int(v) if v not in (None, "") else None


def inject_alloc_failure(step, alloc_step=None, *, bytes_requested=None):
    """Raise a synthetic ``RESOURCE_EXHAUSTED`` when ``step ==
    alloc_step`` (host-side — call it in the train loop just before the
    step dispatch, inside the ``oom_guard``/``guarded_call`` whose
    post-mortem path is under test). ``alloc_step=None`` consults
    ``$APEX_TPU_FAULT_ALLOC_STEP``; still None means no injection —
    safe to leave in production loops, mirroring :func:`inject_nan`."""
    if alloc_step is None:
        alloc_step = alloc_step_from_env()
    if alloc_step is None or int(step) != int(alloc_step):
        return
    detail = (f" while allocating {int(bytes_requested)} bytes"
              if bytes_requested else "")
    raise SyntheticResourceExhausted(
        f"RESOURCE_EXHAUSTED: injected allocation failure at step "
        f"{int(step)}{detail} (faults.inject_alloc_failure)")


def _leaf_path_str(path):
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def inject_nan(tree, step, nan_step=None, path_filter=None):
    """Poison every floating leaf of ``tree`` with NaN when ``step ==
    nan_step`` (jit-native; identity for other steps and when no step
    is armed). ``nan_step=None`` consults the env var; still None means
    no injection — safe to leave in production step functions.

    ``path_filter`` targets the fault at a single module: a string is
    matched as a prefix of each leaf's '/'-joined path (the same path
    formatting ``telemetry.numerics.tree_stats`` groups by, so the
    numerics post-mortem can be asserted to name exactly the poisoned
    module), a callable receives the path string and returns whether to
    poison. Leaves that don't match pass through untouched."""
    if nan_step is None:
        nan_step = nan_step_from_env()
    if nan_step is None:
        return tree
    step = jnp.asarray(step)

    if path_filter is None:
        def match(path_str):
            return True
    elif callable(path_filter):
        match = path_filter
    else:
        def match(path_str, _prefix=str(path_filter)):
            return path_str.startswith(_prefix)

    def poison(path, leaf):
        leaf = jnp.asarray(leaf)
        if not jnp.issubdtype(leaf.dtype, jnp.floating) \
                or not match(_leaf_path_str(path)):
            return leaf
        return jnp.where(step == nan_step, jnp.full_like(leaf, jnp.nan),
                         leaf)

    return tree_util.tree_map_with_path(poison, tree)


@contextlib.contextmanager
def failing_checkpoint_writes(failures=None, after_bytes=64):
    """Make the next ``failures`` checkpoint writes fail after writing
    ``after_bytes`` of the real pickle payload into the temp location
    (the partial-write fault). The canonical step dir never appears, so
    ``latest_step`` must never select the failed step. Yields a dict
    whose ``"fired"`` counts injected failures."""
    from apex_tpu import checkpoint

    if failures is None:
        failures = int(os.environ.get(ENV_CKPT_WRITE_FAILURES, "1"))
    real = checkpoint._write_state
    stats = {"fired": 0}

    def fake(path, host_state, use_orbax):
        if stats["fired"] < failures:
            stats["fired"] += 1
            tmp = f"{path}.tmp-fault"
            os.makedirs(tmp, exist_ok=True)
            payload = pickle.dumps(host_state)
            with open(os.path.join(tmp, "state.pkl"), "wb") as f:
                f.write(payload[:after_bytes])
            raise FaultInjected(
                f"injected write failure #{stats['fired']} after "
                f"{min(after_bytes, len(payload))} bytes ({path})")
        return real(path, host_state, use_orbax)

    checkpoint._write_state = fake
    try:
        yield stats
    finally:
        checkpoint._write_state = real


@contextlib.contextmanager
def torn_checkpoint_write(keep_bytes=64):
    """Make the next checkpoint write land a TRUNCATED ``state.pkl``
    behind a manifest describing the full intended payload — the
    durable wreckage of a crash that lost the file tail. The step IS
    visible to ``latest_step``; only manifest verification can tell it
    from a good one. Yields a dict whose ``"fired"`` flags firing."""
    import json

    from apex_tpu import checkpoint

    real = checkpoint._write_state
    stats = {"fired": 0}

    def fake(path, host_state, use_orbax):
        if stats["fired"]:
            return real(path, host_state, use_orbax)
        stats["fired"] = 1
        payload = pickle.dumps(host_state)
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "state.pkl"), "wb") as f:
            f.write(payload[:keep_bytes])
        manifest = checkpoint._manifest_for(host_state, "pickle")
        manifest["files"] = {
            "state.pkl": {"size": len(payload),
                          "sha256": checkpoint._sha256_bytes(payload)}}
        with open(os.path.join(path, checkpoint.MANIFEST_NAME), "w") as f:
            json.dump(manifest, f)

    checkpoint._write_state = fake
    try:
        yield stats
    finally:
        checkpoint._write_state = real


def corrupt_checkpoint(directory, step, *, offset=-8, nbytes=4):
    """Flip ``nbytes`` bytes of a landed checkpoint's payload in place
    (negative ``offset`` counts from the file end). Targets
    ``state.pkl`` when present, else the largest orbax data file.
    Returns the corrupted file's path."""
    from apex_tpu import checkpoint

    path = checkpoint._step_dir(directory, step)
    target = os.path.join(path, "state.pkl")
    if not os.path.exists(target):
        candidates = []
        for root, _, names in os.walk(path):
            for nm in names:
                if nm == checkpoint.MANIFEST_NAME:
                    continue
                full = os.path.join(root, nm)
                candidates.append((os.path.getsize(full), full))
        if not candidates:
            raise FileNotFoundError(f"no payload files under {path}")
        target = max(candidates)[1]
    with open(target, "r+b") as f:
        f.seek(offset, os.SEEK_END if offset < 0 else os.SEEK_SET)
        pos = f.tell()
        data = f.read(nbytes)
        f.seek(pos)
        f.write(bytes(b ^ 0xFF for b in data))
    return target


def simulate_preemption(sig=signal.SIGTERM):
    """Deliver a real signal to this process (default SIGTERM — what a
    TPU-pod preemption sends). Pair with an installed
    :class:`~apex_tpu.resilience.preemption.PreemptionGuard`, or the
    default handler will kill the process, which is the point of the
    drill."""
    signal.raise_signal(sig)
