"""Deterministic fault injection for the resilience chaos suite.

Every injector is explicit and deterministic — a fault fires at the
step / call you named, never randomly — so a chaos test is a regular
regression test. Gating is API-first (call the injector) with one env
escape hatch for end-to-end drills from the bench/capture drivers:

- ``APEX_TPU_FAULT_PLAN`` — the consolidated fault spec, a
  ``;``-separated list of ``kind@step[:arg]`` entries parsed by
  :func:`parse_fault_plan` / read by :func:`fault_plan`::

      APEX_TPU_FAULT_PLAN="nan@3:layer1;alloc@5;preempt@9"

  Kinds: ``nan`` (arg = module-path prefix filter), ``alloc``,
  ``preempt``, ``device_loss`` (arg = shrink-to world),
  ``decode`` (arg = ``transient``/``persistent``), ``slot_nan``
  (arg = slot id), ``ckpt_torn``, ``ckpt_fail`` (step = failure
  count). Every ``*_from_env`` helper consults the plan, so one
  var scripts a whole chaos campaign.

The pre-plan per-injector vars still work — ``APEX_TPU_FAULT_NAN_STEP``,
``_ALLOC_STEP``, ``_CKPT_WRITE_FAILURES``, ``_SLOT_NAN``,
``_DECODE_STEP``/``_TRANSIENT`` — but are DEPRECATED in favor of the
plan (one ``DeprecationWarning`` per var per process); when both name
the same fault the legacy var wins, so existing drills keep their
meaning.

Injector catalogue:

- :func:`inject_nan` — jit-native NaN poisoning of a grad pytree at
  one chosen step (a ``jnp.where`` on the step counter; compiles into
  the step, costs one select when armed, is the identity when not).
- :func:`inject_alloc_failure` — host-side synthetic
  ``RESOURCE_EXHAUSTED`` at one chosen step (a real HBM exhaustion is
  raised by the runtime at dispatch, so the injector fires on the host
  just before it), making the OOM post-mortem path
  (``telemetry.memory.oom_guard`` / ``resilience.guarded_call``)
  testable on CPU — the allocation sibling of :func:`inject_nan`.
- :func:`failing_checkpoint_writes` — the next N checkpoint writes die
  after flushing a few real payload bytes into the temp location
  (transient disk/FS failure; nothing lands, exercising the retry path
  and ``AsyncCheckpointer`` error surfacing).
- :func:`torn_checkpoint_write` — the next checkpoint write LANDS, but
  with a truncated ``state.pkl`` behind a manifest describing the full
  intended bytes (a crash/power-cut that lost the file tail):
  ``restore`` must reject the step and fall back.
- :func:`corrupt_checkpoint` — flip bytes in a landed checkpoint's
  payload in place (bit rot / torn sector).
- :func:`simulate_preemption` — raise a real SIGTERM in-process, which
  a :class:`~apex_tpu.resilience.preemption.PreemptionGuard` fields.

Serving injectors (the ISSUE-7 chaos surface; all deterministic,
keyed on the engine's lifetime decode-call counter):

- :func:`inject_slot_nan` — poison ONE slot's decode logits at one
  decode call (``APEX_TPU_FAULT_SLOT_NAN="slot:step"``). The engine
  folds the armed slot id into its compiled decode step as a traced
  i32 scalar (identity at -1), so arming never changes the executable
  — the per-slot quarantine path runs under
  ``assert_no_recompiles``.
- :func:`inject_decode_failure` — fail a decode *dispatch* host-side
  at one decode call (``APEX_TPU_FAULT_DECODE_STEP``), transient
  (fires once; the retry succeeds) or permanent (fires on every
  attempt until the retry budget exhausts and the engine raises
  ``serving.robust.DecodeFailedError``).
- :func:`request_storm` — a burst trace (every request arriving at
  the same tick) for admission-control drills: with a bounded pending
  queue the overflow must shed, not grow without bound.

Fleet injectors (the ISSUE-11 chaos surface):

- :func:`inject_replica_loss` — kill ONE serving replica at one fleet
  step (``APEX_TPU_FAULT_PLAN="replica_loss@N:R"``): the fleet's
  router polls :func:`replica_loss_for` each tick, drops the named
  replica's engine, and must migrate its unfinished requests to
  survivors (re-prefill from prompt + emitted tokens) — the
  replica-level sibling of :func:`inject_device_loss`, keyed on the
  fleet's lifetime step counter the way the serving injectors key on
  the decode-call counter. One-shot: the respawned replica is clean.
"""

import contextlib
import os
import pickle
import signal
import warnings

import jax.numpy as jnp
import numpy as np
from jax import tree_util

ENV_FAULT_PLAN = "APEX_TPU_FAULT_PLAN"
ENV_NAN_STEP = "APEX_TPU_FAULT_NAN_STEP"
ENV_CKPT_WRITE_FAILURES = "APEX_TPU_FAULT_CKPT_WRITE_FAILURES"
ENV_ALLOC_STEP = "APEX_TPU_FAULT_ALLOC_STEP"
ENV_SLOT_NAN = "APEX_TPU_FAULT_SLOT_NAN"
ENV_DECODE_STEP = "APEX_TPU_FAULT_DECODE_STEP"
ENV_DECODE_TRANSIENT = "APEX_TPU_FAULT_DECODE_TRANSIENT"

#: every spec kind ``parse_fault_plan`` accepts, with the meaning of
#: the optional ``:arg`` suffix (None = no arg defined for the kind)
PLAN_KINDS = {
    "nan": "module-path prefix filter (inject_nan path_filter)",
    "alloc": None,
    "preempt": None,
    "device_loss": "shrink-to world size for the mesh-shrink restart",
    "decode": "'transient' (default) or 'persistent'",
    "slot_nan": "slot id to poison (default 0)",
    "ckpt_torn": None,
    "ckpt_fail": None,  # step field = number of failing writes
    "replica_loss": "fleet replica index to kill (default 0)",
    "kv_corrupt": "donor replica index whose migration payload is "
                  "corrupted (default 0)",
}


class FaultInjected(OSError):
    """The error raised by injected I/O faults — distinguishable from a
    real failure in test assertions."""


class SyntheticResourceExhausted(FaultInjected):
    """Injected allocation failure. The message carries the literal
    ``RESOURCE_EXHAUSTED`` marker so ``telemetry.memory.is_oom_error``
    treats it exactly like the XLA runtime error it stands in for."""


class InjectedDecodeFailure(FaultInjected):
    """Injected decode-dispatch failure. ``transient`` distinguishes a
    blip (fires once; the engine's retry must succeed) from a
    persistent fault (fires every attempt; the retry budget must
    exhaust). The message carries ``UNAVAILABLE`` so
    ``serving.robust.is_retryable_decode_error`` classifies it exactly
    like the runtime error it stands in for."""

    def __init__(self, msg, *, transient=True):
        super().__init__(msg)
        self.transient = bool(transient)


class DeviceLostError(RuntimeError):
    """Injected device/slice loss. The message carries the literal
    ``DEVICE_LOST`` marker (what the PJRT runtime surfaces when a pod
    slice drops out), so ``resilience.supervisor.classify_failure``
    routes it to the mesh-shrink policy. ``shrink_to`` optionally names
    the world size the surviving mesh should restart at (None = let the
    policy decide, typically world // 2)."""

    def __init__(self, msg, *, shrink_to=None):
        super().__init__(msg)
        self.shrink_to = shrink_to


# -- the consolidated fault plan --------------------------------------------

class FaultPlan:
    """A parsed ``APEX_TPU_FAULT_PLAN`` spec: ``entries`` maps kind ->
    ``{"kind", "step", "arg"}``. One entry per kind (a campaign names
    each fault class at most once — sweep classes across runs, not
    within one)."""

    def __init__(self, entries=None, spec=""):
        self.entries = dict(entries or {})
        self.spec = spec

    def get(self, kind):
        """The entry dict for ``kind``, or None when the plan does not
        name that fault class."""
        return self.entries.get(kind)

    def step(self, kind):
        """The armed step for ``kind``, or None."""
        e = self.entries.get(kind)
        return e["step"] if e else None

    def __bool__(self):
        return bool(self.entries)

    def __repr__(self):
        return f"FaultPlan({self.spec!r})"


def parse_fault_plan(spec):
    """Parse one ``kind@step[:arg]``-list spec (``;``-separated) into a
    :class:`FaultPlan`. Raises ValueError naming the offending entry on
    an unknown kind, a non-integer step, or a duplicate kind."""
    entries = {}
    for raw in (spec or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        kind, at, rest = raw.partition("@")
        kind = kind.strip()
        if not at or kind not in PLAN_KINDS:
            known = ", ".join(sorted(PLAN_KINDS))
            raise ValueError(
                f"{ENV_FAULT_PLAN}: bad entry {raw!r} — want "
                f"'kind@step[:arg]' with kind in ({known})")
        step_s, _, arg = rest.partition(":")
        try:
            step = int(step_s)
        except ValueError:
            raise ValueError(
                f"{ENV_FAULT_PLAN}: entry {raw!r} has a non-integer "
                f"step {step_s!r}") from None
        if kind in entries:
            raise ValueError(
                f"{ENV_FAULT_PLAN}: duplicate entry for kind "
                f"{kind!r} ({raw!r}); name each fault class once")
        entries[kind] = {"kind": kind, "step": step,
                         "arg": arg if arg != "" else None}
    return FaultPlan(entries, spec or "")


def fault_plan():
    """The plan parsed from ``$APEX_TPU_FAULT_PLAN`` (re-read on every
    call — cheap, and monkeypatched envs in tests stay honest). An
    unset/empty var yields an empty plan that gates nothing."""
    return parse_fault_plan(os.environ.get(ENV_FAULT_PLAN, ""))


_legacy_warned = set()


def _legacy_env_step(var, plan_kind):
    """Read a deprecated per-injector step var, warning once per var
    per process that the consolidated plan supersedes it. The legacy
    var wins over a plan entry of the same kind (existing drills keep
    their meaning); returns None when unset."""
    v = os.environ.get(var)
    if v in (None, ""):
        return None
    if var not in _legacy_warned:
        _legacy_warned.add(var)
        warnings.warn(
            f"{var} is deprecated — express the fault in "
            f"{ENV_FAULT_PLAN} instead (e.g. "
            f"'{plan_kind}@{v}'); the legacy var still wins when both "
            "are set", DeprecationWarning, stacklevel=3)
    return int(v)


def nan_step_from_env():
    """The step to poison per ``$APEX_TPU_FAULT_NAN_STEP`` (deprecated)
    or the plan's ``nan@N`` entry, or None."""
    legacy = _legacy_env_step(ENV_NAN_STEP, "nan")
    if legacy is not None:
        return legacy
    return fault_plan().step("nan")


def nan_path_from_env():
    """The module-path prefix filter of the plan's ``nan@N:prefix``
    entry, or None (poison everything). The legacy var has no path
    field, so this is plan-only."""
    e = fault_plan().get("nan")
    return e["arg"] if e else None


def alloc_step_from_env():
    """The step to OOM per ``$APEX_TPU_FAULT_ALLOC_STEP`` (deprecated)
    or the plan's ``alloc@N`` entry, or None."""
    legacy = _legacy_env_step(ENV_ALLOC_STEP, "alloc")
    if legacy is not None:
        return legacy
    return fault_plan().step("alloc")


def preempt_step_from_env():
    """The step to deliver the simulated SIGTERM at, per the plan's
    ``preempt@N`` entry (plan-only — no legacy var existed), or None.
    Consumed by drivers (``tools/chaos_run.py``): preemption is a
    signal, not an in-graph fault, so the driver owns the delivery."""
    return fault_plan().step("preempt")


def device_loss_spec_from_env():
    """``(step, shrink_to)`` of the plan's ``device_loss@N[:world]``
    entry, or ``(None, None)``."""
    e = fault_plan().get("device_loss")
    if not e:
        return None, None
    return e["step"], int(e["arg"]) if e["arg"] else None


def inject_device_loss(step, device_loss_step=None, *, shrink_to=None,
                       world=None):
    """Raise :class:`DeviceLostError` when ``step ==
    device_loss_step`` (host-side — a real device loss kills the
    dispatch, so the injector fires just before it, the topology
    sibling of :func:`inject_alloc_failure`). ``device_loss_step=None``
    consults the plan's ``device_loss@N[:world]`` entry; still None
    means no injection. ``shrink_to`` (default: the plan's arg, else
    None) rides on the error so the supervisor's mesh-shrink policy
    knows the surviving world size."""
    if device_loss_step is None:
        device_loss_step, plan_shrink = device_loss_spec_from_env()
        if shrink_to is None:
            shrink_to = plan_shrink
    if device_loss_step is None or int(step) != int(device_loss_step):
        return
    detail = f" (world was {int(world)})" if world else ""
    raise DeviceLostError(
        f"DEVICE_LOST: injected device loss at step {int(step)}{detail} "
        f"(faults.inject_device_loss)", shrink_to=shrink_to)


def inject_alloc_failure(step, alloc_step=None, *, bytes_requested=None):
    """Raise a synthetic ``RESOURCE_EXHAUSTED`` when ``step ==
    alloc_step`` (host-side — call it in the train loop just before the
    step dispatch, inside the ``oom_guard``/``guarded_call`` whose
    post-mortem path is under test). ``alloc_step=None`` consults
    ``$APEX_TPU_FAULT_ALLOC_STEP``; still None means no injection —
    safe to leave in production loops, mirroring :func:`inject_nan`."""
    if alloc_step is None:
        alloc_step = alloc_step_from_env()
    if alloc_step is None or int(step) != int(alloc_step):
        return
    detail = (f" while allocating {int(bytes_requested)} bytes"
              if bytes_requested else "")
    raise SyntheticResourceExhausted(
        f"RESOURCE_EXHAUSTED: injected allocation failure at step "
        f"{int(step)}{detail} (faults.inject_alloc_failure)")


def _leaf_path_str(path):
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in path)


def inject_nan(tree, step, nan_step=None, path_filter=None):
    """Poison every floating leaf of ``tree`` with NaN when ``step ==
    nan_step`` (jit-native; identity for other steps and when no step
    is armed). ``nan_step=None`` consults the env var; still None means
    no injection — safe to leave in production step functions.

    ``path_filter`` targets the fault at a single module: a string is
    matched as a prefix of each leaf's '/'-joined path (the same path
    formatting ``telemetry.numerics.tree_stats`` groups by, so the
    numerics post-mortem can be asserted to name exactly the poisoned
    module), a callable receives the path string and returns whether to
    poison. Leaves that don't match pass through untouched."""
    if nan_step is None:
        nan_step = nan_step_from_env()
        if path_filter is None:
            # the plan's nan@N:prefix arg targets the fault for free
            path_filter = nan_path_from_env()
    if nan_step is None:
        return tree
    step = jnp.asarray(step)

    if path_filter is None:
        def match(path_str):
            return True
    elif callable(path_filter):
        match = path_filter
    else:
        def match(path_str, _prefix=str(path_filter)):
            return path_str.startswith(_prefix)

    def poison(path, leaf):
        leaf = jnp.asarray(leaf)
        if not jnp.issubdtype(leaf.dtype, jnp.floating) \
                or not match(_leaf_path_str(path)):
            return leaf
        return jnp.where(step == nan_step, jnp.full_like(leaf, jnp.nan),
                         leaf)

    return tree_util.tree_map_with_path(poison, tree)


@contextlib.contextmanager
def failing_checkpoint_writes(failures=None, after_bytes=64):
    """Make the next ``failures`` checkpoint writes fail after writing
    ``after_bytes`` of the real pickle payload into the temp location
    (the partial-write fault). The canonical step dir never appears, so
    ``latest_step`` must never select the failed step. Yields a dict
    whose ``"fired"`` counts injected failures."""
    from apex_tpu import checkpoint

    if failures is None:
        legacy = os.environ.get(ENV_CKPT_WRITE_FAILURES)
        if legacy not in (None, ""):
            if ENV_CKPT_WRITE_FAILURES not in _legacy_warned:
                _legacy_warned.add(ENV_CKPT_WRITE_FAILURES)
                warnings.warn(
                    f"{ENV_CKPT_WRITE_FAILURES} is deprecated — use "
                    f"{ENV_FAULT_PLAN}='ckpt_fail@{legacy}'",
                    DeprecationWarning, stacklevel=3)
            failures = int(legacy)
        else:
            failures = fault_plan().step("ckpt_fail")
            if failures is None:
                failures = 1
    real = checkpoint._write_state
    stats = {"fired": 0}

    def fake(path, host_state, use_orbax):
        if stats["fired"] < failures:
            stats["fired"] += 1
            tmp = f"{path}.tmp-fault"
            os.makedirs(tmp, exist_ok=True)
            payload = pickle.dumps(host_state)
            with open(os.path.join(tmp, "state.pkl"), "wb") as f:
                f.write(payload[:after_bytes])
            raise FaultInjected(
                f"injected write failure #{stats['fired']} after "
                f"{min(after_bytes, len(payload))} bytes ({path})")
        return real(path, host_state, use_orbax)

    checkpoint._write_state = fake
    try:
        yield stats
    finally:
        checkpoint._write_state = real


@contextlib.contextmanager
def torn_checkpoint_write(keep_bytes=64):
    """Make the next checkpoint write land a TRUNCATED ``state.pkl``
    behind a manifest describing the full intended payload — the
    durable wreckage of a crash that lost the file tail. The step IS
    visible to ``latest_step``; only manifest verification can tell it
    from a good one. Yields a dict whose ``"fired"`` flags firing."""
    import json

    from apex_tpu import checkpoint

    real = checkpoint._write_state
    stats = {"fired": 0}

    def fake(path, host_state, use_orbax):
        if stats["fired"]:
            return real(path, host_state, use_orbax)
        stats["fired"] = 1
        payload = pickle.dumps(host_state)
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "state.pkl"), "wb") as f:
            f.write(payload[:keep_bytes])
        manifest = checkpoint._manifest_for(host_state, "pickle")
        manifest["files"] = {
            "state.pkl": {"size": len(payload),
                          "sha256": checkpoint._sha256_bytes(payload)}}
        with open(os.path.join(path, checkpoint.MANIFEST_NAME), "w") as f:
            json.dump(manifest, f)

    checkpoint._write_state = fake
    try:
        yield stats
    finally:
        checkpoint._write_state = real


def corrupt_checkpoint(directory, step, *, offset=-8, nbytes=4):
    """Flip ``nbytes`` bytes of a landed checkpoint's payload in place
    (negative ``offset`` counts from the file end). Targets
    ``state.pkl`` when present, else the largest orbax data file.
    Returns the corrupted file's path."""
    from apex_tpu import checkpoint

    path = checkpoint._step_dir(directory, step)
    target = os.path.join(path, "state.pkl")
    if not os.path.exists(target):
        candidates = []
        for root, _, names in os.walk(path):
            for nm in names:
                if nm == checkpoint.MANIFEST_NAME:
                    continue
                full = os.path.join(root, nm)
                candidates.append((os.path.getsize(full), full))
        if not candidates:
            raise FileNotFoundError(f"no payload files under {path}")
        target = max(candidates)[1]
    with open(target, "r+b") as f:
        f.seek(offset, os.SEEK_END if offset < 0 else os.SEEK_SET)
        pos = f.tell()
        data = f.read(nbytes)
        f.seek(pos)
        f.write(bytes(b ^ 0xFF for b in data))
    return target


# -- serving injectors (ISSUE 7) --------------------------------------------
#
# Armed state is module-level so the engine (which owns the decode-call
# counter) and the driver (which owns the scenario) need no plumbing
# between them; ``arm_*``/context-manager both write the same slot.

_slot_nan_state = None      # {"slot", "step", "fired"}
_decode_fail_state = None   # {"step", "transient", "fired"}


def _slot_nan_from_env():
    v = os.environ.get(ENV_SLOT_NAN)
    if v not in (None, ""):
        if ENV_SLOT_NAN not in _legacy_warned:
            _legacy_warned.add(ENV_SLOT_NAN)
            slot_s, _, step_s = v.partition(":")
            warnings.warn(
                f"{ENV_SLOT_NAN} is deprecated — use "
                f"{ENV_FAULT_PLAN}='slot_nan@{step_s or 0}:{slot_s}'",
                DeprecationWarning, stacklevel=3)
        slot, _, step = v.partition(":")
        return {"slot": int(slot), "step": int(step or 0), "fired": 0}
    e = fault_plan().get("slot_nan")
    if e is None:
        return None
    return {"slot": int(e["arg"] or 0), "step": e["step"], "fired": 0}


def arm_slot_nan(slot, step):
    """Arm a one-shot slot-NaN: the decode call numbered ``step`` (the
    engine's lifetime decode-call counter, 0-based) poisons the logits
    of cache slot ``slot`` in-graph. Returns the armed-state dict
    (``"fired"`` counts firings). Overwrites any previous arming."""
    global _slot_nan_state
    _slot_nan_state = {"slot": int(slot), "step": int(step), "fired": 0}
    return _slot_nan_state


def disarm_slot_nan():
    global _slot_nan_state
    _slot_nan_state = None


@contextlib.contextmanager
def inject_slot_nan(slot, step):
    """Context-manager form of :func:`arm_slot_nan`; disarms on exit.
    Yields the state dict so tests can assert ``state["fired"] == 1``."""
    state = arm_slot_nan(slot, step)
    try:
        yield state
    finally:
        disarm_slot_nan()


def poison_slot_for(decode_step):
    """The slot id to poison at decode call ``decode_step``, or -1.

    Called by ``ServeEngine.decode`` on every dispatch; the returned
    int feeds the compiled step's traced ``poison_slot`` argument
    (identity at -1 — the unarmed fast path costs one ``is None``).
    One-shot: a matching call marks the arming fired so the NEXT
    decode call is clean — the quarantine must recover, not re-poison.
    Env arming (``APEX_TPU_FAULT_SLOT_NAN=slot:step``) is read lazily
    on first consult and follows the same one-shot contract."""
    global _slot_nan_state
    if _slot_nan_state is None and (ENV_SLOT_NAN in os.environ
                                    or fault_plan().get("slot_nan")):
        _slot_nan_state = _slot_nan_from_env()
    st = _slot_nan_state
    if not st or st["fired"] or int(decode_step) != st["step"]:
        return -1
    st["fired"] += 1
    return st["slot"]


def arm_decode_failure(step, transient=None):
    """Arm a decode-dispatch failure at decode call ``step``.
    ``transient=True`` (default) fires once — the engine's first retry
    finds clean air; ``transient=False`` fires on every attempt at
    that step, exhausting the retry budget. Returns the state dict."""
    global _decode_fail_state
    if transient is None:
        transient = os.environ.get(ENV_DECODE_TRANSIENT, "1") != "0"
    _decode_fail_state = {"step": int(step), "transient": bool(transient),
                          "fired": 0}
    return _decode_fail_state


def disarm_decode_failure():
    global _decode_fail_state
    _decode_fail_state = None


@contextlib.contextmanager
def inject_decode_failure(step, transient=True):
    """Context-manager form of :func:`arm_decode_failure`; disarms on
    exit. Yields the state dict (``"fired"`` counts raises)."""
    state = arm_decode_failure(step, transient=transient)
    try:
        yield state
    finally:
        disarm_decode_failure()


def maybe_fail_decode(decode_step):
    """Raise :class:`InjectedDecodeFailure` when a failure is armed for
    decode call ``decode_step`` — called by ``ServeEngine.decode``
    just before each dispatch attempt (the host-side stand-in for the
    runtime raising at dispatch). Transient armings clear after one
    raise; permanent ones keep raising at their step. Env arming via
    ``APEX_TPU_FAULT_DECODE_STEP`` (+ ``..._TRANSIENT=0`` for the
    permanent flavor) is read lazily on first consult."""
    global _decode_fail_state
    if _decode_fail_state is None:
        v = os.environ.get(ENV_DECODE_STEP)
        if v not in (None, ""):
            if ENV_DECODE_STEP not in _legacy_warned:
                _legacy_warned.add(ENV_DECODE_STEP)
                warnings.warn(
                    f"{ENV_DECODE_STEP} is deprecated — use "
                    f"{ENV_FAULT_PLAN}='decode@{v}'",
                    DeprecationWarning, stacklevel=2)
            arm_decode_failure(int(v))
        else:
            e = fault_plan().get("decode")
            if e is not None:
                arm_decode_failure(
                    e["step"], transient=(e["arg"] != "persistent"))
    st = _decode_fail_state
    if not st or int(decode_step) != st["step"]:
        return
    if st["transient"] and st["fired"]:
        return
    st["fired"] += 1
    raise InjectedDecodeFailure(
        f"UNAVAILABLE: injected {'transient' if st['transient'] else 'persistent'} "
        f"decode failure at decode call {int(decode_step)} "
        f"(attempt {st['fired']}; faults.inject_decode_failure)",
        transient=st["transient"])


_replica_loss_state = None   # {"replica", "step", "fired"}


def arm_replica_loss(replica, step):
    """Arm a one-shot replica loss: at fleet step ``step`` (the
    fleet's lifetime step counter, 0-based) replica ``replica`` drops
    dead — its engine becomes unusable and every unfinished request
    must finish on a survivor. Returns the armed-state dict
    (``"fired"`` counts firings). Overwrites any previous arming."""
    global _replica_loss_state
    _replica_loss_state = {"replica": int(replica), "step": int(step),
                           "fired": 0}
    return _replica_loss_state


def disarm_replica_loss():
    global _replica_loss_state
    _replica_loss_state = None


@contextlib.contextmanager
def inject_replica_loss(replica, step):
    """Context-manager form of :func:`arm_replica_loss`; disarms on
    exit. Yields the state dict so tests can assert
    ``state["fired"] == 1``."""
    state = arm_replica_loss(replica, step)
    try:
        yield state
    finally:
        disarm_replica_loss()


def replica_loss_for(fleet_step):
    """The replica index to kill at fleet step ``fleet_step``, or None.

    Polled by ``serving.fleet.ServeFleet.step`` every tick — the
    replica-loss sibling of :func:`poison_slot_for`, keyed on the
    fleet's lifetime step counter. One-shot: a matching call marks the
    arming fired so the respawned replica comes up clean. Env arming
    (``APEX_TPU_FAULT_PLAN="replica_loss@N:R"``) is read lazily on
    first consult and follows the same one-shot contract."""
    global _replica_loss_state
    if _replica_loss_state is None and fault_plan().get("replica_loss"):
        e = fault_plan().get("replica_loss")
        _replica_loss_state = {"replica": int(e["arg"] or 0),
                               "step": e["step"], "fired": 0}
    st = _replica_loss_state
    if not st or st["fired"] or int(fleet_step) != st["step"]:
        return None
    st["fired"] += 1
    return st["replica"]


_kv_corrupt_state = None   # {"replica", "step", "fired"}


def arm_kv_corrupt(replica, step):
    """Arm a one-shot KV-payload corruption: at fleet step ``step``
    (the fleet's lifetime step counter, 0-based), the migration
    payload extracted FROM donor replica ``replica`` gets one byte
    flipped in flight — the checksum-fallback drill. The survivor must
    detect the mismatch, count a loud fallback, and re-prefill from
    tokens with the stream still completing. Returns the armed-state
    dict (``"fired"`` counts firings). Overwrites any previous
    arming."""
    global _kv_corrupt_state
    _kv_corrupt_state = {"replica": int(replica), "step": int(step),
                         "fired": 0}
    return _kv_corrupt_state


def disarm_kv_corrupt():
    global _kv_corrupt_state
    _kv_corrupt_state = None


@contextlib.contextmanager
def inject_kv_corrupt(replica, step):
    """Context-manager form of :func:`arm_kv_corrupt`; disarms on
    exit. Yields the state dict so tests can assert
    ``state["fired"] == 1``."""
    state = arm_kv_corrupt(replica, step)
    try:
        yield state
    finally:
        disarm_kv_corrupt()


def kv_corrupt_for(fleet_step):
    """The donor replica index whose extracted KV payload is corrupted
    at fleet step ``fleet_step``, or None.

    Polled by ``serving.fleet.ServeFleet`` at KV-state capture time —
    the payload-integrity sibling of :func:`replica_loss_for`, keyed
    on the same lifetime step counter (arm both at the same step to
    corrupt the handoff of the replica being killed). One-shot: a
    matching call marks the arming fired. Env arming
    (``APEX_TPU_FAULT_PLAN="kv_corrupt@N:R"``) is read lazily on first
    consult and follows the same one-shot contract."""
    global _kv_corrupt_state
    if _kv_corrupt_state is None and fault_plan().get("kv_corrupt"):
        e = fault_plan().get("kv_corrupt")
        _kv_corrupt_state = {"replica": int(e["arg"] or 0),
                             "step": e["step"], "fired": 0}
    st = _kv_corrupt_state
    if not st or st["fired"] or int(fleet_step) != st["step"]:
        return None
    st["fired"] += 1
    return st["replica"]


def request_storm(n_requests, *, at_tick=0.0, seed=0,
                  prompt_lens=(4, 8, 12), max_new=(4, 8),
                  vocab_size=256, rid_base=10_000):
    """A burst trace: ``n_requests`` all arriving at ``at_tick`` — the
    admission-control drill (``synthetic_trace``'s Poisson arrivals
    never pile up fast enough to exercise shedding on a small trace).
    Deterministic per seed; rids start at ``rid_base`` so a storm can
    ride on top of a regular trace without colliding."""
    from apex_tpu.serving.scheduler import Request

    rs = np.random.RandomState(seed)
    out = []
    for i in range(int(n_requests)):
        plen = int(rs.choice(prompt_lens))
        out.append(Request(
            rid=rid_base + i,
            prompt=rs.randint(0, vocab_size, size=plen).astype("int32"),
            max_new_tokens=int(rs.choice(max_new)),
            arrival=float(at_tick)))
    return out


def simulate_preemption(sig=signal.SIGTERM):
    """Deliver a real signal to this process (default SIGTERM — what a
    TPU-pod preemption sends). Pair with an installed
    :class:`~apex_tpu.resilience.preemption.PreemptionGuard`, or the
    default handler will kill the process, which is the point of the
    drill."""
    signal.raise_signal(sig)
