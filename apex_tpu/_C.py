"""Loader for the native runtime extension with numpy fallbacks.

Mirrors the reference's lazy-import pattern for its C++ extensions (each
Python module imports its kernel lib and degrades to a Python path when
absent, e.g. apex/parallel/distributed.py:15-25 for apex_C.flatten).

``HAVE_NATIVE`` tells callers whether apex_tpu_C is loaded. All four
entry points below work identically either way:

    flatten(arrays, out)        -> bytes copied
    unflatten_into(flat, outs)  -> bytes copied
    assign_buckets(sizes, cap)  -> list[int] bucket ids (greedy, in order)
    pack_batch(samples, out)    -> batch size
"""

import numpy as np

try:
    import apex_tpu_C as _ext

    HAVE_NATIVE = True
except ImportError:  # Python-only build (APEX_TPU_NO_EXT=1)
    _ext = None
    HAVE_NATIVE = False


def _require_contiguous(a, what):
    """The native path rejects non-C-contiguous buffers via the buffer
    protocol; the fallback must match (reshape(-1) on a non-contiguous
    array would copy, silently dropping the writes)."""
    if not a.flags["C_CONTIGUOUS"]:
        raise ValueError(f"{what}: ndarray is not C-contiguous")
    return a


def flatten(arrays, out):
    if _ext is not None:
        return _ext.flatten(arrays, out)
    off = 0
    flat = _require_contiguous(out, "flatten").reshape(-1).view(np.uint8)
    total = sum(np.asarray(a).nbytes for a in arrays)
    if total > out.nbytes:
        raise ValueError(
            f"flatten: output buffer too small ({out.nbytes} < {total} bytes)")
    for a in arrays:
        b = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
        flat[off:off + b.size] = b
        off += b.size
    return off


def unflatten_into(flat, outs):
    if _ext is not None:
        return _ext.unflatten_into(flat, outs)
    src = np.ascontiguousarray(flat).reshape(-1).view(np.uint8)
    total = sum(o.nbytes for o in outs)
    if total > flat.nbytes:
        raise ValueError(
            f"unflatten_into: flat buffer too small ({flat.nbytes} < "
            f"{total} bytes)")
    for o in outs:  # validate ALL before writing ANY (native acquires
        _require_contiguous(o, "unflatten_into")  # every buffer up front)
    off = 0
    for o in outs:
        n = o.nbytes
        o.reshape(-1).view(np.uint8)[:] = src[off:off + n]
        off += n
    return off


def assign_buckets(sizes, cap):
    if _ext is not None:
        return _ext.assign_buckets(list(sizes), int(cap))
    if cap <= 0:
        raise ValueError("assign_buckets: cap must be positive")
    out, acc, bucket, empty = [], 0, 0, True
    for sz in sizes:
        if not empty and acc + sz > cap:
            bucket += 1
            acc = 0
            empty = True
        acc += sz
        empty = False
        out.append(bucket)
    return out


def pack_batch(samples, out):
    if _ext is not None:
        return _ext.pack_batch(samples, out)
    if len(samples) == 0:
        raise ValueError("pack_batch: empty sample list")
    arrays = [np.asarray(s) for s in samples]
    item = arrays[0].nbytes
    if any(a.nbytes != item for a in arrays):
        raise ValueError("pack_batch: samples must be equally sized")
    if out.nbytes != item * len(arrays):
        raise ValueError(
            f"pack_batch: out must be batch*sample bytes ({out.nbytes} != "
            f"{len(arrays)}*{item})")
    batch = np.stack(arrays)
    _require_contiguous(out, "pack_batch")
    out.reshape(-1).view(np.uint8)[:] = batch.reshape(-1).view(np.uint8)
    return len(samples)
