"""Loader for the native runtime extension with numpy fallbacks.

Mirrors the reference's lazy-import pattern for its C++ extensions (each
Python module imports its kernel lib and degrades to a Python path when
absent, e.g. apex/parallel/distributed.py:15-25 for apex_C.flatten).

``HAVE_NATIVE`` tells callers whether apex_tpu_C is loaded. All four
entry points below work identically either way:

    flatten(arrays, out)        -> bytes copied
    unflatten_into(flat, outs)  -> bytes copied
    assign_buckets(sizes, cap)  -> list[int] bucket ids (greedy, in order)
    pack_batch(samples, out)    -> batch size
"""

import numpy as np

try:
    import apex_tpu_C as _ext

    HAVE_NATIVE = True
except ImportError:  # Python-only build (APEX_TPU_NO_EXT=1)
    _ext = None
    HAVE_NATIVE = False


def flatten(arrays, out):
    if _ext is not None:
        return _ext.flatten(arrays, out)
    off = 0
    flat = out.reshape(-1).view(np.uint8)
    for a in arrays:
        b = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
        flat[off:off + b.size] = b
        off += b.size
    return off


def unflatten_into(flat, outs):
    if _ext is not None:
        return _ext.unflatten_into(flat, outs)
    src = flat.reshape(-1).view(np.uint8)
    off = 0
    for o in outs:
        n = o.nbytes
        o.reshape(-1).view(np.uint8)[:] = src[off:off + n]
        off += n
    return off


def assign_buckets(sizes, cap):
    if _ext is not None:
        return _ext.assign_buckets(list(sizes), int(cap))
    if cap <= 0:
        raise ValueError("assign_buckets: cap must be positive")
    out, acc, bucket, empty = [], 0, 0, True
    for sz in sizes:
        if not empty and acc + sz > cap:
            bucket += 1
            acc = 0
            empty = True
        acc += sz
        empty = False
        out.append(bucket)
    return out


def pack_batch(samples, out):
    if _ext is not None:
        return _ext.pack_batch(samples, out)
    if len(samples) == 0:
        raise ValueError("pack_batch: empty sample list")
    batch = np.stack([np.asarray(s) for s in samples])
    out.reshape(-1).view(np.uint8)[:] = batch.reshape(-1).view(np.uint8)
    return len(samples)
