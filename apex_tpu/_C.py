"""Loader for the native runtime extension with numpy fallbacks.

Mirrors the reference's lazy-import pattern for its C++ extensions (each
Python module imports its kernel lib and degrades to a Python path when
absent, e.g. apex/parallel/distributed.py:15-25 for apex_C.flatten).

``HAVE_NATIVE`` tells callers whether apex_tpu_C is loaded. All four
entry points below work identically either way:

    flatten(arrays, out)        -> bytes copied
    unflatten_into(flat, outs)  -> bytes copied
    assign_buckets(sizes, cap)  -> list[int] bucket ids (greedy, in order)
    pack_batch(samples, out)    -> batch size
"""

import os

import numpy as np


def _build_in_place():
    """Compile csrc/apex_tpu_C.cpp into the source tree on first import.

    The reference requires an explicit `pip install --cpp_ext` step; here
    the extension is one self-contained C++17 file, so an editable/source
    checkout self-heals instead of silently running the numpy fallback.
    Returns the imported module or None."""
    import importlib.util
    import shutil
    import subprocess
    import sysconfig
    import warnings

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    src = os.path.join(here, "csrc", "apex_tpu_C.cpp")
    cxx = shutil.which("g++") or shutil.which("c++")
    if not os.path.exists(src) or cxx is None:
        return None
    so = os.path.join(
        here, "apex_tpu_C" + sysconfig.get_config_var("EXT_SUFFIX"))

    def _load(path):
        import sys

        spec = importlib.util.spec_from_file_location("apex_tpu_C", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules["apex_tpu_C"] = mod  # later imports reuse this instance
        return mod

    # Serialize concurrent importers (the multiproc launcher's workers all
    # import at once) behind an flock: one process compiles, the rest wait
    # and load the finished artifact. Compile lands in a temp path then an
    # atomic rename, so a crashed builder never leaves a truncated .so.
    tmp = f"{so}.{os.getpid()}.tmp"
    lock_path = so + ".lock"
    try:
        import fcntl

        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                if os.path.exists(so):  # another process won the race
                    return _load(so)
                cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC",
                       "-pthread", "-I" + sysconfig.get_path("include"),
                       src, "-o", tmp]
                proc = subprocess.run(cmd, capture_output=True, timeout=120)
                if proc.returncode != 0:
                    warnings.warn(
                        "apex_tpu_C build failed; using the numpy "
                        "fallback.\n"
                        + proc.stderr.decode(errors="replace")[-2000:])
                    return None
                os.replace(tmp, so)
                return _load(so)
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)
    except Exception as e:  # no write permission, timeout, bad artifact
        warnings.warn(f"apex_tpu_C build unavailable ({e!r}); "
                      "using the numpy fallback")
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


try:
    import apex_tpu_C as _ext

    HAVE_NATIVE = True
except ImportError:  # Python-only build (APEX_TPU_NO_EXT=1)
    _no_ext = os.environ.get("APEX_TPU_NO_EXT", "").lower() not in (
        "", "0", "false", "no")
    _ext = None if _no_ext else _build_in_place()
    HAVE_NATIVE = _ext is not None


def _require_contiguous(a, what):
    """The native path rejects non-C-contiguous buffers via the buffer
    protocol; the fallback must match (reshape(-1) on a non-contiguous
    array would copy, silently dropping the writes)."""
    if not a.flags["C_CONTIGUOUS"]:
        raise ValueError(f"{what}: ndarray is not C-contiguous")
    return a


def flatten(arrays, out):
    if _ext is not None:
        return _ext.flatten(arrays, out)
    off = 0
    flat = _require_contiguous(out, "flatten").reshape(-1).view(np.uint8)
    total = sum(np.asarray(a).nbytes for a in arrays)
    if total > out.nbytes:
        raise ValueError(
            f"flatten: output buffer too small ({out.nbytes} < {total} bytes)")
    for a in arrays:
        b = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
        flat[off:off + b.size] = b
        off += b.size
    return off


def unflatten_into(flat, outs):
    if _ext is not None:
        return _ext.unflatten_into(flat, outs)
    src = np.ascontiguousarray(flat).reshape(-1).view(np.uint8)
    total = sum(o.nbytes for o in outs)
    if total > flat.nbytes:
        raise ValueError(
            f"unflatten_into: flat buffer too small ({flat.nbytes} < "
            f"{total} bytes)")
    for o in outs:  # validate ALL before writing ANY (native acquires
        _require_contiguous(o, "unflatten_into")  # every buffer up front)
    off = 0
    for o in outs:
        n = o.nbytes
        o.reshape(-1).view(np.uint8)[:] = src[off:off + n]
        off += n
    return off


def assign_buckets(sizes, cap):
    if _ext is not None:
        return _ext.assign_buckets(list(sizes), int(cap))
    if cap <= 0:
        raise ValueError("assign_buckets: cap must be positive")
    out, acc, bucket, empty = [], 0, 0, True
    for sz in sizes:
        if not empty and acc + sz > cap:
            bucket += 1
            acc = 0
            empty = True
        acc += sz
        empty = False
        out.append(bucket)
    return out


def pack_batch(samples, out):
    if _ext is not None:
        return _ext.pack_batch(samples, out)
    if len(samples) == 0:
        raise ValueError("pack_batch: empty sample list")
    arrays = [np.asarray(s) for s in samples]
    item = arrays[0].nbytes
    if any(a.nbytes != item for a in arrays):
        raise ValueError("pack_batch: samples must be equally sized")
    if out.nbytes != item * len(arrays):
        raise ValueError(
            f"pack_batch: out must be batch*sample bytes ({out.nbytes} != "
            f"{len(arrays)}*{item})")
    batch = np.stack(arrays)
    _require_contiguous(out, "pack_batch")
    out.reshape(-1).view(np.uint8)[:] = batch.reshape(-1).view(np.uint8)
    return len(samples)
