"""Fused MLP.

Parity: reference apex/mlp (mlp.py:33-86 ``MLP`` + csrc/mlp_cuda.cu 1,678
LoC): a stack of Linear(+bias)+activation layers executed as one fused
kernel chain (cuBLAS GEMMs with fused bias/activation epilogues).

TPU design: the whole chain inside one jit — XLA fuses bias+activation
into the matmul epilogue on the MXU, which is exactly what mlp_cuda hand
-codes. Supports activation in {none, relu, sigmoid} like the reference.
"""

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

_ACTS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_function(bias: bool, activation: str, x, *weights_and_biases):
    """Functional fused MLP (parity: mlp.py MlpFunction.apply).

    ``weights_and_biases``: w0, w1, ... then (if bias) b0, b1, ...
    Weights are [out, in] like the reference.
    """
    act = _ACTS[activation]
    n = len(weights_and_biases) // 2 if bias else len(weights_and_biases)
    ws = weights_and_biases[:n]
    bs = weights_and_biases[n:] if bias else [None] * n
    h = x
    for w, b in zip(ws, bs):
        h = jnp.matmul(h, w.T, preferred_element_type=jnp.float32).astype(x.dtype)
        if b is not None:
            h = h + b
        h = act(h)
    return h


class MLP(nn.Module):
    """Module parity with reference ``MLP(mlp_sizes, bias, relu/sigmoid)``
    (mlp.py:33): ``mlp_sizes`` includes the input size.
    """

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        if self.activation not in _ACTS:
            raise TypeError(f"activation must be relu or none or sigmoid, "
                            f"got {self.activation}")
        h = x
        for i in range(len(self.mlp_sizes) - 1):
            in_f, out_f = self.mlp_sizes[i], self.mlp_sizes[i + 1]
            # zero-mean normal, std matching reference mlp.py:71-79
            w = self.param(f"weight_{i}",
                           nn.initializers.normal(
                               stddev=(2.0 / (in_f + out_f)) ** 0.5),
                           (out_f, in_f), self.param_dtype)
            h = jnp.matmul(h, w.T, preferred_element_type=jnp.float32
                           ).astype(x.dtype)
            if self.bias:
                b = self.param(f"bias_{i}",
                               nn.initializers.normal(
                                   stddev=(1.0 / out_f) ** 0.5),
                               (out_f,), self.param_dtype)
                h = h + b
            h = _ACTS[self.activation](h)
        return h
