"""Checkpoint / resume for training state.

Parity: the reference's checkpoint story (SURVEY.md §5) is amp
``state_dict``/``load_state_dict`` (apex/amp/frontend.py:365-404) plus
example-level ``torch.save`` of model+optimizer+amp
(examples/imagenet/main_amp.py:95-101). The TPU-native equivalent is a
single utility that snapshots the whole training state — params, optimizer
state (incl. fp32 masters and the loss-scaler state), batch stats, step —
via orbax when available (async, sharding-aware) with a pickle fallback.

    from apex_tpu import checkpoint
    checkpoint.save("ckpt/", step, params=params, opt_state=opt_state,
                    batch_stats=batch_stats)
    state = checkpoint.restore("ckpt/")          # latest step
    state = checkpoint.restore("ckpt/", step=5)  # specific step
"""

import os
import pickle
import re
from typing import Any, Dict, Optional

import jax

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # orbax missing or incompatible
    ocp = None
    _HAVE_ORBAX = False


def _step_dir(directory: str, step: int) -> str:
    # orbax/tensorstore require absolute paths
    return os.path.join(os.path.abspath(directory), f"step_{step:010d}")


def latest_step(directory: str) -> Optional[int]:
    """Newest checkpointed step in ``directory``, or None."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        m = re.fullmatch(r"step_(\d+)", name)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def save(directory: str, step: int, state: Optional[Dict[str, Any]] = None,
         *, use_orbax: Optional[bool] = None, **extra: Any) -> str:
    """Snapshot ``state`` (a dict of pytrees, merged with ``extra``
    kwargs) under ``directory/step_N``.

    Returns the checkpoint path. Device arrays are fetched to host;
    orbax (when available) writes the tree natively.
    """
    state = {**(state or {}), **extra}
    if use_orbax is None:
        use_orbax = _HAVE_ORBAX
    path = _step_dir(directory, step)
    os.makedirs(directory, exist_ok=True)
    host_state = jax.device_get(state)
    if use_orbax:
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(path, host_state, force=True)
    else:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "state.pkl"), "wb") as f:
            pickle.dump(host_state, f)
    return path


def restore(directory: str, step: Optional[int] = None, *,
            use_orbax: Optional[bool] = None,
            template: Any = None) -> Dict[str, Any]:
    """Load the state dict saved by :func:`save`.

    ``step=None`` loads the newest step. ``template`` (a pytree with the
    wanted structure/custom node types, e.g. the live training state) makes
    the orbax path restore into that structure — orbax stores custom pytree
    nodes (NamedTuples, dataclasses) structurally and returns plain dicts
    otherwise. Raises FileNotFoundError when no checkpoints exist.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_dir(directory, step)
    pkl = os.path.join(path, "state.pkl")
    if use_orbax is None:
        use_orbax = _HAVE_ORBAX and not os.path.exists(pkl)
    if use_orbax:
        ckptr = ocp.PyTreeCheckpointer()
        if template is not None:
            restored = ckptr.restore(path, item=jax.device_get(template))
        else:
            restored = ckptr.restore(path)
        return dict(restored)
    with open(pkl, "rb") as f:
        return pickle.load(f)


def save_training_state(directory: str, step: int, params, opt_state,
                        batch_stats=None, extra=None, **kw) -> str:
    """Convenience wrapper bundling the common training tuple + amp scaler
    state (the reference's model+optimizer+amp torch.save pattern)."""
    from apex_tpu import amp

    state = {"params": params, "opt_state": opt_state, "step": step}
    if batch_stats is not None:
        state["batch_stats"] = batch_stats
    if extra is not None:
        state["extra"] = extra
    try:
        state["amp"] = amp.state_dict()
    except Exception as e:
        import warnings

        warnings.warn(f"checkpoint: amp state not saved ({e})")
    return save(directory, step, state, **kw)


def restore_training_state(directory: str, step: Optional[int] = None,
                           **kw) -> Dict[str, Any]:
    """Load what :func:`save_training_state` wrote; re-installs amp scaler
    state when present and rebuilds the optimizer ScalerState (orbax
    stores NamedTuples structurally — pass ``template=`` for full custom-
    node fidelity on arbitrary states)."""
    from apex_tpu import amp
    from apex_tpu.amp.scaler import ScalerState

    state = restore(directory, step, **kw)
    opt_state = state.get("opt_state")
    if isinstance(opt_state, dict) and isinstance(opt_state.get("scaler"),
                                                  dict):
        opt_state["scaler"] = ScalerState(**opt_state["scaler"])
    if "amp" in state:
        try:
            amp.load_state_dict(state["amp"])
        except Exception as e:
            import warnings

            warnings.warn(
                f"checkpoint: amp scaler state failed to load ({e}); "
                "resuming with the current scaler — loss scale may differ "
                "from the saved run")
    return state
