"""Checkpoint / resume for training state.

Parity: the reference's checkpoint story (SURVEY.md §5) is amp
``state_dict``/``load_state_dict`` (apex/amp/frontend.py:365-404) plus
example-level ``torch.save`` of model+optimizer+amp
(examples/imagenet/main_amp.py:95-101). The TPU-native equivalent is a
single utility that snapshots the whole training state — params, optimizer
state (incl. fp32 masters and the loss-scaler state), batch stats, step —
via orbax when available (async, sharding-aware) with a pickle fallback.

    from apex_tpu import checkpoint
    checkpoint.save("ckpt/", step, params=params, opt_state=opt_state,
                    batch_stats=batch_stats)
    state = checkpoint.restore("ckpt/")          # latest step
    state = checkpoint.restore("ckpt/", step=5)  # specific step
"""

import concurrent.futures
import os
import pickle
import re
import threading
from typing import Any, Callable, Dict, Optional

import jax

from apex_tpu.telemetry import trace as _telemetry_trace

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # orbax missing or incompatible
    ocp = None
    _HAVE_ORBAX = False


def _step_dir(directory: str, step: int) -> str:
    # orbax/tensorstore require absolute paths
    return os.path.join(os.path.abspath(directory), f"step_{step:010d}")


def latest_step(directory: str) -> Optional[int]:
    """Newest checkpointed step in ``directory``, or None."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        m = re.fullmatch(r"step_(\d+)", name)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def repair_orphaned_steps(directory: str) -> list:
    """Recover steps stranded by a crash inside ``_write_state``'s
    force-overwrite window: a death between ``rename(path, old)`` and
    ``rename(tmp, path)`` leaves the step only as ``step_N.old-<pid>``,
    which ``latest_step`` rightly skips. Renames each such dir back when
    (and only when) the canonical ``step_N`` is absent — if both exist
    the landed checkpoint is newer and the parked copy stays parked.
    Called from ``save`` (single-writer discipline: don't run it while
    another process is mid-save in the same directory). Returns the
    recovered step numbers."""
    if not os.path.isdir(directory):
        return []
    recovered = []
    for name in os.listdir(directory):
        m = re.fullmatch(r"(step_\d+)\.old-\d+", name)
        if not m:
            continue
        canonical = os.path.join(directory, m.group(1))
        if not os.path.exists(canonical):
            os.rename(os.path.join(directory, name), canonical)
            recovered.append(int(m.group(1)[len("step_"):]))
    return recovered


def save(directory: str, step: int, state: Optional[Dict[str, Any]] = None,
         *, use_orbax: Optional[bool] = None, **extra: Any) -> str:
    """Snapshot ``state`` (a dict of pytrees, merged with ``extra``
    kwargs) under ``directory/step_N``.

    Returns the checkpoint path. Device arrays are fetched to host;
    orbax (when available) writes the tree natively.
    """
    state = {**(state or {}), **extra}
    if use_orbax is None:
        use_orbax = _HAVE_ORBAX
    path = _step_dir(directory, step)
    with _telemetry_trace.span("checkpoint/save", step=step,
                               orbax=use_orbax):
        os.makedirs(directory, exist_ok=True)
        repair_orphaned_steps(directory)
        host_state = jax.device_get(state)
        _write_state(path, host_state, use_orbax)
    return path


def restore(directory: str, step: Optional[int] = None, *,
            use_orbax: Optional[bool] = None,
            template: Any = None) -> Dict[str, Any]:
    """Load the state dict saved by :func:`save`.

    ``step=None`` loads the newest step. ``template`` (a pytree with the
    wanted structure/custom node types, e.g. the live training state) makes
    the orbax path restore into that structure — orbax stores custom pytree
    nodes (NamedTuples, dataclasses) structurally and returns plain dicts
    otherwise. Raises FileNotFoundError when no checkpoints exist.
    """
    if step is None:
        # The resume flow is where a step stranded mid-overwrite (crash
        # between _write_state's two renames) would otherwise silently
        # resolve to an OLDER step — recover parked dirs first. (Under
        # the single-writer discipline repair_orphaned_steps documents,
        # a concurrent writer mid-rename-window would fail its landing
        # rename loudly rather than lose data silently.)
        repair_orphaned_steps(directory)
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_dir(directory, step)
    pkl = os.path.join(path, "state.pkl")
    if use_orbax is None:
        use_orbax = _HAVE_ORBAX and not os.path.exists(pkl)
    with _telemetry_trace.span("checkpoint/restore", step=step,
                               orbax=use_orbax):
        if use_orbax:
            ckptr = ocp.PyTreeCheckpointer()
            if template is not None:
                restored = ckptr.restore(path,
                                         item=jax.device_get(template))
            else:
                restored = ckptr.restore(path)
            return dict(restored)
        with open(pkl, "rb") as f:
            return pickle.load(f)


def _write_state(path: str, host_state, use_orbax: bool) -> None:
    """Write into a temp dir, then rename to ``path`` — ``latest_step``'s
    ``step_\\d+`` fullmatch skips the temp name, so a concurrent
    ``restore(dir)`` never selects a checkpoint whose bytes are still
    landing (the async writer's whole window)."""
    import shutil

    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    try:
        if use_orbax:
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(tmp, host_state, force=True)
        else:
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "state.pkl"), "wb") as f:
                pickle.dump(host_state, f)
        old = None
        if os.path.exists(path):
            # force-overwrite: park the old dir under a non-matching name
            # first so a crash between the two renames leaves the data
            # recoverable and never a half-deleted step dir
            old = f"{path}.old-{os.getpid()}"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(path, old)
        os.rename(tmp, path)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    finally:
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)


class AsyncCheckpointer:
    """Overlap checkpoint serialization with the next training steps.

    ``save`` snapshots device arrays to host **synchronously** (fast —
    HBM-bandwidth D2H; and donation-safe: the next step may immediately
    invalidate the device buffers) and hands the slow part — disk
    serialization — to a background thread, returning before any byte
    hits storage. One checkpoint is in flight at a time: a new ``save``
    first waits for the previous write, and a failed write re-raises on
    the next ``save``/``wait_until_finished`` rather than vanishing.

    The reference has no async story (example-level blocking
    ``torch.save``, examples/imagenet/main_amp.py:95-101); this matches
    the orbax AsyncCheckpointer contract on the same `save`/`restore`
    layout as the blocking functions, so ``restore`` reads either.

        ckptr = AsyncCheckpointer()
        for step in range(n):
            state = train_step(state, batch)       # overlaps the write
            if step % 100 == 0:
                ckptr.save("ckpt/", step, params=state.params, ...)
        ckptr.wait_until_finished()
    """

    def __init__(self, *, use_orbax: Optional[bool] = None,
                 _pre_write_hook: Optional[Callable[[], None]] = None):
        self._use_orbax = _HAVE_ORBAX if use_orbax is None else use_orbax
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="apex_tpu_ckpt")
        self._future: Optional[concurrent.futures.Future] = None
        self._lock = threading.Lock()
        self._pre_write_hook = _pre_write_hook

    def save(self, directory: str, step: int,
             state: Optional[Dict[str, Any]] = None, **extra: Any) -> str:
        """Snapshot to host now, write in the background; returns the
        checkpoint path immediately."""
        with self._lock:
            self._join_locked()
            merged = {**(state or {}), **extra}
            path = _step_dir(directory, step)
            os.makedirs(directory, exist_ok=True)
            repair_orphaned_steps(directory)
            # synchronous D2H: after this the device buffers are free to
            # be donated/overwritten by the next step
            with _telemetry_trace.span("checkpoint/snapshot", step=step):
                host_state = jax.device_get(merged)

            def job():
                if self._pre_write_hook is not None:
                    self._pre_write_hook()
                with _telemetry_trace.span("checkpoint/async_write",
                                           step=step):
                    _write_state(path, host_state, self._use_orbax)

            self._future = self._pool.submit(job)
            return path

    def wait_until_finished(self) -> None:
        """Block until the in-flight write (if any) has landed; re-raises
        its error."""
        with self._lock:
            self._join_locked()

    def _join_locked(self) -> None:
        if self._future is not None:
            fut, self._future = self._future, None
            fut.result()  # propagate background-write failures

    def close(self) -> None:
        try:
            self.wait_until_finished()  # re-raises a failed write
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def save_training_state(directory: str, step: int, params, opt_state,
                        batch_stats=None, extra=None, **kw) -> str:
    """Convenience wrapper bundling the common training tuple + amp scaler
    state (the reference's model+optimizer+amp torch.save pattern)."""
    from apex_tpu import amp

    state = {"params": params, "opt_state": opt_state, "step": step}
    if batch_stats is not None:
        state["batch_stats"] = batch_stats
    if extra is not None:
        state["extra"] = extra
    try:
        state["amp"] = amp.state_dict()
    except Exception as e:
        import warnings

        warnings.warn(f"checkpoint: amp state not saved ({e})")
    return save(directory, step, state, **kw)


def restore_training_state(directory: str, step: Optional[int] = None,
                           **kw) -> Dict[str, Any]:
    """Load what :func:`save_training_state` wrote; re-installs amp scaler
    state when present and rebuilds the optimizer ScalerState (orbax
    stores NamedTuples structurally — pass ``template=`` for full custom-
    node fidelity on arbitrary states)."""
    from apex_tpu import amp
    from apex_tpu.amp.scaler import ScalerState

    state = restore(directory, step, **kw)
    opt_state = state.get("opt_state")
    if isinstance(opt_state, dict) and isinstance(opt_state.get("scaler"),
                                                  dict):
        opt_state["scaler"] = ScalerState(**opt_state["scaler"])
    if "amp" in state:
        try:
            amp.load_state_dict(state["amp"])
        except Exception as e:
            import warnings

            warnings.warn(
                f"checkpoint: amp scaler state failed to load ({e}); "
                "resuming with the current scaler — loss scale may differ "
                "from the saved run")
    return state
