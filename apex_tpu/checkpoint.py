"""Checkpoint / resume for training state, with durability guarantees.

Parity: the reference's checkpoint story (SURVEY.md §5) is amp
``state_dict``/``load_state_dict`` (apex/amp/frontend.py:365-404) plus
example-level ``torch.save`` of model+optimizer+amp
(examples/imagenet/main_amp.py:95-101). The TPU-native equivalent is a
single utility that snapshots the whole training state — params, optimizer
state (incl. fp32 masters and the loss-scaler state), batch stats, step —
via orbax when available (async, sharding-aware) with a pickle fallback.

    from apex_tpu import checkpoint
    checkpoint.save("ckpt/", step, params=params, opt_state=opt_state,
                    batch_stats=batch_stats)
    state = checkpoint.restore("ckpt/")          # latest step
    state = checkpoint.restore("ckpt/", step=5)  # specific step

Durability (the apex_tpu.resilience checkpoint pillar — docs/resilience.md):

- every ``save`` writes a ``manifest.json`` inside the step dir (landing
  atomically with the data): per-leaf tree paths/shapes/dtypes/crc32
  checksums plus per-file size/sha256 of every payload file, so a torn
  write, a bit flip, or a half-restored tree is *detectable*;
- ``restore`` verifies files before decoding and leaves after, wraps any
  decode failure (unpickle, orbax) in :class:`CheckpointCorruptError`,
  and — on the resume path (``step=None``) — walks back through older
  steps with a loud warning naming exactly what was rejected;
- transient write failures retry with exponential backoff + jitter
  (``retries`` / ``$APEX_TPU_CKPT_RETRIES``, telemetry counter
  ``checkpoint/write_retries``);
- ``keep_last_n`` prunes old steps only AFTER the new one has landed and
  passed shallow verification — retention can never eat the only good
  checkpoint.

Pre-manifest checkpoints (or foreign orbax trees) still restore: a
missing manifest downgrades to a warning, not a rejection.
"""

import concurrent.futures
import hashlib
import json
import os
import pickle
import random
import re
import threading
import time
import warnings
import zlib
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from apex_tpu.telemetry import trace as _telemetry_trace
from apex_tpu.telemetry.registry import get_registry as _get_registry

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except Exception:  # orbax missing or incompatible
    ocp = None
    _HAVE_ORBAX = False

MANIFEST_NAME = "manifest.json"
MANIFEST_FORMAT = 1
ENV_RETRIES = "APEX_TPU_CKPT_RETRIES"

# audit record of the last successful restore() in this process (see
# last_restore_metadata); None until a restore succeeded
_LAST_RESTORE_META: Optional[Dict[str, Any]] = None


def last_restore_metadata() -> Optional[Dict[str, Any]]:
    """The audit record of this process's most recent successful
    :func:`restore`: ``{"directory", "requested_step", "settled_step",
    "rejected": [{"step", "error"}], "fallback_depth"}`` — the answer
    to "what did the fallback chain actually load, and what did it walk
    past". None before any restore."""
    return _LAST_RESTORE_META


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification or could not be
    decoded (torn write, bit flip, truncated pickle, orbax failure).
    The resume path (``restore(dir)``) catches this per step and falls
    back to the next-older checkpoint."""


def _step_dir(directory: str, step: int) -> str:
    # orbax/tensorstore require absolute paths
    return os.path.join(os.path.abspath(directory), f"step_{step:010d}")


def _all_steps(directory: str):
    """Sorted (ascending) list of step numbers present in ``directory``."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = re.fullmatch(r"step_(\d+)", name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Newest checkpointed step in ``directory``, or None."""
    steps = _all_steps(directory)
    return steps[-1] if steps else None


def repair_orphaned_steps(directory: str) -> list:
    """Recover steps stranded by a crash inside ``_write_state``'s
    force-overwrite window: a death between ``rename(path, old)`` and
    ``rename(tmp, path)`` leaves the step only as ``step_N.old-<pid>``,
    which ``latest_step`` rightly skips. Renames each such dir back when
    (and only when) the canonical ``step_N`` is absent — if both exist
    the landed checkpoint is newer and the parked copy stays parked.
    Called from ``save`` (single-writer discipline: don't run it while
    another process is mid-save in the same directory). Returns the
    recovered step numbers."""
    if not os.path.isdir(directory):
        return []
    recovered = []
    for name in os.listdir(directory):
        m = re.fullmatch(r"(step_\d+)\.old-\d+", name)
        if not m:
            continue
        canonical = os.path.join(directory, m.group(1))
        if not os.path.exists(canonical):
            os.rename(os.path.join(directory, name), canonical)
            recovered.append(int(m.group(1)[len("step_"):]))
    return recovered


# ---------------------------------------------------------------------------
# manifest: per-leaf checksums + per-file hashes
# ---------------------------------------------------------------------------

def _key_str(k) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _leaf_fingerprint(leaf):
    """(crc32, dtype name, shape list) of one host-side leaf. Arrays
    checksum their raw bytes; anything numpy can't type (rare ``extra``
    payloads) falls back to its repr."""
    arr = np.asarray(leaf)
    if arr.dtype == object or arr.dtype.kind in ("U", "S"):
        return zlib.crc32(repr(leaf).encode()), "object", []
    return (zlib.crc32(np.ascontiguousarray(arr).tobytes()),
            arr.dtype.name, list(arr.shape))


def _manifest_for(host_state, writer: str) -> Dict[str, Any]:
    """The integrity manifest for a host-side state tree: tree
    structure, and per-leaf path/shape/dtype/crc32."""
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(host_state)
    leaves = []
    for path, leaf in paths_leaves:
        crc, dtype, shape = _leaf_fingerprint(leaf)
        leaves.append({"path": "/".join(_key_str(k) for k in path),
                       "shape": shape, "dtype": dtype, "crc32": crc})
    return {"format": MANIFEST_FORMAT, "writer": writer,
            "num_leaves": len(leaves), "treedef": str(treedef),
            "leaves": leaves}


def _sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hash_files(path: str) -> Dict[str, Dict[str, Any]]:
    """size + sha256 of every payload file under ``path`` (recursively;
    the manifest itself excluded) — works for the single-file pickle
    layout and orbax's ocdbt tree alike."""
    out = {}
    for root, _, names in os.walk(path):
        for name in names:
            if name == MANIFEST_NAME:
                continue
            full = os.path.join(root, name)
            h = hashlib.sha256()
            with open(full, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            out[os.path.relpath(full, path)] = {
                "size": os.path.getsize(full), "sha256": h.hexdigest()}
    return out


def _read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The step's manifest, None when absent (pre-manifest checkpoint),
    CheckpointCorruptError when present but unreadable."""
    mpath = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return None
    try:
        with open(mpath) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable {MANIFEST_NAME} ({e})") from e


def _verify_files(path: str, manifest: Dict[str, Any]) -> None:
    """Byte-level integrity: every manifest-listed file exists with the
    recorded size and sha256 (catches torn writes before a decoder sees
    the bytes)."""
    for rel, info in (manifest.get("files") or {}).items():
        full = os.path.join(path, rel)
        if not os.path.exists(full):
            raise CheckpointCorruptError(f"{path}: payload file {rel} "
                                         "missing")
        size = os.path.getsize(full)
        if size != info.get("size"):
            raise CheckpointCorruptError(
                f"{path}: {rel} is {size} bytes, manifest recorded "
                f"{info.get('size')} (torn write?)")
        h = hashlib.sha256()
        with open(full, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != info.get("sha256"):
            raise CheckpointCorruptError(
                f"{path}: {rel} sha256 mismatch (bit corruption?)")


def _verify_tree(restored, manifest: Dict[str, Any], path: str) -> None:
    """Logical integrity: the restored tree's leaves match the manifest
    (count, and per-path shape/dtype/crc32). Restore backends may
    re-spell container types (orbax returns plain dicts for NamedTuple
    nodes), so when the path *names* differ the comparison degrades to
    the multiset of leaf fingerprints rather than flagging a
    re-spelling as corruption."""
    want = manifest.get("leaves")
    if want is None:
        return
    got = _manifest_for(restored, manifest.get("writer", "?"))["leaves"]
    if len(got) != len(want):
        raise CheckpointCorruptError(
            f"{path}: restored {len(got)} leaves, manifest recorded "
            f"{len(want)}")
    want_by_path = {e["path"]: e for e in want}
    got_by_path = {e["path"]: e for e in got}
    if set(want_by_path) == set(got_by_path):
        for p, w in want_by_path.items():
            g = got_by_path[p]
            for field in ("shape", "dtype", "crc32"):
                if g[field] != w[field]:
                    raise CheckpointCorruptError(
                        f"{path}: leaf {p!r} {field} mismatch "
                        f"(restored {g[field]!r}, manifest {w[field]!r})")
    else:
        fp = lambda e: (e["dtype"], tuple(e["shape"]), e["crc32"])  # noqa: E731
        if sorted(map(fp, got)) != sorted(map(fp, want)):
            raise CheckpointCorruptError(
                f"{path}: restored leaf set does not match manifest "
                "(content checksums differ)")


def verify_checkpoint(path: str) -> Dict[str, Any]:
    """Shallow verification of a landed step dir: the manifest parses
    and every payload file matches its recorded size/sha256. Raises
    :class:`CheckpointCorruptError` (manifest absent counts as a
    failure — this is the gate retention uses before pruning). Returns
    the manifest."""
    manifest = _read_manifest(path)
    if manifest is None:
        raise CheckpointCorruptError(f"{path}: no {MANIFEST_NAME}")
    _verify_files(path, manifest)
    return manifest


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

def save(directory: str, step: int, state: Optional[Dict[str, Any]] = None,
         *, use_orbax: Optional[bool] = None, retries: Optional[int] = None,
         retry_base_delay: float = 0.05, keep_last_n: Optional[int] = None,
         **extra: Any) -> str:
    """Snapshot ``state`` (a dict of pytrees, merged with ``extra``
    kwargs) under ``directory/step_N``, with a ``manifest.json`` of
    content checksums landing atomically alongside the data.

    ``retries`` transient-write retries (default ``$APEX_TPU_CKPT_RETRIES``
    or 2) run with exponential backoff + jitter. ``keep_last_n`` prunes
    older steps — only after this one verified. Returns the checkpoint
    path. Device arrays are fetched to host; orbax (when available)
    writes the tree natively.
    """
    state = {**(state or {}), **extra}
    if use_orbax is None:
        use_orbax = _HAVE_ORBAX
    path = _step_dir(directory, step)
    with _telemetry_trace.span("checkpoint/save", step=step,
                               orbax=use_orbax):
        os.makedirs(directory, exist_ok=True)
        repair_orphaned_steps(directory)
        host_state = jax.device_get(state)
        _write_state_with_retries(path, host_state, use_orbax,
                                  retries=retries,
                                  retry_base_delay=retry_base_delay)
        if keep_last_n is not None:
            verify_checkpoint(path)  # never prune behind an unverified save
            _prune_old_steps(directory, keep_last_n)
    return path


def _write_state_with_retries(path: str, host_state, use_orbax: bool, *,
                              retries: Optional[int] = None,
                              retry_base_delay: float = 0.05) -> None:
    """``_write_state`` with exponential backoff + jitter on transient
    failures. ``retries`` counts re-attempts after the first try; the
    final failure re-raises. Each retry lands a
    ``checkpoint/write_retries`` counter tick and a warning."""
    if retries is None:
        retries = int(os.environ.get(ENV_RETRIES, "2"))
    attempt = 0
    while True:
        try:
            # module-global lookup on purpose: the fault injectors
            # (resilience.faults) patch checkpoint._write_state
            return _write_state(path, host_state, use_orbax)
        except Exception as e:
            if attempt >= retries:
                raise
            delay = retry_base_delay * (2 ** attempt)
            delay += random.uniform(0, delay)  # jitter: desync replicas
            attempt += 1
            reg = _get_registry()
            if reg.enabled:
                reg.counter("checkpoint/write_retries").inc()
                reg.event("checkpoint", "write_retry", path=path,
                          attempt=attempt, error=str(e)[:200])
            warnings.warn(
                f"checkpoint: write attempt {attempt}/{retries + 1} for "
                f"{path} failed ({type(e).__name__}: {e}); retrying in "
                f"{delay:.2f}s")
            time.sleep(delay)


def _prune_old_steps(directory: str, keep_last_n: int) -> list:
    """Retention: delete all but the newest ``keep_last_n`` steps.
    Only called after the newest step verified (see :func:`save`).
    Returns the pruned step numbers."""
    import shutil

    if keep_last_n < 1:
        raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
    steps = _all_steps(directory)
    pruned = steps[:-keep_last_n]
    for s in pruned:
        shutil.rmtree(_step_dir(directory, s), ignore_errors=True)
    if pruned:
        reg = _get_registry()
        if reg.enabled:
            reg.counter("checkpoint/steps_pruned").inc(len(pruned))
            reg.event("checkpoint", "pruned", steps=pruned,
                      kept=keep_last_n)
    return pruned


# ---------------------------------------------------------------------------
# restore (with verification + fallback chain)
# ---------------------------------------------------------------------------

def restore(directory: str, step: Optional[int] = None, *,
            use_orbax: Optional[bool] = None, template: Any = None,
            verify: bool = True,
            fallback: Optional[bool] = None,
            with_metadata: bool = False):
    """Load the state dict saved by :func:`save`.

    ``step=None`` loads the newest step — and, when that step fails
    verification or decoding (:class:`CheckpointCorruptError`), walks
    back through older steps with a loud warning naming what was
    rejected and why, until one verifies (``fallback`` defaults to True
    on the resume path, False for an explicit ``step``). ``verify=False``
    skips manifest verification entirely (not recommended outside
    debugging). ``template`` (a pytree with the wanted structure/custom
    node types, e.g. the live training state) makes the orbax path
    restore into that structure — orbax stores custom pytree nodes
    (NamedTuples, dataclasses) structurally and returns plain dicts
    otherwise. Raises FileNotFoundError when no checkpoints exist.

    ``with_metadata=True`` returns ``(state, metadata)`` where
    metadata is the audit record of what was *actually* loaded —
    ``settled_step``, the ``rejected`` ``[{"step", "error"}]`` the
    fallback chain walked past, and ``fallback_depth`` — so a
    supervisor (or a human reading the logs) can see that "resumed"
    meant "resumed from an OLDER step". The same record is always
    kept at :func:`last_restore_metadata`, and a non-empty fallback
    additionally lands the ``checkpoint/restore_fallback_step`` gauge
    + a ``restore_fallback`` event in the registry.
    """
    global _LAST_RESTORE_META
    if fallback is None:
        fallback = step is None
    if step is None:
        # The resume flow is where a step stranded mid-overwrite (crash
        # between _write_state's two renames) would otherwise silently
        # resolve to an OLDER step — recover parked dirs first. (Under
        # the single-writer discipline repair_orphaned_steps documents,
        # a concurrent writer mid-rename-window would fail its landing
        # rename loudly rather than lose data silently.)
        repair_orphaned_steps(directory)
        candidates = _all_steps(directory)[::-1]  # newest first
        if not candidates:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    else:
        candidates = [step]
    rejected = []
    for i, s in enumerate(candidates):
        try:
            restored = _restore_step(directory, s, use_orbax=use_orbax,
                                     template=template, verify=verify)
            meta = {
                "directory": directory,
                "requested_step": step,
                "settled_step": s,
                "rejected": [{"step": rs, "error": str(re)[:300]}
                             for rs, re in rejected],
                "fallback_depth": len(rejected),
            }
            _LAST_RESTORE_META = meta
            if rejected:
                reg = _get_registry()
                if reg.enabled:
                    reg.gauge("checkpoint/restore_fallback_step").set(s)
                    reg.event("checkpoint", "restore_fallback",
                              settled_step=s,
                              rejected_steps=[r["step"]
                                              for r in meta["rejected"]])
            return (restored, meta) if with_metadata else restored
        except CheckpointCorruptError as e:
            if not fallback:
                raise
            rejected.append((s, e))
            reg = _get_registry()
            if reg.enabled:
                reg.counter("checkpoint/restore_rejected").inc()
                reg.event("checkpoint", "restore_rejected", step=s,
                          error=str(e)[:300])
            older = candidates[i + 1] if i + 1 < len(candidates) else None
            warnings.warn(
                f"checkpoint: REJECTED step {s} under {directory} — "
                f"{e} — "
                + (f"falling back to step {older}" if older is not None
                   else "no older step to fall back to"))
    raise CheckpointCorruptError(
        f"every checkpoint under {directory} failed to load: "
        + "; ".join(f"step {s}: {e}" for s, e in rejected))


def _restore_step(directory: str, step: int, *,
                  use_orbax: Optional[bool] = None, template: Any = None,
                  verify: bool = True) -> Dict[str, Any]:
    """Load + verify one step. Any integrity or decode failure —
    manifest/file mismatch, unpickle error, orbax failure, a step dir
    with no loadable payload — surfaces as
    :class:`CheckpointCorruptError` so the fallback chain (and callers)
    see one failure type instead of an opaque backend traceback."""
    path = _step_dir(directory, step)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint dir {path}")
    manifest = _read_manifest(path)
    pkl = os.path.join(path, "state.pkl")
    if use_orbax is None:
        use_orbax = _HAVE_ORBAX and not os.path.exists(pkl)
    with _telemetry_trace.span("checkpoint/restore", step=step,
                               orbax=use_orbax):
        if manifest is not None and verify:
            _verify_files(path, manifest)
        elif manifest is None and verify:
            warnings.warn(
                f"checkpoint: {path} has no {MANIFEST_NAME} "
                "(pre-manifest checkpoint?) — loading without integrity "
                "verification")
        if use_orbax:
            if not _HAVE_ORBAX:
                raise CheckpointCorruptError(
                    f"{path}: no state.pkl and orbax is unavailable — "
                    "nothing can decode this step")
            try:
                ckptr = ocp.PyTreeCheckpointer()
                if template is not None:
                    restored = ckptr.restore(path,
                                             item=jax.device_get(template))
                else:
                    restored = ckptr.restore(path)
                restored = dict(restored)
            except CheckpointCorruptError:
                raise
            except Exception as e:
                raise CheckpointCorruptError(
                    f"{path}: orbax restore failed "
                    f"({type(e).__name__}: {str(e)[:300]})") from e
        else:
            if not os.path.exists(pkl):
                raise CheckpointCorruptError(
                    f"{path}: state.pkl missing")
            try:
                with open(pkl, "rb") as f:
                    restored = pickle.load(f)
            except Exception as e:
                raise CheckpointCorruptError(
                    f"{path}: state.pkl failed to unpickle "
                    f"({type(e).__name__}: {str(e)[:300]})") from e
        if manifest is not None and verify:
            _verify_tree(restored, manifest, path)
    return restored


def _write_state(path: str, host_state, use_orbax: bool) -> None:
    """Write into a temp dir — payload first, then the integrity
    manifest (leaf checksums + per-file hashes) — then rename to
    ``path``: ``latest_step``'s ``step_\\d+`` fullmatch skips the temp
    name, so a concurrent ``restore(dir)`` never selects a checkpoint
    whose bytes are still landing (the async writer's whole window),
    and the manifest is atomically present for every landed step."""
    import shutil

    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    try:
        if use_orbax:
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(tmp, host_state, force=True)
        else:
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "state.pkl"), "wb") as f:
                pickle.dump(host_state, f)
        manifest = _manifest_for(host_state,
                                 "orbax" if use_orbax else "pickle")
        manifest["files"] = _hash_files(tmp)
        with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f)
        old = None
        if os.path.exists(path):
            # force-overwrite: park the old dir under a non-matching name
            # first so a crash between the two renames leaves the data
            # recoverable and never a half-deleted step dir
            old = f"{path}.old-{os.getpid()}"
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(path, old)
        os.rename(tmp, path)
        if old is not None:
            shutil.rmtree(old, ignore_errors=True)
    finally:
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)


class AsyncCheckpointer:
    """Overlap checkpoint serialization with the next training steps.

    ``save`` snapshots device arrays to host **synchronously** (fast —
    HBM-bandwidth D2H; and donation-safe: the next step may immediately
    invalidate the device buffers) and hands the slow part — disk
    serialization — to a background thread, returning before any byte
    hits storage. One checkpoint is in flight at a time: a new ``save``
    first waits for the previous write, and a failed write re-raises on
    the next ``save``/``wait_until_finished``/``close`` rather than
    vanishing. A failed write never lands its step dir, so
    ``latest_step``/``restore`` can never select it.

    The background write runs the same durability path as the blocking
    :func:`save`: manifest, transient-failure retries (``retries``),
    and ``keep_last_n`` retention gated on post-landing verification.

    The reference has no async story (example-level blocking
    ``torch.save``, examples/imagenet/main_amp.py:95-101); this matches
    the orbax AsyncCheckpointer contract on the same `save`/`restore`
    layout as the blocking functions, so ``restore`` reads either.

        ckptr = AsyncCheckpointer()
        for step in range(n):
            state = train_step(state, batch)       # overlaps the write
            if step % 100 == 0:
                ckptr.save("ckpt/", step, params=state.params, ...)
        ckptr.wait_until_finished()
    """

    def __init__(self, *, use_orbax: Optional[bool] = None,
                 retries: Optional[int] = None,
                 retry_base_delay: float = 0.05,
                 keep_last_n: Optional[int] = None,
                 _pre_write_hook: Optional[Callable[[], None]] = None):
        self._use_orbax = _HAVE_ORBAX if use_orbax is None else use_orbax
        self._retries = retries
        self._retry_base_delay = retry_base_delay
        self._keep_last_n = keep_last_n
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="apex_tpu_ckpt")
        self._future: Optional[concurrent.futures.Future] = None
        self._lock = threading.Lock()
        self._pre_write_hook = _pre_write_hook

    def save(self, directory: str, step: int,
             state: Optional[Dict[str, Any]] = None, **extra: Any) -> str:
        """Snapshot to host now, write in the background; returns the
        checkpoint path immediately."""
        with self._lock:
            self._join_locked()
            merged = {**(state or {}), **extra}
            path = _step_dir(directory, step)
            os.makedirs(directory, exist_ok=True)
            repair_orphaned_steps(directory)
            # synchronous D2H: after this the device buffers are free to
            # be donated/overwritten by the next step
            with _telemetry_trace.span("checkpoint/snapshot", step=step):
                host_state = jax.device_get(merged)

            def job():
                if self._pre_write_hook is not None:
                    self._pre_write_hook()
                with _telemetry_trace.span("checkpoint/async_write",
                                           step=step):
                    _write_state_with_retries(
                        path, host_state, self._use_orbax,
                        retries=self._retries,
                        retry_base_delay=self._retry_base_delay)
                    if self._keep_last_n is not None:
                        verify_checkpoint(path)
                        _prune_old_steps(directory, self._keep_last_n)

            self._future = self._pool.submit(job)
            return path

    def wait_until_finished(self) -> None:
        """Block until the in-flight write (if any) has landed; re-raises
        its error."""
        with self._lock:
            self._join_locked()

    def _join_locked(self) -> None:
        if self._future is not None:
            fut, self._future = self._future, None
            fut.result()  # propagate background-write failures

    def close(self) -> None:
        try:
            self.wait_until_finished()  # re-raises a failed write
        finally:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def save_training_state(directory: str, step: int, params, opt_state,
                        batch_stats=None, extra=None, topology=None,
                        **kw) -> str:
    """Convenience wrapper bundling the common training tuple + amp scaler
    state (the reference's model+optimizer+amp torch.save pattern).

    ``topology`` records the WRITING topology in the checkpoint (and so
    in its manifest) — ``{"world": 8, "axis_name": "dp", "optimizer":
    "DistributedFusedAdam", "block_size": 256}`` or whatever the run's
    sharded state needs for an elastic restore. ZeRO shards written at
    world=8 can only be re-partitioned onto a world=4 mesh if the
    restorer knows they WERE world=8 —
    ``DistributedFusedAdam.load_state_dict_resharded`` consumes exactly
    this record (docs/resilience.md, "Supervised training")."""
    from apex_tpu import amp

    state = {"params": params, "opt_state": opt_state, "step": step}
    if batch_stats is not None:
        state["batch_stats"] = batch_stats
    if extra is not None:
        state["extra"] = extra
    if topology is not None:
        state["topology"] = {k: v for k, v in dict(topology).items()}
    try:
        state["amp"] = amp.state_dict()
    except Exception as e:
        warnings.warn(f"checkpoint: amp state not saved ({e})")
    return save(directory, step, state, **kw)


def restore_training_state(directory: str, step: Optional[int] = None,
                           **kw):
    """Load what :func:`save_training_state` wrote; re-installs amp scaler
    state when present and rebuilds the optimizer ScalerState (orbax
    stores NamedTuples structurally — pass ``template=`` for full custom-
    node fidelity on arbitrary states). The saved ``topology`` record
    (writing world size etc.) comes back under ``state["topology"]``;
    ``with_metadata=True`` forwards to :func:`restore` and returns
    ``(state, metadata)``."""
    from apex_tpu import amp
    from apex_tpu.amp.scaler import ScalerState

    out = restore(directory, step, **kw)
    state, meta = out if kw.get("with_metadata") else (out, None)
    opt_state = state.get("opt_state")
    if isinstance(opt_state, dict) and isinstance(opt_state.get("scaler"),
                                                  dict):
        opt_state["scaler"] = ScalerState(**opt_state["scaler"])
    if "amp" in state:
        try:
            amp.load_state_dict(state["amp"])
        except Exception as e:
            warnings.warn(
                f"checkpoint: amp scaler state failed to load ({e}); "
                "resuming with the current scaler — loss scale may differ "
                "from the saved run")
    return (state, meta) if meta is not None else state
