"""Fused dense layers.

Parity: reference apex/fused_dense (fused_dense.py:64 ``FusedDense``, 82
``FusedDenseGeluDense`` + csrc/fused_dense_cuda.cu): GEMM+bias and
GEMM+bias+GeLU+GEMM+bias fused chains. XLA fuses these epilogues on TPU;
the module/function surface is kept 1:1.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


def fused_dense_function(x, weight, bias=None):
    """y = x @ w.T + b (parity: fused_dense_cuda linear_bias_forward)."""
    y = jnp.matmul(x, weight.T, preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if bias is not None:
        y = y + bias
    return y


def fused_dense_gelu_dense_function(x, w1, b1, w2, b2):
    """y = gelu(x @ w1.T + b1) @ w2.T + b2."""
    h = fused_dense_function(x, w1, b1)
    h = jax.nn.gelu(h.astype(jnp.float32)).astype(x.dtype)
    return fused_dense_function(h, w2, b2)


class FusedDense(nn.Module):
    in_features: int
    out_features: int
    bias: bool = True
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.out_features, self.in_features), self.param_dtype)
        b = (self.param("bias", nn.initializers.zeros, (self.out_features,),
                        self.param_dtype) if self.bias else None)
        return fused_dense_function(x, w, b)


class DenseNoBias(nn.Module):
    """Parity: reference DenseNoBias."""

    in_features: int
    out_features: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.lecun_normal(),
                       (self.out_features, self.in_features), self.param_dtype)
        return fused_dense_function(x, w, None)


class FusedDenseGeluDense(nn.Module):
    in_features: int
    intermediate_features: int
    out_features: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        w1 = self.param("weight1", nn.initializers.lecun_normal(),
                        (self.intermediate_features, self.in_features),
                        self.param_dtype)
        b1 = self.param("bias1", nn.initializers.zeros,
                        (self.intermediate_features,), self.param_dtype)
        w2 = self.param("weight2", nn.initializers.lecun_normal(),
                        (self.out_features, self.intermediate_features),
                        self.param_dtype)
        b2 = self.param("bias2", nn.initializers.zeros,
                        (self.out_features,), self.param_dtype)
        return fused_dense_gelu_dense_function(x, w1, b1, w2, b2)
