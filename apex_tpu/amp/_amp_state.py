"""Process-global amp state.

Parity: reference apex/amp/_amp_state.py:8-59 (singleton holding handle,
loss_scalers, opt_properties, verbosity).
"""


class AmpState(object):
    def __init__(self):
        self.hard_override = False
        self.allow_incoming_model_not_fp32 = False
        self.verbosity = 1
        self.opt_properties = None
        self.loss_scalers = []
        self.optimizers = []

    def reset(self):
        self.__init__()


_amp_state = AmpState()


def maybe_print(msg, rank0=False):
    if _amp_state.verbosity > 0:
        print(msg)


def master_params(optimizer):
    """Iterate over the fp32 master params of an AmpOptimizer
    (parity: apex/amp/_amp_state.py master_params)."""
    import jax

    state = getattr(optimizer, "last_state", None)
    inner = state.get("inner", {}) if state is not None else {}
    for key in ("amp_master", "master"):
        if key in inner:
            yield from jax.tree_util.tree_leaves(inner[key])
            return
