"""apex_tpu.amp — automatic mixed precision for TPU.

Parity: reference apex/amp (frontend.py:197 ``initialize``, handle.py:16
``scale_loss``, amp.py:30-70 registry decorators, frontend.py:365-404
``state_dict``/``load_state_dict``).

TPU design: fp16+loss-scaling on GPU becomes bf16-first on TPU. O1's
runtime monkey-patching of the torch namespace has no JAX analog — tracing
happens once under jit — so O1 maps to a *dtype policy* that apex_tpu's
layers consult (``amp.autocast`` / ``amp.policy``) plus the
``apex_tpu.amp.{jnp,nn,lax}`` shim namespaces: user code importing
``from apex_tpu.amp import jnp`` gets the reference's O1 white/black-list
casts (amp/lists.py) on its own ops. O2/O3 map to whole-model casts with
fp32 master weights kept by the wrapped optimizer.
The ``LossScaler`` keeps the reference's dynamic-scaling semantics (init
2^16, window 2000, halve on overflow) in a functional, jit-friendly state.
"""

from apex_tpu.amp.frontend import (  # noqa: F401
    initialize,
    state_dict,
    load_state_dict,
    Properties,
    O0,
    O1,
    O2,
    O3,
)
from apex_tpu.amp.handle import scale_loss, disable_casts  # noqa: F401
from apex_tpu.amp.scaler import LossScaler, ScalerState  # noqa: F401
from apex_tpu.amp.policy import (  # noqa: F401
    autocast,
    current_policy,
    set_global_policy,
    DtypePolicy,
    half_function,
    float_function,
    promote_function,
    register_half_function,
    register_float_function,
    register_promote_function,
)
from apex_tpu.amp.amp_optimizer import AmpOptimizer  # noqa: F401
from apex_tpu.amp._amp_state import _amp_state  # noqa: F401
from apex_tpu.amp import jnp  # noqa: F401  (O1 shim namespaces)
from apex_tpu.amp import lax  # noqa: F401
from apex_tpu.amp import lists  # noqa: F401
from apex_tpu.amp import nn  # noqa: F401
