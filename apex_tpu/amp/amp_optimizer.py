"""AmpOptimizer — the optimizer wrapper produced by ``amp.initialize``.

Parity: reference apex/amp/_process_optimizer.py:321-489, which attaches
master-weight management and grad unscale hooks to a torch optimizer. Here
the same responsibilities are one functional stepper:

    state = opt.init(params)
    new_params, new_state = opt.step(grads, state, params)

per step it (1) unscales grads by the live loss scale, (2) detects
inf/nan, (3) runs the wrapped optimizer's update branch-free-skipped on
overflow (reference handle.py:128-154 step patching), (4) updates the
dynamic scaler state, (5) for O2, keeps fp32 master weights and re-casts
into the low-precision model params (reference
_process_optimizer.py:28-90 ``lazy_init_with_master_weights``).
"""

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, ScalerState
from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_scale


class AmpOptimizer(object):
    def __init__(self, optimizer, scaler: LossScaler, master_weights=False,
                 model_dtype=None):
        self.inner = optimizer
        self.scaler = scaler
        self.master_weights = master_weights
        self.model_dtype = model_dtype
        self.last_state = None

    # accessors forwarded for parity with torch optimizer interface
    @property
    def lr(self):
        return self.inner.lr

    def init(self, params):
        inner_state = self.inner.init(params)
        # If the wrapped optimizer maintains its own fp32 masters
        # (e.g. FusedAdam(master_weights=True)), defer to it entirely.
        # Amp-owned masters live under a distinct key so ownership is
        # derivable from a (possibly checkpoint-restored) state alone.
        if self.master_weights and "master" not in inner_state:
            # alias-free copy: astype is a no-op on already-fp32 leaves
            # (all norm params under O2) and would alias masters to the
            # live params — donating both then trips XLA's
            # donate-same-buffer-twice check (the double-donation lint
            # rule in apex_tpu.analysis catches this at trace time)
            from apex_tpu.optimizers._base import master_copy_tree

            inner_state["amp_master"] = master_copy_tree(params)
        return {"inner": inner_state, "scaler": self.scaler.init_state()}

    def step(self, grads, state, params, *, lr=None):
        scaler_state: ScalerState = state["scaler"]
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        inv = 1.0 / scaler_state.loss_scale
        unscaled, found_inf = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros((), jnp.float32), [leaves, leaves], inv)
        grads = jax.tree_util.tree_unflatten(treedef, unscaled)

        if "amp_master" in state["inner"]:
            # Update runs on fp32 masters; model params are re-cast copies.
            masters = state["inner"]["amp_master"]
            inner_wo_master = {k: v for k, v in state["inner"].items()
                               if k != "amp_master"}
            new_masters, new_inner = self.inner.step(
                grads, inner_wo_master, masters, lr=lr, found_inf=found_inf)
            new_params = jax.tree_util.tree_map(
                lambda m, p: m.astype(p.dtype), new_masters, params)
            new_inner["amp_master"] = new_masters
        else:
            new_params, new_inner = self.inner.step(
                grads, state["inner"], params, lr=lr, found_inf=found_inf)

        new_scaler = self.scaler.update(scaler_state, found_inf)
        new_state = {"inner": new_inner, "scaler": new_scaler}
        self.last_state = new_state
        return new_params, new_state

    def scale_loss(self, loss, state=None):
        sstate = state["scaler"] if state is not None else self.scaler._state
        return loss.astype(jnp.float32) * sstate.loss_scale

    # torch-optimizer-style checkpoint hooks
    def state_dict(self):
        return {"scaler": self.scaler.state_dict()}

    def load_state_dict(self, sd):
        self.scaler.load_state_dict(sd["scaler"])
