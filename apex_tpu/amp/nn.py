"""``apex_tpu.amp.nn`` — O1 shim over ``jax.nn`` (see amp/jnp.py).

Parity: reference apex/amp/lists/functional_overrides.py — softmax /
log_softmax and friends run fp32, activations run in the compute dtype.
"""

import jax.nn as _nn

from apex_tpu.amp import lists as _lists
from apex_tpu.amp.policy import float_function, half_function

_WRAPPED = {}
for _name in _lists.NN_HALF:
    if hasattr(_nn, _name):
        _WRAPPED[_name] = half_function(getattr(_nn, _name))
for _name in _lists.NN_FLOAT:
    if hasattr(_nn, _name):
        _WRAPPED[_name] = float_function(getattr(_nn, _name))
globals().update(_WRAPPED)


def __getattr__(name):
    return getattr(_nn, name)


def __dir__():
    return sorted(set(dir(_nn)) | set(_WRAPPED))
