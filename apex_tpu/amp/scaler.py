"""Loss scaler with static + dynamic modes.

Parity: reference apex/amp/scaler.py:33-217 — dynamic init 2^16,
``scale_window=2000``, halve on overflow / double after 2000 clean steps
(197-217); ``unscale`` via ``multi_tensor_scale`` with overflow detection.

TPU design: the scaler state is a small pytree (scale, unskipped counter) so
the whole scale/unscale/update cycle lives inside one jitted train step —
no host sync on the overflow flag (the reference D2H-syncs at
scaler.py:200). On bf16 the scaler degenerates to scale=1 but the API and
state survive, as required for checkpoint parity.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu.ops import multi_tensor_scale


class ScalerState(NamedTuple):
    loss_scale: jnp.ndarray  # f32 scalar
    unskipped: jnp.ndarray   # i32 steps since last overflow


class LossScaler(object):
    warned_no_fused_kernel = False
    warned_unscaling_non_fp32_grad = False
    has_fused_kernel = True

    def __init__(self, loss_scale, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, min_loss_scale=None, max_loss_scale=2.0 ** 24):
        if loss_scale == "dynamic":
            self.dynamic = True
            self._loss_scale = min(max_loss_scale, init_scale)
        else:
            self.dynamic = False
            self._loss_scale = loss_scale
        self._max_loss_scale = max_loss_scale
        self._min_loss_scale = min_loss_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        # Eager-mode mirror of the functional state.
        self._state = self.init_state()

    # -- functional API (jit-friendly) -------------------------------------
    def init_state(self) -> ScalerState:
        return ScalerState(
            loss_scale=jnp.asarray(self._loss_scale, jnp.float32),
            unskipped=jnp.zeros((), jnp.int32),
        )

    def scale(self, loss, state: ScalerState = None):
        s = (state or self._state).loss_scale
        return loss.astype(jnp.float32) * s

    def unscale_grads(self, grads, state: ScalerState = None):
        """Unscale a grad pytree; returns (unscaled_grads, found_inf f32)."""
        state = state or self._state
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        inv = 1.0 / state.loss_scale
        outs, found_inf = multi_tensor_applier(
            multi_tensor_scale, jnp.zeros((), jnp.float32), [leaves, leaves], inv)
        return jax.tree_util.tree_unflatten(treedef, outs), found_inf

    def update(self, state: ScalerState, found_inf) -> ScalerState:
        """Dynamic scale update (reference scaler.py:197-217).

        Telemetry: when the registry is enabled AND the values are
        concrete (eager use — ``update_scale``, host-side drivers), the
        new state lands as the ``amp/loss_scale`` gauge plus the
        ``amp/overflow`` / ``amp/scale_window_growth`` counters and an
        ``amp`` JSONL event, so scale dynamics are visible next to the
        guard events. Inside jit the values are tracers and recording
        is skipped entirely — telemetry never adds a host callback to
        the compiled update (the lowered HLO is byte-identical with the
        registry on or off), and a disabled registry costs one
        attribute read, no allocation.
        """
        if not self.dynamic:
            return state
        overflow = found_inf > 0
        # explicit None test: min_loss_scale=0 is a legal floor ("no
        # floor at all") that a truthiness check silently coerced to 1.0
        floor = 1.0 if self._min_loss_scale is None else self._min_loss_scale
        new_scale = jnp.where(
            overflow,
            jnp.maximum(state.loss_scale / self._scale_factor, floor),
            jnp.where(state.unskipped + 1 >= self._scale_window,
                      jnp.minimum(state.loss_scale * self._scale_factor,
                                  self._max_loss_scale),
                      state.loss_scale))
        new_unskipped = jnp.where(
            overflow | (state.unskipped + 1 >= self._scale_window),
            0, state.unskipped + 1).astype(jnp.int32)
        new_state = ScalerState(new_scale, new_unskipped)
        self.record_update(state, new_state, found_inf)
        return new_state

    def record_update(self, state: ScalerState, new_state: ScalerState,
                      found_inf, registry=None):
        """Host-side telemetry for one scale update. No-op (and
        allocation-free) when the registry is disabled, and a no-op
        under tracing — concrete values are required, so callers
        polling device-side scaler state can invoke this directly with
        the fetched states."""
        from apex_tpu.telemetry.registry import get_registry

        reg = registry or get_registry()
        if not reg.enabled:
            return
        if any(isinstance(v, jax.core.Tracer)
               for v in (state.loss_scale, new_state.loss_scale,
                         found_inf)):
            return
        scale = float(new_state.loss_scale)
        prev = float(state.loss_scale)
        overflow = float(found_inf) > 0
        grew = scale > prev
        reg.gauge("amp/loss_scale").set(scale)
        if overflow:
            reg.counter("amp/overflow").inc()
        if grew:
            reg.counter("amp/scale_window_growth").inc()
        reg.event("amp", "loss_scale", scale=scale, overflow=overflow,
                  grew=grew, unskipped=int(new_state.unskipped))

    # -- eager/stateful API (reference parity) -----------------------------
    def loss_scale(self):
        return float(self._state.loss_scale)

    def unscale(self, grads):
        grads, found_inf = self.unscale_grads(grads, self._state)
        self._last_found_inf = found_inf
        return grads

    def update_scale(self):
        found_inf = getattr(self, "_last_found_inf", jnp.zeros((), jnp.float32))
        self._state = self.update(self._state, found_inf)
        self._last_found_inf = jnp.zeros((), jnp.float32)
        return bool(found_inf > 0)

    # -- checkpointing (reference frontend.py:365-404) ---------------------
    def state_dict(self):
        return {
            "loss_scale": float(self._state.loss_scale),
            "unskipped": int(self._state.unskipped),
            "dynamic": self.dynamic,
        }

    def load_state_dict(self, sd):
        self.dynamic = sd.get("dynamic", self.dynamic)
        self._state = ScalerState(
            loss_scale=jnp.asarray(sd["loss_scale"], jnp.float32),
            unskipped=jnp.asarray(sd.get("unskipped", 0), jnp.int32),
        )
