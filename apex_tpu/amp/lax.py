"""``apex_tpu.amp.lax`` — O1 shim over ``jax.lax`` (see amp/jnp.py).

Parity: reference apex/amp/lists/functional_overrides.py FP16 conv ops —
convolutions and dot_general are MXU-bound, so they run in the compute
dtype under the policy.
"""

import jax.lax as _lax

from apex_tpu.amp import lists as _lists
from apex_tpu.amp.policy import float_function, half_function

_WRAPPED = {}
for _name in _lists.LAX_HALF:
    if hasattr(_lax, _name):
        _WRAPPED[_name] = half_function(getattr(_lax, _name))
for _name in _lists.LAX_FLOAT:
    if hasattr(_lax, _name):
        _WRAPPED[_name] = float_function(getattr(_lax, _name))
globals().update(_WRAPPED)


def __getattr__(name):
    return getattr(_lax, name)


def __dir__():
    return sorted(set(dir(_lax)) | set(_WRAPPED))
