"""Dtype policy — the TPU analog of amp O1 function patching.

Parity: reference apex/amp/amp.py:30-183 (monkey-patching torch namespaces
per white/black lists, ``half_function``/``float_function``/
``promote_function`` decorators and ``register_*`` registry) and the cast
lists in apex/amp/lists/.

TPU design: under jit the program is traced once, so instead of patching a
namespace at runtime we maintain a context-scoped *policy* object that
apex_tpu layers (and user code, via the decorators) consult at trace time:
- compute ops (matmul/conv classes, the functional_overrides white list)
  run in ``compute_dtype`` (bf16 by default),
- reduction/loss ops (the black list) run in fp32,
- promote ops follow ``jnp.promote_types`` of their inputs.
"""

import contextlib
import functools
import threading

import jax
import jax.numpy as jnp


class DtypePolicy(object):
    def __init__(self, enabled=False, compute_dtype=jnp.bfloat16,
                 cast_model_outputs=None):
        self.enabled = enabled
        self.compute_dtype = compute_dtype
        self.cast_model_outputs = cast_model_outputs

    def cast_to_compute(self, *args):
        if not self.enabled:
            return args if len(args) > 1 else args[0]
        out = tuple(_cast_tree(a, self.compute_dtype) for a in args)
        return out if len(out) > 1 else out[0]

    def cast_to_float(self, *args):
        if not self.enabled:
            return args if len(args) > 1 else args[0]
        out = tuple(_cast_tree(a, jnp.float32) for a in args)
        return out if len(out) > 1 else out[0]


def _is_float_array(x):
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast_tree(tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_float_array(x) else x, tree)


_local = threading.local()

# Process-global default policy: amp.initialize(opt_level="O1") enables it
# (the analog of the reference's initialize-time namespace patching,
# apex/amp/amp.py:74-183 — active globally until changed). An autocast()
# block overrides it thread-locally.
_global_policy = DtypePolicy(enabled=False)


def set_global_policy(policy: DtypePolicy) -> None:
    global _global_policy
    _global_policy = policy


def current_policy() -> DtypePolicy:
    return getattr(_local, "policy", None) or _global_policy


@contextlib.contextmanager
def autocast(enabled=True, dtype=jnp.bfloat16):
    """Context manager enabling the compute-dtype policy (amp O1)."""
    prev = getattr(_local, "policy", None)
    _local.policy = DtypePolicy(enabled=enabled, compute_dtype=dtype)
    try:
        yield _local.policy
    finally:
        _local.policy = prev


# -- decorators (reference apex/amp/amp.py:30-70) ---------------------------

def half_function(fn):
    """Run ``fn`` with inputs cast to the compute dtype when amp is active."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol.enabled:
            args = _cast_tree(args, pol.compute_dtype)
            kwargs = _cast_tree(kwargs, pol.compute_dtype)
        return fn(*args, **kwargs)
    return wrapper


def float_function(fn):
    """Run ``fn`` in fp32 when amp is active (loss-like ops)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol.enabled:
            args = _cast_tree(args, jnp.float32)
            kwargs = _cast_tree(kwargs, jnp.float32)
        return fn(*args, **kwargs)
    return wrapper


def promote_function(fn):
    """Promote all floating inputs to the widest input dtype."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol.enabled:
            dtypes = [a.dtype for a in jax.tree_util.tree_leaves((args, kwargs))
                      if _is_float_array(a)]
            if dtypes:
                widest = functools.reduce(jnp.promote_types, dtypes)
                args = _cast_tree(args, widest)
                kwargs = _cast_tree(kwargs, widest)
        return fn(*args, **kwargs)
    return wrapper


# register_* operate on modules/objects in place (reference amp.py:42-70).

def register_half_function(module, name):
    setattr(module, name, half_function(getattr(module, name)))


def register_float_function(module, name):
    setattr(module, name, float_function(getattr(module, name)))


def register_promote_function(module, name):
    setattr(module, name, promote_function(getattr(module, name)))
