"""Dtype policy — the TPU analog of amp O1 function patching.

Parity: reference apex/amp/amp.py:30-183 (monkey-patching torch namespaces
per white/black lists, ``half_function``/``float_function``/
``promote_function`` decorators and ``register_*`` registry) and the cast
lists in apex/amp/lists/.

TPU design: under jit the program is traced once, so instead of patching a
namespace at runtime we maintain a context-scoped *policy* object that
apex_tpu layers (and user code, via the decorators) consult at trace time:
- compute ops (matmul/conv classes, the functional_overrides white list)
  run in ``compute_dtype`` (bf16 by default),
- reduction/loss ops (the black list) run in fp32,
- promote ops follow ``jnp.promote_types`` of their inputs.
"""

import contextlib
import functools
import threading

import jax
import jax.numpy as jnp


class DtypePolicy(object):
    def __init__(self, enabled=False, compute_dtype=jnp.bfloat16,
                 cast_model_outputs=None):
        self.enabled = enabled
        self.compute_dtype = compute_dtype
        self.cast_model_outputs = cast_model_outputs

    def cast_to_compute(self, *args):
        if not self.enabled:
            return args if len(args) > 1 else args[0]
        out = tuple(_cast_tree(a, self.compute_dtype) for a in args)
        return out if len(out) > 1 else out[0]

    def cast_to_float(self, *args):
        if not self.enabled:
            return args if len(args) > 1 else args[0]
        out = tuple(_cast_tree(a, jnp.float32) for a in args)
        return out if len(out) > 1 else out[0]


def _is_float_array(x):
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast_tree(tree, dtype):
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_float_array(x) else x, tree)


_local = threading.local()

# Process-global default policy: amp.initialize(opt_level="O1") enables it
# (the analog of the reference's initialize-time namespace patching,
# apex/amp/amp.py:74-183 — active globally until changed). An autocast()
# block overrides it thread-locally.
_global_policy = DtypePolicy(enabled=False)

# Trace-ordering hazard bookkeeping (VERDICT r2 weak #4): jit caches
# traces, and the policy is consulted at trace time — a user function
# traced while the policy was disabled silently keeps its fp32 trace
# after amp.initialize. We can't invalidate jit caches for the user, but
# we can detect the ordering: any shim op traced (tracer arguments) with
# the policy disabled sets a flag, and the first enabling flip afterwards
# warns once.
_trace_state = {"disabled_trace_seen": False, "warned": False}


def _note_disabled_trace(args, kwargs):
    # Best-effort and cheap: this runs on the disabled-policy passthrough
    # path of every shim op, so no pytree flatten — a top-level isinstance
    # scan catches the ordinary jnp-op call shapes, and once the hazard is
    # latched (or the one-shot warning has fired) it costs two dict reads.
    if _trace_state["disabled_trace_seen"] or _trace_state["warned"]:
        return
    for leaf in args if not kwargs else (*args, *kwargs.values()):
        if isinstance(leaf, jax.core.Tracer):
            _trace_state["disabled_trace_seen"] = True
            return


def set_global_policy(policy: DtypePolicy, verbosity: int = 0) -> None:
    """Install the process-global policy. With ``verbosity > 0`` a notice
    is logged whenever the enabled state actually flips (ADVICE r2:
    initialize() mutates process-global behavior — make the flip
    observable in multi-component processes)."""
    global _global_policy
    flipped = bool(policy.enabled) != bool(_global_policy.enabled)
    if flipped and verbosity > 0:
        import logging

        logging.getLogger("apex_tpu.amp").info(
            "amp: global dtype policy %s (compute dtype %s)",
            "enabled" if policy.enabled else "disabled",
            jnp.dtype(policy.compute_dtype).name)
    if (policy.enabled and _trace_state["disabled_trace_seen"]
            and not _trace_state["warned"]):
        _trace_state["warned"] = True
        import warnings

        warnings.warn(
            "apex_tpu.amp: the dtype policy was enabled AFTER amp shim "
            "ops were already traced with it disabled. jit caches traces, "
            "so functions jitted before amp.initialize keep their fp32 "
            "traces on later calls — call amp.initialize first, or clear "
            "the affected jit caches (jax.clear_caches()).",
            stacklevel=3)
    _global_policy = policy


def current_policy() -> DtypePolicy:
    return getattr(_local, "policy", None) or _global_policy


@contextlib.contextmanager
def autocast(enabled=True, dtype=jnp.bfloat16):
    """Context manager enabling the compute-dtype policy (amp O1)."""
    prev = getattr(_local, "policy", None)
    _local.policy = DtypePolicy(enabled=enabled, compute_dtype=dtype)
    try:
        yield _local.policy
    finally:
        _local.policy = prev


# -- decorators (reference apex/amp/amp.py:30-70) ---------------------------

def half_function(fn):
    """Run ``fn`` with inputs cast to the compute dtype when amp is active."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol.enabled:
            args = _cast_tree(args, pol.compute_dtype)
            kwargs = _cast_tree(kwargs, pol.compute_dtype)
        else:
            _note_disabled_trace(args, kwargs)
        return fn(*args, **kwargs)
    return wrapper


def float_function(fn):
    """Run ``fn`` in fp32 when amp is active (loss-like ops)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol.enabled:
            args = _cast_tree(args, jnp.float32)
            kwargs = _cast_tree(kwargs, jnp.float32)
        else:
            _note_disabled_trace(args, kwargs)
        return fn(*args, **kwargs)
    return wrapper


def promote_function(fn):
    """Promote all floating inputs to the widest input dtype."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if pol.enabled:
            dtypes = [a.dtype for a in jax.tree_util.tree_leaves((args, kwargs))
                      if _is_float_array(a)]
            if dtypes:
                widest = functools.reduce(jnp.promote_types, dtypes)
                args = _cast_tree(args, widest)
                kwargs = _cast_tree(kwargs, widest)
        else:
            _note_disabled_trace(args, kwargs)
        return fn(*args, **kwargs)
    return wrapper


# register_* operate on modules/objects in place (reference amp.py:42-70).

def register_half_function(module, name):
    setattr(module, name, half_function(getattr(module, name)))


def register_float_function(module, name):
    setattr(module, name, float_function(getattr(module, name)))


def register_promote_function(module, name):
    setattr(module, name, promote_function(getattr(module, name)))
