"""amp O1 cast lists for the jnp/nn/lax shim namespaces.

Parity: reference apex/amp/lists/{torch_overrides,functional_overrides,
tensor_overrides}.py — translated from torch op names to their
jax.numpy / jax.nn / jax.lax equivalents. Ops with no JAX analog
(in-place variants, RNN cells, torch-only losses) have no entry; jnp ops
not listed pass through untouched, which matches the reference's default
of leaving unlisted ops alone.

Three semantics (reference apex/amp/amp.py:74-183):
- HALF  ("fp16 on GPU" -> bf16 on TPU): MXU-bound ops — matmuls, convs.
- FLOAT (fp32): reductions, transcendentals, norms, losses — ops where
  bf16 accumulation loses too much precision.
- PROMOTE: multi-arg elementwise ops run in the widest input dtype
  (jnp's numpy-style promotion already does this; wrapping pins the
  documented semantics even if inputs carry weak types).

``REFERENCE_AUDIT`` at the bottom accounts for EVERY entry of the three
reference lists (VERDICT r2 item 4): each maps to its translation here or
to a documented reason there is no JAX analog.
``tests/L0/test_amp_cast_matrix.py`` asserts the audit is exhaustive
against the reference name sets and that every "translated" target really
is wrapped by a shim namespace.
"""

# jax.numpy names (reference torch_overrides.py FP16 list: mm, matmul,
# bmm, addmm/baddbmm family collapse to matmul/einsum in jnp)
JNP_HALF = (
    "matmul", "dot", "vdot", "inner", "outer", "tensordot", "einsum",
    "kron",
)

# reference torch_overrides.py FP32 list: acos, asin, cosh, erfinv, exp,
# expm1, log, log10, log1p, log2, reciprocal, rsqrt, sinh, tan, pow,
# prod, sum, cumprod, cumsum, norm, dist, renorm, ...
JNP_FLOAT = (
    "exp", "expm1", "log", "log1p", "log2", "log10", "power", "float_power",
    "prod", "sum", "cumprod", "cumsum", "mean", "var", "std", "median",
    "reciprocal", "sinh", "cosh", "tan", "arcsin", "arccos", "arctan",
    "arcsinh", "arccosh", "arctanh", "nansum", "nanprod", "nanmean",
    "trace", "interp",
)

# reference torch_overrides.py CASTS/promote list: add, div, mul, sub,
# cat, stack, equal-family, min/max, addcdiv/addcmul, ...
JNP_PROMOTE = (
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "remainder", "mod", "concatenate", "stack", "hstack", "vstack",
    "dstack", "column_stack", "where", "minimum", "maximum", "fmin",
    "fmax", "hypot", "heaviside", "logaddexp", "logaddexp2", "equal",
    "not_equal", "less", "less_equal", "greater", "greater_equal",
    "allclose", "isclose", "arctan2", "cross", "array_equal",
)

# jax.nn names (reference functional_overrides.py: FP16 = conv*/linear/
# attention-ish, FP32 = softmax/log_softmax + the loss zoo).
# DELIBERATE DEVIATION: the reference runs F.gelu in fp32 (its erf-based
# CUDA kernel was precision-sensitive); jax.nn.gelu (tanh approximation)
# is bf16-stable and standard on TPU (flax runs it in the compute dtype),
# so it stays HALF here.
NN_HALF = ("relu", "gelu", "silu", "swish", "glu", "leaky_relu", "elu",
           "celu", "selu", "hard_tanh", "relu6")
NN_FLOAT = ("softmax", "log_softmax", "logsumexp", "standardize",
            "softplus", "sigmoid", "log_sigmoid", "one_hot")

# jax.lax names (conv kernels — functional_overrides FP16 conv1d..3d,
# conv_transpose*; dot_general is the matmul primitive)
LAX_HALF = ("conv", "conv_with_general_padding", "conv_general_dilated",
            "conv_transpose", "dot", "dot_general", "batch_matmul")

# jax.lax names forced fp32 (reference torch FP32 rsqrt / erfinv — both
# live on jax.lax, not jax.numpy)
LAX_FLOAT = ("rsqrt", "erf_inv")

# jnp.linalg names forced fp32 (reference FP32 "norm", "dist")
LINALG_FLOAT = ("norm", "cond", "det", "slogdet", "eigvals", "eigvalsh",
                "svd", "qr", "cholesky", "inv", "pinv", "solve", "lstsq",
                "matrix_power", "matrix_rank")


# ---------------------------------------------------------------------------
# Reference-list audit (VERDICT r2 item 4). Status values:
#   "jnp:<name>" / "nn:<name>" / "lax:<name>" / "linalg:<name>"
#       translated — wrapped under that shim namespace entry.
#   "subsumed:<why>"
#       the behavior the reference enforces by wrapping is a *built-in
#       guarantee* of JAX semantics or of an apex_tpu layer; nothing to
#       wrap.
#   "no-analog:<why>"
#       the op does not exist in the JAX surface; the composition users
#       write instead is already covered by listed ops (or can be wrapped
#       with amp.float_function/half_function by hand).
#   "deviation:<why>"
#       a JAX analog exists and is DELIBERATELY placed in a different
#       class than the reference's, with the TPU rationale.
#
# Keys are the reference's names, grouped exactly as its three files
# group them, so the audit can be diffed against the reference lists.

_TORCH_CONV_FP16 = {
    # torch_overrides.py FP16_FUNCS (+ the CUDA>=9.1 _bmms branch)
    "conv1d": "lax:conv_general_dilated",
    "conv2d": "lax:conv_general_dilated",
    "conv3d": "lax:conv_general_dilated",
    "conv_transpose1d": "lax:conv_transpose",
    "conv_transpose2d": "lax:conv_transpose",
    "conv_transpose3d": "lax:conv_transpose",
    "conv_tbc": "no-analog:torch-only time-batch-channel layout; "
                "lax.conv_general_dilated covers it via dimension_numbers",
    "prelu": "no-analog:not in jax.nn; users compose "
             "where(x>0,x,a*x) from PROMOTE ops",
    "addmm": "jnp:matmul",   # fused add+mm: XLA fuses the add epilogue
    "addmv": "jnp:matmul",
    "addr": "jnp:outer",
    "matmul": "jnp:matmul",
    "mm": "jnp:matmul",
    "mv": "jnp:matmul",
    "addbmm": "jnp:matmul",
    "baddbmm": "jnp:matmul",
    "bmm": "jnp:matmul",
}

_TORCH_FP32 = {
    # torch_overrides.py FP32_FUNCS
    "acos": "jnp:arccos",
    "asin": "jnp:arcsin",
    "cosh": "jnp:cosh",
    "erfinv": "lax:erf_inv",
    "exp": "jnp:exp",
    "expm1": "jnp:expm1",
    "log": "jnp:log",
    "log10": "jnp:log10",
    "log2": "jnp:log2",
    "reciprocal": "jnp:reciprocal",
    "rsqrt": "lax:rsqrt",
    "sinh": "jnp:sinh",
    "tan": "jnp:tan",
    "pow": "jnp:power",
    "cumprod": "jnp:cumprod",
    "cumsum": "jnp:cumsum",
    "dist": "no-analog:torch-only; users write "
            "linalg.norm(a-b) — linalg:norm is wrapped",
    "mean": "jnp:mean",  # reference blacklists it only pre-torch-1.1
    "norm": "linalg:norm",
    "prod": "jnp:prod",
    "std": "jnp:std",
    "sum": "jnp:sum",
    "var": "jnp:var",
    "renorm": "no-analog:torch-only per-slice renorm; compose from "
              "linalg:norm + PROMOTE ops",
}

_TORCH_CASTS = {
    # torch_overrides.py CASTS + SEQUENCE_CASTS
    "addcdiv": "no-analog:fused a+v*t1/t2; composes of PROMOTE ops "
               "(add/multiply/divide), each promoting",
    "addcmul": "no-analog:fused a+v*t1*t2; same composition",
    "atan2": "jnp:arctan2",
    "cross": "jnp:cross",
    "bilinear": "no-analog:torch F.bilinear; users write einsum "
                "(jnp:einsum, HALF — matmul-class on the MXU)",
    "dot": "deviation:reference promotes; jnp:dot is HALF here — dot is "
           "matmul-class on the MXU and bf16-safe like mm/matmul",
    "add": "jnp:add",
    "div": "jnp:divide",
    "mul": "jnp:multiply",
    "eq": "jnp:equal",
    "equal": "jnp:array_equal",
    "ge": "jnp:greater_equal",
    "gt": "jnp:greater",
    "le": "jnp:less_equal",
    "lt": "jnp:less",
    "ne": "jnp:not_equal",
    "cat": "jnp:concatenate",
    "stack": "jnp:stack",
}

_FUNCTIONAL_FP16 = {
    # functional_overrides.py FP16_FUNCS (convs shared with torch list)
    "conv1d": "lax:conv_general_dilated",
    "conv2d": "lax:conv_general_dilated",
    "conv3d": "lax:conv_general_dilated",
    "conv_transpose1d": "lax:conv_transpose",
    "conv_transpose2d": "lax:conv_transpose",
    "conv_transpose3d": "lax:conv_transpose",
    "conv_tbc": "no-analog:see torch list",
    "linear": "subsumed:no jax.nn.linear; dense layers lower to "
              "lax:dot_general (wrapped HALF), and apex_tpu layers "
              "(fused_dense, mlp, tensor_parallel) manage dtypes "
              "explicitly",
}

_FUNCTIONAL_FP32 = {
    # functional_overrides.py FP32_FUNCS
    "interpolate": "no-analog:jax.image.resize (separate module); wrap "
                   "with amp.float_function if needed",
    "grid_sample": "no-analog:no JAX equivalent",
    "softplus": "nn:softplus",
    "softmin": "no-analog:not in jax.nn; softmin(x)=softmax(-x) — "
               "nn:softmax is wrapped",
    "log_softmax": "nn:log_softmax",
    "softmax": "nn:softmax",
    "gelu": "deviation:reference fp32 (erf-kernel precision); "
            "jax.nn.gelu (tanh approx) is bf16-stable -> NN_HALF",
    "layer_norm": "subsumed:apex_tpu.normalization.FusedLayerNorm "
                  "computes stats in fp32 regardless of input dtype "
                  "(the reason the reference forces fp32)",
    "group_norm": "subsumed:contrib.groupbn delegates to SyncBatchNorm "
                  "whose Welford stats are fp32",
    "local_response_norm": "no-analog:no JAX equivalent; compose from "
                           "FLOAT reductions",
    "normalize": "nn:standardize",
    "cosine_similarity": "no-analog:compose linalg:norm (FLOAT) + "
                         "jnp:sum (FLOAT)",
    # Loss zoo: JAX has no nn.functional loss namespace to shim — losses
    # live in optax / user code. The fp32 guarantee the reference buys by
    # wrapping these is provided here by (a) nn:softmax / nn:log_softmax /
    # nn:logsumexp forced fp32, (b) apex_tpu.contrib.xentropy and
    # focal_loss computing in fp32 internally, and (c) amp.float_function
    # for user-defined losses (the documented pattern).
    "poisson_nll_loss": "no-analog:loss zoo — see note above",
    "cosine_embedding_loss": "no-analog:loss zoo",
    "cross_entropy": "no-analog:loss zoo (apex_tpu.contrib.xentropy is "
                     "the in-repo fp32 implementation)",
    "hinge_embedding_loss": "no-analog:loss zoo",
    "kl_div": "no-analog:loss zoo",
    "l1_loss": "no-analog:loss zoo",
    "mse_loss": "no-analog:loss zoo",
    "margin_ranking_loss": "no-analog:loss zoo",
    "multilabel_margin_loss": "no-analog:loss zoo",
    "multilabel_soft_margin_loss": "no-analog:loss zoo",
    "multi_margin_loss": "no-analog:loss zoo",
    "nll_loss": "no-analog:loss zoo",
    "binary_cross_entropy_with_logits": "no-analog:loss zoo "
                                        "(optax.sigmoid_binary_cross_"
                                        "entropy; fp32 via nn:log_sigmoid)",
    "smooth_l1_loss": "no-analog:loss zoo",
    "soft_margin_loss": "no-analog:loss zoo",
    "triplet_margin_loss": "no-analog:loss zoo",
    "ctc_loss": "no-analog:loss zoo (optax.ctc_loss)",
    # BANNED_FUNCS
    "binary_cross_entropy": "subsumed:the reference bans it because a "
                            "preceding sigmoid may emit fp16; in JAX the "
                            "user owns dtypes end to end and "
                            "nn:log_sigmoid is forced fp32 — use "
                            "_with_logits form, same guidance",
}

_TENSOR_OVERRIDES = {
    # tensor_overrides.py: method/dunder mirrors of the torch list.
    # jax.Array methods cannot be (and need not be) monkey-patched:
    "__matmul__": "subsumed:a @ b dispatches to the same dot_general XLA "
                  "primitive as jnp.matmul; inside amp-aware code use "
                  "amp.jnp.matmul (HALF). NumPy promotion makes mixed "
                  "operands well-defined either way",
    "__pow__": "subsumed:jnp power promotes to the widest float; for the "
               "fp32 guarantee use amp.jnp.power (FLOAT)",
    "__ipow__": "no-analog:in-place op; jax arrays are immutable",
    "__rpow__": "subsumed:see __pow__",
    "cpu": "subsumed:jax.device_get preserves dtype; no cast needed on "
           "transfer",
    # CASTS dunders (__add__, __mul__, comparison family, in-place and
    # reflected variants): torch *errors* on half+float arithmetic, so
    # the reference must wrap every dunder to promote. jnp's NumPy type
    # promotion already computes in the widest input dtype — the exact
    # PROMOTE semantics — as a language guarantee.
    "__add__": "subsumed:NumPy promotion is the PROMOTE semantics",
    "__div__": "subsumed:NumPy promotion",
    "__eq__": "subsumed:NumPy promotion",
    "__ge__": "subsumed:NumPy promotion",
    "__gt__": "subsumed:NumPy promotion",
    "__iadd__": "no-analog:in-place; jax arrays are immutable",
    "__idiv__": "no-analog:in-place",
    "__imul__": "no-analog:in-place",
    "__isub__": "no-analog:in-place",
    "__itruediv__": "no-analog:in-place",
    "__le__": "subsumed:NumPy promotion",
    "__lt__": "subsumed:NumPy promotion",
    "__mul__": "subsumed:NumPy promotion",
    "__ne__": "subsumed:NumPy promotion",
    "__radd__": "subsumed:NumPy promotion",
    "__rdiv__": "subsumed:NumPy promotion",
    "__rmul__": "subsumed:NumPy promotion",
    "__rsub__": "subsumed:NumPy promotion",
    "__rtruediv__": "subsumed:NumPy promotion",
    "__sub__": "subsumed:NumPy promotion",
    "__truediv__": "subsumed:NumPy promotion",
}

REFERENCE_AUDIT = {
    "torch_overrides.FP16_FUNCS": _TORCH_CONV_FP16,
    "torch_overrides.FP32_FUNCS": _TORCH_FP32,
    "torch_overrides.CASTS": _TORCH_CASTS,
    "functional_overrides.FP16_FUNCS": _FUNCTIONAL_FP16,
    "functional_overrides.FP32_FUNCS": _FUNCTIONAL_FP32,
    "tensor_overrides": _TENSOR_OVERRIDES,
}
