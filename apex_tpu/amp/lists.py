"""amp O1 cast lists for the jnp/nn/lax shim namespaces.

Parity: reference apex/amp/lists/{torch_overrides,functional_overrides,
tensor_overrides}.py (~258 entries across the three) — translated from
torch op names to their jax.numpy / jax.nn / jax.lax equivalents. Ops with
no JAX analog (in-place variants, RNN cells, torch-only losses) have no
entry; jnp ops not listed pass through untouched, which matches the
reference's default of leaving unlisted ops alone.

Three semantics (reference apex/amp/amp.py:74-183):
- HALF  ("fp16 on GPU" -> bf16 on TPU): MXU-bound ops — matmuls, convs.
- FLOAT (fp32): reductions, transcendentals, norms, losses — ops where
  bf16 accumulation loses too much precision.
- PROMOTE: multi-arg elementwise ops run in the widest input dtype
  (jnp's numpy-style promotion already does this; wrapping pins the
  documented semantics even if inputs carry weak types).
"""

# jax.numpy names (reference torch_overrides.py FP16 list: mm, matmul,
# bmm, addmm/baddbmm family collapse to matmul/einsum in jnp)
JNP_HALF = (
    "matmul", "dot", "vdot", "inner", "outer", "tensordot", "einsum",
    "kron",
)

# reference torch_overrides.py FP32 list: acos, asin, cosh, erfinv, exp,
# expm1, log, log10, log1p, log2, reciprocal, rsqrt, sinh, tan, pow,
# prod, sum, cumprod, cumsum, norm, dist, renorm, ...
JNP_FLOAT = (
    "exp", "expm1", "log", "log1p", "log2", "log10", "power", "float_power",
    "prod", "sum", "cumprod", "cumsum", "mean", "var", "std", "median",
    "reciprocal", "sinh", "cosh", "tan", "arcsin", "arccos", "arctan",
    "arcsinh", "arccosh", "arctanh", "nansum", "nanprod", "nanmean",
    "trace", "interp",
)

# reference torch_overrides.py CASTS/promote list: add, div, mul, sub,
# cat, stack, equal-family, min/max, addcdiv/addcmul, ...
JNP_PROMOTE = (
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "remainder", "mod", "concatenate", "stack", "hstack", "vstack",
    "dstack", "column_stack", "where", "minimum", "maximum", "fmin",
    "fmax", "hypot", "heaviside", "logaddexp", "logaddexp2", "equal",
    "not_equal", "less", "less_equal", "greater", "greater_equal",
    "allclose", "isclose",
)

# jax.nn names (reference functional_overrides.py: FP16 = conv*/linear/
# attention-ish, FP32 = softmax/log_softmax + the loss zoo)
NN_HALF = ("relu", "gelu", "silu", "swish", "glu", "leaky_relu", "elu",
           "celu", "selu", "hard_tanh", "relu6")
NN_FLOAT = ("softmax", "log_softmax", "logsumexp", "standardize",
            "softplus", "sigmoid", "log_sigmoid", "one_hot")

# jax.lax names (conv kernels — functional_overrides FP16 conv1d..3d,
# conv_transpose*; dot_general is the matmul primitive)
LAX_HALF = ("conv", "conv_with_general_padding", "conv_general_dilated",
            "conv_transpose", "dot", "dot_general", "batch_matmul")

# jnp.linalg names forced fp32 (reference FP32 "norm", "dist")
LINALG_FLOAT = ("norm", "cond", "det", "slogdet", "eigvals", "eigvalsh",
                "svd", "qr", "cholesky", "inv", "pinv", "solve", "lstsq",
                "matrix_power", "matrix_rank")
