"""``apex_tpu.amp.jnp`` — the O1 shim namespace over ``jax.numpy``.

Parity: reference apex/amp/amp.py:74-183. The reference implements O1 by
monkey-patching the global torch namespaces so *user* code gets automatic
casts; under jit that trick is both impossible (tracing) and rude (global
mutation). The TPU-native equivalent is an import-swap: user code does

    from apex_tpu.amp import jnp   # instead of: import jax.numpy as jnp

and every listed op gets the reference's O1 cast semantics whenever the
amp dtype policy is active (``amp.initialize(..., opt_level="O1")`` or an
``amp.autocast()`` block) — matmuls in bf16, reductions/transcendentals
in fp32, multi-arg elementwise ops promoted — while unlisted ops and
disabled-policy runs pass straight through to ``jax.numpy``. Companion
shims: ``apex_tpu.amp.nn`` (jax.nn) and ``apex_tpu.amp.lax`` (jax.lax
convs/dots).

Everything not explicitly wrapped is forwarded verbatim via module
``__getattr__``, so the shim tracks jax.numpy's full surface.

Ordering requirement: the policy is consulted at *trace* time, and jit
caches traces. Call ``amp.initialize`` (or enter ``amp.autocast``)
BEFORE the first call of any jitted function that uses the shim — a
function traced while the policy was disabled keeps its fp32 trace on
later cache hits (the reference's runtime patching has the mirror-image
hazard: ops bound before ``amp.init`` keep their unpatched references,
apex/amp/amp.py docs).
"""

import jax.numpy as _jnp

from apex_tpu.amp import lists as _lists
from apex_tpu.amp.policy import (
    float_function,
    half_function,
    promote_function,
)


class _WrappedLinalg:
    """jnp.linalg proxy: decompositions/norms fp32, rest forwarded."""

    def __getattr__(self, name):
        fn = getattr(_jnp.linalg, name)
        if name in _lists.LINALG_FLOAT:
            return float_function(fn)
        return fn


linalg = _WrappedLinalg()

_WRAPPED = {}
for _name in _lists.JNP_HALF:
    if hasattr(_jnp, _name):
        _WRAPPED[_name] = half_function(getattr(_jnp, _name))
for _name in _lists.JNP_FLOAT:
    if hasattr(_jnp, _name):
        _WRAPPED[_name] = float_function(getattr(_jnp, _name))
for _name in _lists.JNP_PROMOTE:
    if hasattr(_jnp, _name):
        _WRAPPED[_name] = promote_function(getattr(_jnp, _name))
globals().update(_WRAPPED)


def __getattr__(name):  # PEP 562: forward the rest of jax.numpy
    return getattr(_jnp, name)


def __dir__():
    return sorted(set(dir(_jnp)) | set(_WRAPPED) | {"linalg"})
