"""``amp.scale_loss`` context manager + ``disable_casts``.

Parity: reference apex/amp/handle.py:16-158 (scale on enter, unscale +
overflow-check + step-skip patching on exit) and 163-167 (disable_casts).

TPU design: JAX grads are values, not ``.grad`` attributes, so the eager
context manager scales the loss and arms the optimizer's scaler; the
actual unscale/skip happens inside ``AmpOptimizer.step`` (branch-free under
jit). For fully-jitted training loops prefer the functional API:
``scaled = opt.scale_loss(loss, state)`` then ``opt.step(grads, state, params)``.
"""

import contextlib

from apex_tpu.amp._amp_state import _amp_state


@contextlib.contextmanager
def scale_loss(loss, optimizers, loss_id=0, model=None,
               delay_unscale=False, delay_overflow_check=False):
    """Yield ``loss * current_loss_scale``.

    Unlike the reference, exiting the context does not mutate gradients —
    compute grads of the yielded scaled loss and pass them to
    ``optimizer.step``, which unscales and skips on overflow
    (reference handle.py:128-154 semantics).
    """
    if _amp_state.opt_properties is None or not _amp_state.opt_properties.enabled:
        yield loss
        return
    if loss_id < len(_amp_state.loss_scalers):
        scaler = _amp_state.loss_scalers[loss_id]
    else:
        raise RuntimeError("Invalid loss_id {}".format(loss_id))
    yield scaler.scale(loss)


@contextlib.contextmanager
def disable_casts():
    """Disable the O1 dtype policy inside the context
    (reference handle.py:163-167)."""
    from apex_tpu.amp import policy

    prev = getattr(policy._local, "policy", None)
    policy._local.policy = policy.DtypePolicy(enabled=False)
    try:
        yield
    finally:
        policy._local.policy = prev
