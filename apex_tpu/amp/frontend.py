"""amp frontend: opt-level property system + ``initialize``.

Parity: reference apex/amp/frontend.py — ``Properties`` (9-99), ``O0``-``O3``
presets (104-193), ``initialize`` (197-362), ``state_dict``/
``load_state_dict`` (365-404).

TPU mapping of the opt levels (fp16 -> bf16):
  O0: pure fp32 (no casts, loss_scale=1).
  O1: params fp32, compute ops in bf16 via the dtype policy
      (``amp.autocast``); dynamic loss scale kept for API parity.
  O2: params cast to bf16 except normalization layers; fp32 master weights
      in the optimizer; dynamic loss scale.
  O3: pure bf16, no masters, loss_scale=1.
"""


import jax
import jax.numpy as jnp

from apex_tpu.amp._amp_state import _amp_state, maybe_print
from apex_tpu.amp.amp_optimizer import AmpOptimizer
from apex_tpu.amp.scaler import LossScaler


class Properties(object):
    """Mutable option bundle (reference frontend.py:9-99)."""

    def __init__(self):
        self.options = {
            "enabled": False,
            "opt_level": None,
            "cast_model_type": None,
            "patch_torch_functions": False,  # name kept for parity; means "use dtype policy"
            "keep_batchnorm_fp32": None,
            "master_weights": None,
            "loss_scale": 1.0,
        }

    def _update_options_dict(self, new_options):
        for k, v in new_options.items():
            if k in self.options:
                self.options[k] = v
            else:
                raise ValueError("Tried to set unexpected option {}".format(k))

    def __getattr__(self, name):
        if "options" in self.__dict__ and name in self.__dict__["options"]:
            return self.options[name]
        raise AttributeError(name)

    def __setattr__(self, name, value):
        if "options" in self.__dict__ and name in self.options:
            self.options[name] = value
        else:
            super(Properties, self).__setattr__(name, value)


class O3:
    brief = "O3: Pure (b)f16 training."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O3"
        properties.cast_model_type = jnp.bfloat16
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = False
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


class O2:
    brief = "O2: (b)f16 model with fp32 master weights."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O2"
        properties.cast_model_type = jnp.bfloat16
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = True
        properties.master_weights = True
        properties.loss_scale = "dynamic"
        return properties


class O1:
    brief = "O1: Insert automatic casts around compute ops (dtype policy)."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O1"
        properties.cast_model_type = None
        properties.patch_torch_functions = True
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = None
        properties.loss_scale = "dynamic"
        return properties


class O0:
    brief = "O0: Pure fp32 training."

    def __call__(self, properties):
        properties.enabled = True
        properties.opt_level = "O0"
        properties.cast_model_type = jnp.float32
        properties.patch_torch_functions = False
        properties.keep_batchnorm_fp32 = None
        properties.master_weights = False
        properties.loss_scale = 1.0
        return properties


opt_levels = {"O3": O3(), "O2": O2(), "O1": O1(), "O0": O0()}


_BN_MARKERS = ("batchnorm", "batch_norm", "bn", "norm")


def _is_norm_path(path) -> bool:
    keys = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
    return any(any(m in str(k).lower() for m in _BN_MARKERS) for k in keys)


def cast_model(params, dtype, keep_batchnorm_fp32=False):
    """Cast a parameter pytree, optionally keeping norm-layer params fp32
    (reference fp16util.convert_network keeps BN fp32,
    apex/amp/_initialize.py:178-184)."""
    def cast(path, leaf):
        if not (hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating)):
            return leaf
        if keep_batchnorm_fp32 and _is_norm_path(path):
            return leaf.astype(jnp.float32)
        return leaf.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def initialize(models, optimizers=None, enabled=True, opt_level="O1",
               cast_model_type=None, patch_torch_functions=None,
               keep_batchnorm_fp32=None, master_weights=None, loss_scale=None,
               cast_model_outputs=None, num_losses=1, verbosity=1,
               min_loss_scale=None, max_loss_scale=2.0 ** 24):
    """Initialize amp (reference frontend.py:197-362).

    Args:
      models: a parameter pytree (or list of pytrees). In JAX, "the model"
        is its parameters; apply fns are pure and need no patching.
      optimizers: an apex_tpu fused optimizer (or list). Wrapped in
        :class:`AmpOptimizer` which owns unscale/master-weight handling.
    Returns:
      (models, optimizers) with params cast per the opt level and
      optimizers wrapped.
    """
    _amp_state.verbosity = verbosity
    if not enabled:
        # a previously-armed O1 global policy must not leak into a
        # disabled (fp32 control) run
        from apex_tpu.amp import policy as _policy

        _policy.set_global_policy(_policy.DtypePolicy(enabled=False),
                                  verbosity=verbosity)
        return models, optimizers

    if opt_level not in opt_levels:
        raise RuntimeError("Unexpected optimization level {}".format(opt_level))

    _amp_state.opt_properties = opt_levels[opt_level](Properties())
    maybe_print("Selected optimization level {}".format(opt_levels[opt_level].brief))
    for k, v in {
        "cast_model_type": cast_model_type,
        "patch_torch_functions": patch_torch_functions,
        "keep_batchnorm_fp32": keep_batchnorm_fp32,
        "master_weights": master_weights,
        "loss_scale": loss_scale,
    }.items():
        if v is not None:
            setattr(_amp_state.opt_properties, k, v)

    props = _amp_state.opt_properties

    # O1: activate the global dtype policy so the apex_tpu.amp.{jnp,nn,
    # lax} shim namespaces cast user ops from here on (the reference
    # patches the torch namespaces at this point, amp/_initialize.py:235-248).
    from apex_tpu.amp import policy as _policy

    _policy.set_global_policy(_policy.DtypePolicy(
        enabled=bool(props.patch_torch_functions),
        compute_dtype=jnp.bfloat16,
        cast_model_outputs=cast_model_outputs), verbosity=verbosity)

    models_was_list = isinstance(models, list)
    models_list = models if models_was_list else [models]
    if props.cast_model_type is not None and props.cast_model_type != jnp.float32:
        models_list = [
            cast_model(m, props.cast_model_type,
                       keep_batchnorm_fp32=bool(props.keep_batchnorm_fp32))
            for m in models_list
        ]

    out_optimizers = optimizers
    _amp_state.loss_scalers = []
    for _ in range(num_losses):
        _amp_state.loss_scalers.append(
            LossScaler(props.loss_scale, min_loss_scale=min_loss_scale,
                       max_loss_scale=max_loss_scale))

    if optimizers is not None:
        opt_was_list = isinstance(optimizers, list)
        opt_list = optimizers if opt_was_list else [optimizers]
        wrapped = [
            AmpOptimizer(opt, _amp_state.loss_scalers[min(i, num_losses - 1)],
                         master_weights=bool(props.master_weights),
                         model_dtype=props.cast_model_type)
            for i, opt in enumerate(opt_list)
        ]
        _amp_state.optimizers = wrapped
        out_optimizers = wrapped if opt_was_list else wrapped[0]

    out_models = models_list if models_was_list else models_list[0]
    return out_models, out_optimizers


def state_dict(destination=None):
    """Checkpoint all loss scalers (reference frontend.py:365-381)."""
    if destination is None:
        destination = {}
    for idx, ls in enumerate(_amp_state.loss_scalers):
        destination["loss_scaler%d" % idx] = ls.state_dict()
    return destination


def load_state_dict(state_dict):
    """Restore loss scalers (reference frontend.py:384-404)."""
    if len(state_dict) != len(_amp_state.loss_scalers):
        import warnings

        warnings.warn("Found {} loss scalers in state_dict, expected {}".format(
            len(state_dict), len(_amp_state.loss_scalers)))
    for idx, ls in enumerate(_amp_state.loss_scalers):
        key = "loss_scaler%d" % idx
        if key in state_dict:
            ls.load_state_dict(state_dict[key])
