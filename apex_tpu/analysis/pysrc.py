"""Python-source static analysis for the repo itself.

``ruff.toml`` at the repo root is the canonical configuration — run
``ruff check .`` in any environment that has ruff. The driver container
does NOT ship ruff (and nothing may be pip-installed into it), so this
module implements the enforced subset with the stdlib ``ast``: the
tier-1 test (tests/L0/test_static_analysis.py) runs ruff when it is on
PATH and always runs this checker, so the invariants hold in every
environment.

Checks (ruff rule codes for cross-reference):

- ``E999`` syntax error (the file doesn't parse)
- ``F401`` unused import (module and function scope; names re-exported
  via ``__all__`` count as used; ``__init__.py`` files are exempt per
  the ruff per-file-ignores)
- ``F841`` unused local variable (function scope only, mirroring
  pyflakes: simple ``name = ...`` / annotated assignments and
  ``except ... as name`` bindings never read again; tuple-unpacking,
  augmented-assignment and loop targets are exempt, as are
  underscore-prefixed names)
- ``E711`` comparison to ``None`` with ``==`` / ``!=``
- ``E722`` bare ``except:``
- ``B006`` mutable default argument (list/dict/set literals or
  constructor calls)

Suppression mirrors ruff: a trailing ``# noqa`` (optionally with
codes) on the offending line, plus the ``[lint.per-file-ignores]``
table in ``ruff.toml`` (parsed here so both tools agree).
"""

import ast
import fnmatch
import os
import re

DEFAULT_DIRS = ("apex_tpu", "tools", "tests")

_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?",
                      re.IGNORECASE)


class PyFinding:
    __slots__ = ("path", "line", "code", "message")

    def __init__(self, path, line, code, message):
        self.path = path
        self.line = line
        self.code = code
        self.message = message

    def __str__(self):
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _noqa_codes(source_lines, lineno):
    """Codes suppressed on a line: None = no noqa, () = bare noqa
    (suppresses everything)."""
    if not 1 <= lineno <= len(source_lines):
        return None
    m = _NOQA_RE.search(source_lines[lineno - 1])
    if not m:
        return None
    codes = m.group("codes")
    if not codes:
        return ()
    return tuple(c.strip().upper() for c in codes.split(",") if c.strip())


def _suppressed(source_lines, lineno, code):
    codes = _noqa_codes(source_lines, lineno)
    if codes is None:
        return False
    return codes == () or code in codes


def load_per_file_ignores(ruff_toml_path):
    """Parse the ``[lint.per-file-ignores]`` table of OUR ruff.toml
    (``"glob" = ["CODE", ...]`` lines). Python 3.10 has no tomllib, and
    the file is repo-controlled, so a line parser is sufficient — an
    unreadable file just yields no ignores."""
    ignores = {}
    try:
        with open(ruff_toml_path) as f:
            text = f.read()
    except OSError:
        return ignores
    in_section = False
    for line in text.splitlines():
        s = line.strip()
        if s.startswith("["):
            in_section = s in ("[lint.per-file-ignores]",
                               "[per-file-ignores]")
            continue
        if not in_section or "=" not in s or s.startswith("#"):
            continue
        glob_part, _, codes_part = s.partition("=")
        glob = glob_part.strip().strip('"').strip("'")
        codes = re.findall(r'["\']([A-Z0-9]+)["\']', codes_part)
        if glob and codes:
            ignores[glob] = tuple(codes)
    return ignores


def _file_ignored_codes(rel_path, per_file_ignores):
    codes = set()
    norm = rel_path.replace(os.sep, "/")
    for glob, glob_codes in per_file_ignores.items():
        if fnmatch.fnmatch(norm, glob) \
                or fnmatch.fnmatch(os.path.basename(norm), glob):
            codes.update(glob_codes)
    return codes


class _ImportScope:
    """One scope's imported names and the usage accounting for F401."""

    def __init__(self):
        self.imports = {}  # name -> (lineno, display)
        self.used = set()


def _collect_f401(tree, source_lines, path, findings, ignored):
    """Unused-import detection. Conservative where Python is dynamic:
    any Name/Attribute-root usage anywhere in the same scope (or any
    nested scope) counts, ``__all__`` strings count, and star imports
    are never flagged."""
    if "F401" in ignored:
        return

    all_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" \
                        and isinstance(node.value, (ast.List, ast.Tuple)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant) \
                                and isinstance(elt.value, str):
                            all_names.add(elt.value)

    def scope_check(body_nodes, top_level):
        scope = _ImportScope()
        nested = []

        def visit(node, in_same_scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)) \
                    and not in_same_scope:
                return
            if isinstance(node, ast.Import) and in_same_scope:
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    scope.imports[name] = (node.lineno,
                                           alias.asname or alias.name)
            elif isinstance(node, ast.ImportFrom) and in_same_scope:
                if node.module == "__future__":
                    pass
                else:
                    for alias in node.names:
                        if alias.name == "*":
                            continue
                        name = alias.asname or alias.name
                        scope.imports[name] = (node.lineno, name)
            if isinstance(node, ast.Name):
                scope.used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # the root Name is visited separately
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                nested.append(node)
                # names used in nested scopes still count as usage of
                # the enclosing import; walk them for Names only
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        scope.used.add(sub.id)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, in_same_scope)

        for n in body_nodes:
            visit(n, True)
        for name, (lineno, display) in scope.imports.items():
            if name in scope.used or name in all_names:
                continue
            if name.startswith("_"):
                continue  # conventional "imported for side effects"
            if _suppressed(source_lines, lineno, "F401"):
                continue
            findings.append(PyFinding(
                path, lineno, "F401",
                f"'{display}' imported but unused"))
        for node in nested:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope_check(node.body, False)
            elif isinstance(node, ast.ClassDef):
                scope_check(node.body, False)

    scope_check(tree.body, True)


def _collect_f841(tree, source_lines, path, findings, ignored):
    """Unused-local detection, function scope only (a module-level name
    is API surface, not a local). Conservative exactly where ruff's
    pyflakes engine is: only simple ``name = value`` / annotated
    assignments and ``except ... as name`` count as flaggable bindings
    — tuple unpacking, ``for`` targets, ``with ... as``, walrus and
    augmented assignments never fire — and ANY load of the name
    anywhere in the function (nested scopes included) counts as a
    use."""
    if "F841" in ignored:
        return

    def check_function(fn_node):
        declared_elsewhere = set()  # global / nonlocal names
        bindings = {}               # name -> first binding lineno
        loads = set()

        def collect_bindings(node, top):
            """Own-scope bindings only — nested function/class bodies
            are their own scopes and get their own check."""
            if node is not fn_node and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda, ast.ClassDef)):
                return
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared_elsewhere.update(node.names)
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                bindings.setdefault(node.targets[0].id, node.lineno)
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                bindings.setdefault(node.target.id, node.lineno)
            elif isinstance(node, ast.ExceptHandler) and node.name:
                bindings.setdefault(node.name, node.lineno)
            for child in ast.iter_child_nodes(node):
                collect_bindings(child, False)

        collect_bindings(fn_node, True)
        # loads from ANYWHERE inside the function (closures over our
        # locals included) count as uses — conservative like F401.
        # ``del name`` also counts (pyflakes parity: an explicit
        # delete is a deliberate end-of-life, not an unused binding).
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, (ast.Load, ast.Del)):
                loads.add(node.id)
        for name, lineno in sorted(bindings.items(),
                                   key=lambda kv: kv[1]):
            if name in loads or name in declared_elsewhere \
                    or name.startswith("_"):
                continue
            if _suppressed(source_lines, lineno, "F841"):
                continue
            findings.append(PyFinding(
                path, lineno, "F841",
                f"local variable '{name}' is assigned to but never "
                f"used"))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            check_function(node)


_MUTABLE_CALLS = {"list", "dict", "set", "defaultdict", "OrderedDict",
                  "Counter", "deque"}


def _check_defaults(node, source_lines, path, findings, ignored):
    if "B006" in ignored:
        return
    args = node.args
    for default in list(args.defaults) + [d for d in args.kw_defaults
                                          if d is not None]:
        bad = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
            isinstance(default, ast.Call)
            and isinstance(default.func, ast.Name)
            and default.func.id in _MUTABLE_CALLS)
        if bad and not _suppressed(source_lines, default.lineno, "B006"):
            findings.append(PyFinding(
                path, default.lineno, "B006",
                f"mutable default argument in '{node.name}' — shared "
                f"across calls; use None and create inside"))


def check_source(source, path="<string>", per_file_ignores=None):
    """Run every check over one source string. Returns [PyFinding]."""
    findings = []
    ignored = _file_ignored_codes(path, per_file_ignores or {})
    source_lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        findings.append(PyFinding(path, e.lineno or 0, "E999",
                                  f"syntax error: {e.msg}"))
        return findings
    _collect_f401(tree, source_lines, path, findings, ignored)
    _collect_f841(tree, source_lines, path, findings, ignored)
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if "E722" not in ignored and not _suppressed(
                    source_lines, node.lineno, "E722"):
                findings.append(PyFinding(
                    path, node.lineno, "E722",
                    "bare 'except:' — catches SystemExit/"
                    "KeyboardInterrupt; name the exception"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_defaults(node, source_lines, path, findings, ignored)
        elif isinstance(node, ast.Compare) and "E711" not in ignored:
            for op, comparator in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Eq, ast.NotEq)) \
                        and isinstance(comparator, ast.Constant) \
                        and comparator.value is None \
                        and not _suppressed(source_lines, node.lineno,
                                            "E711"):
                    findings.append(PyFinding(
                        path, node.lineno, "E711",
                        "comparison to None with ==/!= — use "
                        "'is None' / 'is not None'"))
    return findings


def check_paths(root, dirs=DEFAULT_DIRS, extra_files=("bench.py",
                                                      "setup.py")):
    """Check every .py file under ``dirs`` (plus ``extra_files``)
    relative to ``root``. Returns [PyFinding], repo-relative paths."""
    per_file = load_per_file_ignores(os.path.join(root, "ruff.toml"))
    findings = []
    paths = []
    for d in dirs:
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, d)):
            dirnames[:] = [n for n in dirnames
                           if n not in ("__pycache__", ".git")]
            paths.extend(os.path.join(dirpath, f)
                         for f in filenames if f.endswith(".py"))
    for f in extra_files:
        p = os.path.join(root, f)
        if os.path.exists(p):
            paths.append(p)
    for p in sorted(paths):
        rel = os.path.relpath(p, root)
        try:
            with open(p, encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(PyFinding(rel, 0, "E999",
                                      f"unreadable: {e}"))
            continue
        findings.extend(check_source(source, rel, per_file))
    return findings


def main(argv=None):
    import sys

    root = (argv or sys.argv[1:] or [os.getcwd()])[0]
    findings = check_paths(root)
    for f in findings:
        print(f)
    print(f"pysrc: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
