"""apex_tpu.analysis — static lint over lowered HLO / jaxprs enforcing
the repo's hot-path invariants (docs/analysis.md).

The fused-kernel / fused-optimizer value proposition only holds while
the compiled step stays free of hidden host syncs, dtype leaks, and
redundant buffers. Those invariants used to live in fragile ad-hoc
string greps spread across the test suite; this package makes them a
rule-based, structured, waivable static-analysis pass over a
``jax.jit(...).lower(...)`` artifact — trace-only, never compiling or
executing anything.

    from apex_tpu.analysis import assert_clean_hlo
    assert_clean_hlo(train_step, params, opt_state, x, y)

Integration points:

- ``CompileWatcher`` lints every newly compiled executable when
  ``APEX_TPU_HLO_LINT=1`` and emits ``lint`` JSONL events.
- ``assert_clean_hlo(fn, *args, rules=...)`` is the CI primitive next
  to ``assert_no_recompiles``.
- ``tools/hlo_lint.py`` lints every default bench config's lowered
  step and prints a rule x config table.
- ``apex_tpu.analysis.pysrc`` is the repo's Python-source checker (the
  ruff-config fallback when ruff itself isn't installed).
"""

from apex_tpu.analysis.lint import (  # noqa: F401
    HloLintError,
    LintContext,
    LintReport,
    assert_clean_hlo,
    build_context,
    lint_fn,
    lint_lowered,
    run_rules,
)
from apex_tpu.analysis.rules import (  # noqa: F401
    HOST_CALLBACK_TARGETS,
    RULES,
    Finding,
    LintConfig,
)
from apex_tpu.analysis.sharding import (  # noqa: F401
    CollectiveGraph,
    CollectiveOp,
    audit_spmd,
    collective_graph,
    static_comm_bytes,
)


def report_to_registry(report, *, registry=None, name=None):
    """Emit a LintReport into the telemetry registry: one ``lint``
    event per finding plus a summary event, and the
    ``lint/violations`` counter. No-op (beyond the return) when the
    registry is disabled — same contract as every other telemetry
    producer."""
    from apex_tpu.telemetry.registry import get_registry

    reg = registry or get_registry()
    if not reg.enabled:
        return report
    tag = name or report.name
    if report.findings:
        reg.counter("lint/violations").inc(len(report.findings))
    for f in report.findings:
        reg.event("lint", tag, **f.to_dict())
    reg.event("lint", tag, summary=True,
              violations=len(report.findings),
              rules_run=list(report.rules_run),
              rules_skipped=list(report.rules_skipped),
              clean=report.ok)
    return report
